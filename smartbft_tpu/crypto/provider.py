"""Signer/Verifier crypto providers: host signing, batched TPU verification.

The reference treats Signer/Verifier as opaque app plugins
(/root/reference/pkg/api/dependencies.go:47-71) and verifies each commit
signature on its own goroutine (/root/reference/internal/bft/view.go:537-541).
Here the crypto seam is a first-class component:

* :class:`Keyring` — node-id -> public key registry + own private key
  (key types are scheme-opaque).
* :class:`CryptoProvider` — implements the crypto subset of the
  Verifier/Signer SPI for a pluggable signature scheme (P-256, Ed25519).
  Signing is host-side (one signature per decision; never hot).
  Verification goes through a pluggable engine:
    - :class:`HostVerifyEngine`  — pure-Python ints; the CPU baseline.
    - :class:`JaxVerifyEngine`   — pads votes into fixed-size lanes and runs
      ONE jitted verify-kernel launch per flush; an asyncio micro-batcher
      coalesces concurrent quorum checks (across sequences and view-change
      validations) into shared launches, which is where the cross-request
      x cross-replica batching of BASELINE.md configs[2] comes from.

Wire format of a consenter signature (Signature.msg): canonical encoding of
:class:`ConsenterSigMsg` binding the proposal digest and the auxiliary data
(the reference smuggles PreparesFrom aux the same way, view.go:472-481).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..codec import decode, encode, wiremsg
from ..messages import Proposal, Signature
from ..types import VerifyPlaneDown, proposal_digest
from ..utils.memo import LruMemo
from ..utils.tasks import create_logged_task
from . import bls12381, ed25519, p256


@wiremsg
class ConsenterSigMsg:
    """The exact bytes a consenter signs for a commit vote."""

    proposal_digest: str = ""
    aux: bytes = b""


class Keyring:
    """Public keys of all replicas + this replica's private key.

    Key types are scheme-opaque: P-256 uses (int, (qx, qy)); Ed25519 uses
    (bytes, bytes).  The keyring never interprets them — only the scheme
    module does.
    """

    def __init__(self, self_id: int, private_key,
                 public_keys: dict[int, object]):
        self.self_id = self_id
        self.private_key = private_key
        self.public_keys = dict(public_keys)

    @classmethod
    def generate(cls, node_ids: Sequence[int], seed: bytes = b"smartbft",
                 scheme=p256):
        """Deterministic keyring set for tests/benches: one per node id."""
        keys = {nid: scheme.keygen(seed + b"-%d" % nid) for nid in node_ids}
        return {
            nid: cls(nid, keys[nid][0], {n: k[1] for n, k in keys.items()})
            for nid in node_ids
        }


# ---------------------------------------------------------------------------
# verify engines
# ---------------------------------------------------------------------------

@dataclass
class VerifyStats:
    """Batch-occupancy + latency accounting (BASELINE.md metrics).

    ``metrics``: optionally a :class:`smartbft_tpu.metrics.TPUCryptoMetrics`
    bundle — every record() then also feeds the embedder's metrics
    provider (batch-fill histogram, per-sig latency, counters)."""

    launches: int = 0
    sigs_verified: int = 0
    slots_used: int = 0
    total_kernel_seconds: float = 0.0
    metrics: object = None

    def record(self, n_sigs: int, n_slots: int, seconds: float) -> None:
        self.launches += 1
        self.sigs_verified += n_sigs
        self.slots_used += n_slots
        self.total_kernel_seconds += seconds
        if self.metrics is not None:
            self.metrics.count_batches.add(1)
            self.metrics.count_sigs_verified.add(n_sigs)
            if n_slots:
                self.metrics.batch_fill_percent.observe(100.0 * n_sigs / n_slots)
            if n_sigs:
                self.metrics.verify_latency_per_sig_us.observe(
                    1e6 * seconds / n_sigs
                )

    @property
    def batch_fill_pct(self) -> float:
        return 100.0 * self.sigs_verified / self.slots_used if self.slots_used else 0.0

    @property
    def us_per_sig(self) -> float:
        if not self.sigs_verified:
            return 0.0
        return 1e6 * self.total_kernel_seconds / self.sigs_verified


@dataclass
class MeshVerifyStats(VerifyStats):
    """VerifyStats for a device-mesh engine: every record also accounts
    pad waste and per-device launch fill (a batch-axis-partitioned wave
    places its items contiguously, so padding lands on the TAIL devices —
    the per-device fill vector makes that visible instead of hiding it in
    the overall mean).  Exported through ``MeshVerifyEngine.mesh_snapshot``
    into the ``mesh`` block of every bench row."""

    devices: int = 1
    pad_slots: int = 0
    launches_spanning_all_devices: int = 0
    last_device_fill_pct: list = field(default_factory=list)

    def record(self, n_sigs: int, n_slots: int, seconds: float,
               per_device: Optional[list] = None) -> None:
        """``per_device``: the engine's actual per-device item counts for
        this launch (the strided-placement engine reports them exactly);
        None falls back to the contiguous-placement model (items fill
        devices front to back, padding on the tail)."""
        super().record(n_sigs, n_slots, seconds)
        pad = max(n_slots - n_sigs, 0)
        self.pad_slots += pad
        per_dev = max(1, n_slots // max(1, self.devices))
        if per_device is not None:
            fills = [round(100.0 * got / per_dev, 1) for got in per_device]
        else:
            fills = []
            for d in range(self.devices):
                got = min(max(n_sigs - d * per_dev, 0), per_dev)
                fills.append(round(100.0 * got / per_dev, 1))
        self.last_device_fill_pct = fills
        if fills and min(fills) > 0:
            self.launches_spanning_all_devices += 1
        m = self.metrics
        if m is not None and hasattr(m, "count_mesh_launches"):
            m.count_mesh_launches.add(1)
            m.count_mesh_pad_slots.add(pad)
            if fills:
                m.mesh_device_fill_percent.observe(min(fills))

    def mesh_block(self, capacity: int = 0) -> dict:
        """The JSON-able engine half of the bench ``mesh`` block."""
        return {
            "devices": self.devices,
            "launches": self.launches,
            "items": self.sigs_verified,
            "slots": self.slots_used,
            "fill_pct": round(self.batch_fill_pct, 1),
            "pad_slots": self.pad_slots,
            "pad_waste_pct": round(100.0 * self.pad_slots / self.slots_used, 1)
            if self.slots_used else 0.0,
            "capacity_items_per_launch": int(capacity),
            "device_fill_pct_last": list(self.last_device_fill_pct),
            "launches_spanning_all_devices": self.launches_spanning_all_devices,
        }


class LaunchTimeout(Exception):
    """A coalescer flush exceeded its launch deadline.  The wave was
    abandoned: the worker thread keeps running, but its late result is
    discarded on arrival (counted in VerifyFaultStats)."""


class VerifyResultMismatch(RuntimeError):
    """An engine returned a different number of results than it was given
    items.  Silently slicing such a batch would mis-assign verdicts across
    every coalesced submitter, so the wave fails loudly instead and the
    mismatch counts as a launch failure."""


@dataclass(frozen=True)
class VerifyFaultPolicy:
    """Fault-tolerance knobs for the verify plane.

    All durations are WALL-CLOCK seconds (the engine runs on worker
    threads, outside any logical test clock).  ``launch_timeout`` is the
    per-flush deadline (None disables deadlines); ``launch_retries`` is
    how many times a failed/timed-out wave is re-submitted with
    exponential backoff (+ jitter) before falling back to the host engine;
    ``breaker_threshold`` consecutive launch failures trip the
    host-fallback circuit breaker open (a permanent kernel error trips it
    immediately); while open, a background canary probe re-tries the
    device every ``probe_interval`` seconds (backing off to
    ``probe_backoff_max``) and flips the breaker closed on success.
    """

    launch_timeout: Optional[float] = 30.0
    launch_retries: int = 2
    backoff_base: float = 0.05
    backoff_max: float = 2.0
    backoff_jitter: float = 0.5
    breaker_threshold: int = 3
    probe_interval: float = 2.0
    probe_backoff_max: float = 30.0

    @classmethod
    def from_config(cls, config) -> "VerifyFaultPolicy":
        """Map the Configuration.verify_* knobs onto a policy."""
        return cls(
            launch_timeout=config.verify_launch_timeout,
            launch_retries=config.verify_launch_retries,
            breaker_threshold=config.verify_breaker_threshold,
            probe_interval=config.verify_probe_interval,
        )


@dataclass
class TagStats:
    """Per-tag (per-shard) attribution of coalesced verify traffic."""

    items: int = 0       # verify items this tag submitted
    waves: int = 0       # flushes containing >=1 of this tag's items
    solo_waves: int = 0  # flushes containing ONLY this tag's items


@dataclass
class ShardAttribution:
    """Wave-composition accounting for a shared coalescer.

    The sharded deployment's whole point is that one device launch carries
    verify items from MANY consensus groups (cross-shard fill); these
    counters make that measured instead of asserted.  Tags are opaque
    (shard ids in practice); untagged submissions are legal and only
    counted in ``waves``.  Updated at flush time — when the wave's
    composition is fixed — so failed launches still attribute."""

    waves: int = 0          # coalesced flushes total
    tagged_waves: int = 0   # flushes with >=1 tagged submission
    mixed_waves: int = 0    # flushes mixing >=2 distinct tags — the
    #                         cross-shard-coalescing witness
    max_tags_in_wave: int = 0
    per_tag: dict = field(default_factory=dict)

    def note_wave(self, futures) -> None:
        self.waves += 1
        counts: dict = {}
        for entry in futures:
            _fut, _start, n, tag = entry
            if tag is None:
                continue
            counts[tag] = counts.get(tag, 0) + n
        if not counts:
            return
        self.tagged_waves += 1
        if len(counts) >= 2:
            self.mixed_waves += 1
        self.max_tags_in_wave = max(self.max_tags_in_wave, len(counts))
        for tag, n in counts.items():
            st = self.per_tag.get(tag)
            if st is None:
                st = self.per_tag[tag] = TagStats()
            st.items += n
            st.waves += 1
            if len(counts) == 1:
                st.solo_waves += 1

    def snapshot(self) -> dict:
        """JSON-able block for bench rows and the tier-1 coalescing gate."""
        return {
            "waves": self.waves,
            "tagged_waves": self.tagged_waves,
            "mixed_waves": self.mixed_waves,
            "max_tags_in_wave": self.max_tags_in_wave,
            "per_tag": {
                str(tag): {"items": st.items, "waves": st.waves,
                           "solo_waves": st.solo_waves}
                for tag, st in sorted(self.per_tag.items(), key=lambda kv: str(kv[0]))
            },
        }


@dataclass
class FlushHoldStats:
    """Occupancy-aware flush-gating accounting (ISSUE 11 tentpole a).

    Every decision the gate takes is exported (``mesh_snapshot``'s
    ``hold`` block rides every bench row): how many waves were held, for
    how long, how many items the holds actually gained (``depth_gain``
    — the wave-deepening payoff), and the two bounded-latency outs —
    holds that ran out the hard ``verify_flush_hold`` deadline and
    flushes that skipped the hold because the breaker was open (host
    fallback must never wait on device-occupancy predictions)."""

    waves_held: int = 0
    held_ms: float = 0.0
    depth_gain_items: int = 0
    deadline_expired: int = 0
    breaker_bypass: int = 0

    def snapshot(self, hold_s: float) -> dict:
        return {
            "hold_s": float(hold_s),
            "waves_held": self.waves_held,
            "held_ms": round(self.held_ms, 2),
            "depth_gain_items": self.depth_gain_items,
            "deadline_expired": self.deadline_expired,
            "breaker_bypass": self.breaker_bypass,
        }


class TagRateTracker:
    """Per-tag submit-cadence tracking: the occupancy signal behind
    flush gating (the PR 8 drain-rate-EWMA idiom, pointed at ARRIVALS).

    Each ``submit(tag=...)`` notes wall time; the inter-submit gap per
    tag folds into an EWMA.  :meth:`any_imminent` answers the gate's one
    question — does any recently-live tag plausibly deliver another wave
    within the remaining hold budget?  A tag is *live* while the time
    since its last submit is within ``slack`` expected gaps (cold tags
    borrow the coalescer window as their gap estimate), and *imminent*
    while its predicted next arrival fits in the remaining budget.
    Untagged submissions track under ``None`` — single-group
    deployments still deepen their waves."""

    __slots__ = ("_last", "_ewma", "slack", "default_gap")

    #: tags silent this long are evicted outright — far beyond any
    #: plausible hold budget (sub-second), so eviction can never hide a
    #: tag a live hold could still be waiting for.  Bounds both memory
    #: and the any_imminent scan under shard churn (the PR 7 autoscaler
    #: retires shard ids over a long-lived process's life).
    EVICT_AFTER = 60.0
    #: dict size that triggers an eviction sweep in note() — sweeps are
    #: O(tags) but amortized across at least this many submits
    EVICT_SWEEP_AT = 128

    def __init__(self, default_gap: float = 0.002, slack: float = 4.0):
        self._last: dict = {}
        self._ewma: dict = {}
        self.slack = slack
        self.default_gap = default_gap

    def note(self, tag, now: float) -> None:
        prev = self._last.get(tag)
        if prev is not None:
            gap = max(now - prev, 1e-6)
            # sub-window gaps are the SAME logical wave (a shard's n
            # replicas submit the same quorum check within microseconds)
            # — folding them in would teach the tracker a microsecond
            # "cadence" and make every tag look quiet the moment its
            # burst ends; only inter-wave gaps carry cadence signal
            if gap >= self.default_gap:
                old = self._ewma.get(tag)
                self._ewma[tag] = gap if old is None \
                    else 0.5 * old + 0.5 * gap
        elif len(self._last) >= self.EVICT_SWEEP_AT:
            # a NEW tag on a full tracker: sweep out long-dead tags so
            # retired shards can never grow the dict without bound
            dead = [t for t, ts in self._last.items()
                    if now - ts > self.EVICT_AFTER]
            for t in dead:
                del self._last[t]
                self._ewma.pop(t, None)
        self._last[tag] = now

    def any_imminent(self, now: float, remaining: float,
                     budget: Optional[float] = None) -> bool:
        if budget is None:
            budget = self.slack * self.default_gap
        for tag, last in self._last.items():
            gap = self._ewma.get(tag)
            if gap is None:
                # cold tag (one submit seen, no cadence yet): stay
                # optimistic within the hold budget — the hard deadline
                # bounds the cost, and a second wave teaches the cadence
                if now - last <= budget:
                    return True
                continue
            if now - last > self.slack * gap:
                continue  # tag went quiet — stop predicting it
            # overdue counts as "any moment now"; otherwise the predicted
            # arrival must fit inside what is left of the hold budget
            if last + gap <= now + remaining:
                return True
        return False


@dataclass
class VerifyFaultStats:
    """Plain counters for the fault machinery — introspectable without a
    metrics provider; benches export them in every JSON row."""

    launch_failures: int = 0
    launch_timeouts: int = 0
    retries: int = 0
    breaker_opens: int = 0
    breaker_closes: int = 0
    host_fallback_batches: int = 0
    probe_attempts: int = 0
    probe_successes: int = 0
    abandoned_late_arrivals: int = 0


class HostVerifyEngine:
    """Sequential pure-Python verification — the CPU baseline engine."""

    # sequential engine: coalescing gains nothing, don't add window latency
    preferred_coalesce_window = 0.0

    def __init__(self, scheme=p256, metrics=None) -> None:
        self.scheme = scheme
        self.stats = VerifyStats(metrics=metrics)
        self._lock = threading.Lock()

    def _verify_one(self, item) -> bool:
        """Per-item hook; subclasses swap in other sequential backends."""
        return self.scheme.verify_item(item)

    def verify(self, items) -> list[bool]:
        t0 = time.perf_counter()
        out = [self._verify_one(item) for item in items]
        dt = time.perf_counter() - t0
        with self._lock:
            self.stats.record(len(items), len(items), dt)
        return out


class JaxVerifyEngine:
    """Padded, jit-cached, batched signature verification on the JAX device.

    Lane sizes are fixed (powers of two) so at most ``len(pad_sizes)``
    kernels ever compile; every call pads up to the next size.  Thread-safe;
    the jit cache is shared.
    """

    preferred_coalesce_window = 0.002  # batched engine: wait for fan-in

    def __init__(self,
                 pad_sizes: Sequence[int] = (8, 32, 128, 512, 2048, 4096,
                                             8192, 16384),
                 scheme=p256, metrics=None):
        """``pad_sizes``: the top rung bounds how much of a large cluster's
        quorum wave one launch can absorb (n=128 -> 10880 signatures);
        per-launch overhead is fixed, so bigger is better.  A size only
        compiles a kernel when a batch of that shape first occurs — call
        :meth:`prewarm_shapes` at startup to pay those compiles before
        protocol traffic (a mid-protocol compile can outlast heartbeat
        timeouts; benchmarks/throughput.py prewarms every rung)."""
        import jax  # deferred: engine construction may precede platform pin

        self._jax = jax
        self._metrics = metrics
        self.scheme = scheme
        self.pad_sizes = tuple(sorted(pad_sizes))
        self._kernel = jax.jit(scheme.verify_kernel)
        # The fused limb-major Pallas kernel (pallas_ecdsa.ecdsa_verify) is
        # the DEFAULT P-256 path whenever the backend is a TPU — a production
        # embedder gets the fast path with no env plumbing.  SMARTBFT_PALLAS=0
        # (or any set value other than "1") opts out; SMARTBFT_PALLAS=1
        # forces it on other backends (CI uses interpret-mode tests instead).
        # The backend probe is LAZY — deciding at the first kernel call, when
        # backend init is inevitable anyway — so constructing an engine never
        # initializes jax (platform pins like force_cpu still work after).
        # static-key comb path (pallas_comb): the fastest P-256 route —
        # host-precomputed per-replica comb tables, 32 point-op levels per
        # verify.  Used for every chunk whose signer keys are registrable;
        # shares the lazy backend probe and failure-guard semantics below.
        self._comb = None
        self._comb_state = {"enabled": None, "transient": 0}
        if self.supports_pallas \
                and os.environ.get("SMARTBFT_PALLAS", "1") == "1":
            if scheme is p256:
                from . import pallas_ecdsa
                from .pallas_comb import CombVerifier

                self._comb = CombVerifier()
                xla_kernel = self._kernel
                state = {"enabled": None, "transient": 0}

                def guarded_kernel(*arrays):
                    out = self._guarded_call(
                        state, "pallas",
                        lambda: pallas_ecdsa.ecdsa_verify(*arrays),
                    )
                    return out if out is not None else xla_kernel(*arrays)

                self._kernel = guarded_kernel
            elif scheme is ed25519:
                # ed25519 has no generic pallas kernel — the comb path IS
                # the fused kernel; fallback is the XLA batch-major kernel
                from .pallas_ed25519 import Ed25519CombVerifier

                self._comb = Ed25519CombVerifier()
        self._lock = threading.Lock()
        self.stats = VerifyStats(metrics=metrics)

    def _guarded_call(self, state: dict, name: str, fn):
        """Tri-state failure guard shared by the Pallas kernel paths.

        Returns fn()'s result, or None to tell the caller to fall back.
        Compile-type failures (Mosaic lowering, an unimplemented primitive)
        disable the path permanently; transient runtime blips (momentary
        device OOM, a flaky tunnel) fall back per-call and retry, up to a
        consecutive-failure cap.  The backend probe is lazy: first call
        decides via _use_pallas.
        """
        if state["enabled"] is None:
            state["enabled"] = self._use_pallas(self.scheme)
        if not state["enabled"]:
            return None
        try:
            out = fn()
            state["transient"] = 0
            return out
        except Exception as exc:  # noqa: BLE001
            import logging

            log = logging.getLogger("smartbft_tpu.crypto")
            if self._is_permanent_kernel_error(exc):
                state["enabled"] = False
                log.warning(
                    "%s kernel failed to compile (%s: %s); engine "
                    "PERMANENTLY falls back for this process",
                    name, type(exc).__name__, exc,
                )
            else:
                state["transient"] += 1
                if state["transient"] >= 5:
                    state["enabled"] = False
                    log.warning(
                        "%s kernel failed %d consecutive times (%s: %s); "
                        "engine PERMANENTLY falls back",
                        name, state["transient"], type(exc).__name__, exc,
                    )
                else:
                    log.warning(
                        "%s kernel transient failure %d/5 (%s: %s); this "
                        "call falls back, next call retries",
                        name, state["transient"], type(exc).__name__, exc,
                    )
            return None

    #: subclasses whose inputs are mesh-placed (ShardedVerifyEngine) must
    #: opt out — pallas_call has no partitioning rules, so routing sharded
    #: lanes into it would silently collapse the mesh to one device
    supports_pallas = True

    def _use_pallas(self, scheme) -> bool:
        """Default the fused Pallas kernel on when the backend is a TPU.

        Called lazily from the first kernel invocation (never at engine
        construction — see __init__): any set SMARTBFT_PALLAS value other
        than "1" disables, "1" forces on, unset auto-detects the backend."""
        if scheme not in (p256, ed25519) or not self.supports_pallas:
            return False
        flag = os.environ.get("SMARTBFT_PALLAS")
        if flag is not None:
            return flag == "1"
        try:
            backend = self._jax.default_backend()
        except Exception:  # backend init failure — let the XLA path report it
            return False
        # the axon plugin exposes the tunneled TPU under its own platform name
        return backend in ("tpu", "axon")

    @staticmethod
    def _is_permanent_kernel_error(exc: Exception) -> bool:
        """Compile-type failures never succeed on retry; runtime blips may."""
        text = f"{type(exc).__name__}: {exc}"
        permanent = (
            "Mosaic", "lowering", "Lowering", "NotImplemented",
            "Unsupported", "unsupported", "INVALID_ARGUMENT", "UNIMPLEMENTED",
        )
        transient = (
            "RESOURCE_EXHAUSTED", "UNAVAILABLE", "DEADLINE_EXCEEDED",
            "ABORTED", "CANCELLED", "Connection", "Socket", "timed out",
        )
        if any(t in text for t in transient):
            return False
        return any(p in text for p in permanent)

    def _pad_to(self, n: int) -> int:
        for s in self.pad_sizes:
            if n <= s:
                return s
        return self.pad_sizes[-1]

    def verify(self, items) -> list[bool]:
        """items: scheme.make_item tuples -> validity per item."""
        if not items:
            return []
        out: list[bool] = []
        # oversized batches run in chunks of the largest lane size
        cap = self.pad_sizes[-1]
        for off in range(0, len(items), cap):
            out.extend(self._verify_chunk(items[off : off + cap]))
        return out

    def _place(self, a):
        """Hook for subclasses to place padded inputs (e.g. mesh-sharded)."""
        return a

    def prewarm_keys(self, pubs) -> None:
        """Register a known key set (e.g. the whole keyring) with the comb
        registry up front, so no verify path ever re-traces mid-protocol."""
        if self._comb is not None:
            self._comb.prewarm_keys(pubs)

    def prewarm_shapes(self, item, sizes: Optional[Sequence[int]] = None) -> None:
        """Compile every pad-ladder shape up front with copies of ``item``
        (one scheme verify item whose key is registered/registrable).

        Kernel shapes otherwise compile on first use — fine for benches,
        but in a live protocol the first large quorum wave would stall for
        the compile (possibly past heartbeat/view-change timeouts)."""
        for size in (self.pad_sizes if sizes is None else sizes):
            self.verify([item] * size)

    def _comb_verify(self, items, size):
        """Comb-kernel chunk verify under the shared guard semantics.

        Returns the (n,) mask, or None to fall back (unregistrable key,
        non-TPU backend, compile failure, or repeated transient errors)."""
        if self._comb is None:
            return None
        return self._guarded_call(
            self._comb_state, "comb", lambda: self._comb.verify(items, size)
        )

    def _verify_chunk(self, items) -> list[bool]:
        n = len(items)
        size = self._pad_to(n)
        t0 = time.perf_counter()
        mask = self._comb_verify(items, size)
        if mask is not None:
            mask = np.asarray(mask)
        else:
            arrays = self.scheme.verify_inputs(items)

            def pad(a):
                return self._place(
                    np.concatenate(
                        [a, np.zeros((size - n,) + a.shape[1:], a.dtype)]
                    )
                )

            mask = np.asarray(self._kernel(*(pad(a) for a in arrays)))
        dt = time.perf_counter() - t0
        with self._lock:
            self.stats.record(n, size, dt)
        return [bool(v) for v in mask[:n]]


def prewarm_verify_engine(engine, scheme=None,
                          sizes: Optional[Sequence[int]] = None) -> None:
    """Compile every pad-ladder shape of ``engine`` with a generated
    probe item — the device-rig prewarm helper (ISSUE 11 satellite).

    Pair with :func:`smartbft_tpu.utils.jaxenv.enable_compile_cache`:
    with the persistent compilation cache pointed at a durable directory
    (``SMARTBFT_JAX_CACHE_DIR``), the first process pays each mesh
    shape's XLA compile ONCE and every later process — each bench
    subprocess, each sweep point — loads it from disk, so the 2–3 min
    per-process compile tax (PERF.md "cold-compile budget") stops
    poisoning device bench rows.  No-op for engines without a pad ladder
    (host engines compile nothing)."""
    prewarm = getattr(engine, "prewarm_shapes", None)
    if prewarm is None:
        return
    scheme = scheme if scheme is not None else engine.scheme
    sk, pub = scheme.keygen(b"smartbft-prewarm-probe")
    item = scheme.make_item(b"p", scheme.sign_raw(sk, b"p"), pub)
    prewarm(item, sizes)


class AsyncBatchCoalescer:
    """Merges concurrent verify calls into shared kernel launches.

    The protocol core awaits ``submit(items)``; submissions that arrive
    within ``window`` seconds (or until ``max_batch`` fills) are flushed as
    one engine call on a worker thread.  This is the TPU analog of the
    reference's per-signature goroutine fan-out — except the fan-*in* is
    explicit, so one launch serves many sequences and replicas.
    """

    def __init__(self, engine, window: float = 0.002, max_batch: int = 2048,
                 dedupe: bool = False,
                 policy: Optional[VerifyFaultPolicy] = None,
                 fallback_engine=None, metrics=None,
                 hold: Optional[float] = None):
        """``dedupe``: verify each DISTINCT item once per flush and fan the
        verdict out to every submitter.  Verification is a pure function of
        (message, signature, key), so this is sound; it pays off when many
        colocated replicas share one engine — a quorum wave then contains
        each commit signature up to n times (every replica checks the same
        votes), and deduplication collapses an n*(quorum-1) wave to at most
        n distinct lanes.  The reference never shares a verifier across
        replicas, so it has no analogous seam (view.go:537-541 is
        per-replica fan-out).  Off by default: single-replica engines see
        no repeats, and the dict pass would be pure overhead.

        ``policy``: a :class:`VerifyFaultPolicy` arming launch deadlines,
        retry/backoff, and the host-fallback circuit breaker.  None keeps
        the legacy contract: one attempt, failures surface to submitters as
        plain RuntimeError.  With a policy, transient failures are retried,
        exhausted waves route to ``fallback_engine`` (consensus keeps
        committing at CPU speed), and only a wave that exhausts retries AND
        the fallback raises :class:`~smartbft_tpu.types.VerifyPlaneDown`.
        ``metrics``: an optional TPUCryptoMetrics bundle counting launch
        failures/timeouts/retries and breaker transitions.

        ``hold``: occupancy-aware flush gating (the
        ``Configuration.verify_flush_hold`` knob).  When > 0, a flush
        whose wave is below a pad-ladder rung briefly HOLDS — up to
        ``hold`` wall-clock seconds, the hard latency bound — while the
        per-tag submit-rate tracker predicts more waves inbound, so one
        deeper launch replaces several shallow ones (the fixed-launch-
        overhead economics of PAPERS.md [7]).  The hold never engages
        when the breaker is open (host fallback must not wait), never
        past ``max_batch``, and flushes the moment the wave lands
        exactly on a rung (zero pad waste beats more depth).  None/0
        keeps the legacy eager-window contract."""
        self.engine = engine
        self.window = window
        self.max_batch = max_batch
        self.dedupe = dedupe
        self.policy = policy
        #: a constructor-supplied policy is EXPLICIT and never overridden;
        #: defaulted/config-wired policies stay re-wirable (configure())
        self._policy_explicit = policy is not None
        self.fallback_engine = fallback_engine
        self.metrics = metrics
        if metrics is not None:
            metrics.breaker_state.set(0.0)  # healthy until proven otherwise
        self.fault_stats = VerifyFaultStats()
        self.shard_stats = ShardAttribution()
        #: occupancy-aware flush gating (ISSUE 11): hold budget seconds
        #: (0 = eager legacy flushing), per-tag arrival tracker, and the
        #: exported decision accounting.  A constructor-supplied hold is
        #: EXPLICIT like a constructor policy (configure_hold never
        #: overrides it); config-wired holds stay re-wirable.
        self.hold = float(hold) if hold else 0.0
        self._hold_explicit = hold is not None
        self.hold_stats = FlushHoldStats()
        self._tag_rates = TagRateTracker(default_gap=max(window, 0.001))
        #: mesh graduation accounting (CryptoProvider.configure_verify_mesh
        #: writes these; they live on the coalescer because the coalescer
        #: is the ONE shared object in sharded mode — like the breaker)
        self.mesh_configured = 0   # Configuration.verify_mesh_devices wired
        self.mesh_downgrades = 0   # loud unbuildable-mesh downgrades
        #: flight recorder (obs.TraceRecorder; nop singleton when tracing
        #: is off) — verify enqueue/hold/launch spans + breaker
        #: transitions, correlated by a per-coalescer launch id.  Shared
        #: like the breaker: ONE recorder serves every colocated shard.
        from ..obs.recorder import NOP_RECORDER

        self.recorder = NOP_RECORDER
        self._launch_seq = 0
        self._pending: list[tuple] = []
        self._futures: list[tuple[asyncio.Future, int, int, object]] = []
        self._flush_scheduled = False
        self._launch_inflight = False
        self._lock = asyncio.Lock()
        self._log = logging.getLogger("smartbft_tpu.crypto")
        self._breaker_is_open = False
        self._consecutive_failures = 0
        self._probe_task: Optional[asyncio.Task] = None
        #: a known-well-formed item from the last wave, re-verified by the
        #: breaker probe as the device-health canary
        self._canary: Optional[tuple] = None
        #: flip-warm mode (ISSUE 15): until this wall-clock instant the
        #: plane flushes EAGERLY — no coalescing window, no occupancy
        #: hold.  Armed by note_view_flip when a view change installs a
        #: new view: the mesh idled through the depose, and the flip's
        #: first deep-window waves must launch at once so the stalled
        #: backlog lands on a warm plane instead of re-paying the
        #: batching latency it was tuned for in steady state.
        self._warm_until = 0.0
        self.flip_warms = 0
        self.flip_warm_bypasses = 0

    # -- late wiring ---------------------------------------------------------

    def configure(self, policy: Optional[VerifyFaultPolicy] = None,
                  fallback_engine=None, metrics=None,
                  explicit: bool = False) -> None:
        """Late fault-plane wiring (the Consensus facade calls this at
        start AND on every reconfig with Configuration-derived values).

        A policy supplied at construction is explicit and is never
        overridden; a defaulted or previously config-wired policy IS
        replaced, so Configuration.verify_* knobs (and reconfigs carrying
        new ones) actually reach the plane.  Fallback engine and metrics
        fill only when unset — the coalescer may be shared across
        colocated replicas and churning instances would be pointless."""
        if policy is not None and (explicit or not self._policy_explicit):
            self.policy = policy
            self._policy_explicit = self._policy_explicit or explicit
        if fallback_engine is not None and self.fallback_engine is None:
            self.fallback_engine = fallback_engine
        if metrics is not None and self.metrics is None:
            self.metrics = metrics
            self.metrics.breaker_state.set(1.0 if self._breaker_is_open else 0.0)

    def attach_recorder(self, recorder) -> None:
        """Point the verify plane's trace events at ``recorder`` (the
        harness/embedder wires this when tracing is on; the default nop
        recorder keeps the hot path at one attribute read per site)."""
        from ..obs.recorder import NOP_RECORDER

        self.recorder = recorder if recorder is not None else NOP_RECORDER

    def configure_hold(self, hold: Optional[float],
                       explicit: bool = False) -> None:
        """Late flush-gating wiring (``Consensus._wire_verify_plane``
        applies ``Configuration.verify_flush_hold`` here at start and on
        every reconfig).  Same precedence contract as :meth:`configure`:
        a constructor-supplied hold is explicit and never overridden; a
        defaulted or previously config-wired one IS replaced."""
        if hold is None:
            return
        if explicit or not self._hold_explicit:
            self.hold = max(0.0, float(hold))
            self._hold_explicit = self._hold_explicit or explicit

    #: how long flip-warm mode lasts (wall seconds): long enough for the
    #: new view's first deep windows to stage and launch their quorum
    #: waves, short enough that steady-state coalescing resumes within
    #: the same failover transient
    FLIP_WARM_SPAN = 0.25

    def note_view_flip(self, span: Optional[float] = None) -> None:
        """A view change just installed a new view (ISSUE 15): flush any
        pending wave immediately and run windowless/holdless for
        ``span`` seconds.  Safe from any caller on the event loop; a
        caller without a running loop (unit code) just arms the mode."""
        self._warm_until = time.monotonic() + (
            span if span is not None else self.FLIP_WARM_SPAN
        )
        self.flip_warms += 1
        rec = self.recorder
        if rec.enabled:
            rec.record("verify.flip_warm", extra={"pending": len(self._pending)})
        if self._pending and not self._launch_inflight:
            # flush NOW even when a windowed flush is already parked in
            # its sleep: the immediate task swaps the batch out and the
            # stale sleeper later wakes to an empty (or fresher) batch —
            # exactly the race _flush_after is already written to absorb.
            # Probe for the loop BEFORE building the coroutine: a no-loop
            # caller just arms the mode (the next submit flushes eagerly),
            # and an abandoned coroutine would warn "never awaited".
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                return
            create_logged_task(
                self._flush_after(0.0), name="coalescer-flush-flip"
            )
            self._flush_scheduled = True

    def note_view_depose(self, span: Optional[float] = None) -> None:
        """The current view is being torn down for a view change (ISSUE
        15): same eager-flush transient as the flip — in-window waves
        already handed to the plane launch NOW instead of idling in the
        coalescing window/hold while the VC sub-protocol runs, so the
        plane stays busy through the depose and the flip lands warm."""
        self.note_view_flip(span)

    def _flip_warm(self) -> bool:
        return time.monotonic() < self._warm_until

    @property
    def breaker_open(self) -> bool:
        return self._breaker_is_open

    def fault_snapshot(self) -> dict:
        """One JSON-able dict for bench rows: breaker state + fault counts,
        so a degraded run is never silently reported as a device run."""
        s = self.fault_stats
        return {
            "policy_configured": self.policy is not None,
            "open": self._breaker_is_open,
            "degraded": self._breaker_is_open or s.host_fallback_batches > 0,
            "opens": s.breaker_opens,
            "closes": s.breaker_closes,
            "launch_failures": s.launch_failures,
            "launch_timeouts": s.launch_timeouts,
            "retries": s.retries,
            "host_fallback_batches": s.host_fallback_batches,
            "probe_attempts": s.probe_attempts,
            "probe_successes": s.probe_successes,
            "abandoned_late_arrivals": s.abandoned_late_arrivals,
            # ISSUE 15: view-flip warm transients (eager windowless
            # flushing) and the occupancy holds they bypassed
            "flip_warms": self.flip_warms,
            "flip_warm_bypasses": self.flip_warm_bypasses,
        }

    def shard_snapshot(self) -> dict:
        """Wave-composition attribution (see :class:`ShardAttribution`)."""
        return self.shard_stats.snapshot()

    def mesh_snapshot(self) -> dict:
        """The ``mesh`` block of every bench row: which verify plane ran
        (single device or an N-device mesh), per-launch fill per device,
        pad waste, and the loud-downgrade count — so a row measured on a
        downgraded single-device plane is never mistaken for a mesh run.
        ``shard_map_available`` records the capability truth (memoized
        probe, satellite of ISSUE 10) for the 2D quorum-step path."""
        eng = self.engine
        devices = int(getattr(eng, "devices", 0))
        out = {
            "enabled": devices > 0,
            "devices": devices if devices > 0 else 1,
            "configured_devices": self.mesh_configured,
            "downgrades": self.mesh_downgrades,
            "topology": getattr(eng, "topology", "1d"),
            # occupancy-aware flush gating decisions (ISSUE 11): every
            # hold the gate took, its cost, and its depth payoff
            "hold": self.hold_stats.snapshot(self.hold),
        }
        try:
            from ..parallel.engine import shard_map_available

            out["shard_map_available"] = shard_map_available()
        except Exception:  # noqa: BLE001 — capability probe only
            out["shard_map_available"] = None
        snap = getattr(eng, "mesh_snapshot", None)
        if snap is not None:
            try:
                out.update(snap())
            except Exception:  # noqa: BLE001 — a stats hiccup must not
                pass           # poison a bench row assembly
        return out

    async def submit(self, items, tag=None) -> list[bool]:
        """``tag``: opaque attribution label (the submitter's shard id in
        sharded mode) — flush composition is tracked per tag in
        :attr:`shard_stats`, so cross-shard launch mixing is measurable."""
        if not items:
            return []
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        rec = self.recorder
        if rec.enabled:
            rec.record("verify.enqueue",
                       extra={"items": len(items), "tag": str(tag)})
        self._tag_rates.note(tag, time.monotonic())
        async with self._lock:
            start = len(self._pending)
            self._pending.extend(items)
            self._futures.append((fut, start, len(items), tag))
            # _flush_scheduled covers exactly the CURRENT batch: it resets
            # when a flush swaps the batch out.  While a launch is already
            # in flight nothing is scheduled here — completion-triggered
            # flushing (below) drains whatever accumulated the moment the
            # engine frees, which is what lets k pipelined decisions'
            # quorum waves merge into one launch: queueing a second launch
            # behind a busy device would only split the batch without
            # finishing any earlier.
            if self._launch_inflight:
                pass
            elif len(self._pending) >= self.max_batch:
                create_logged_task(
                    self._flush_after(0.0), name="coalescer-flush-full"
                )
                self._flush_scheduled = True
            elif not self._flush_scheduled:
                self._flush_scheduled = True
                # flip-warm mode: the failover transient flushes eagerly
                # (no coalescing window) so the new view's first waves
                # launch at once
                delay = 0.0 if self._flip_warm() else self.window
                create_logged_task(
                    self._flush_after(delay), name="coalescer-flush"
                )
        return await fut

    def _rung_exact(self, n: int) -> bool:
        """A wave sitting exactly on a pad-ladder rung has zero pad
        waste — holding it can only trade guaranteed-perfect fill for
        speculative depth, so the gate flushes it immediately."""
        sizes = getattr(self.engine, "pad_sizes", None)
        return bool(sizes) and n in sizes

    async def _maybe_hold(self) -> None:
        """Occupancy-aware flush gating: briefly hold this flush while
        the per-tag arrival tracker predicts more waves inbound, bounded
        by the hard ``hold`` deadline.  See the constructor docstring
        for the never-hold conditions (breaker open, full batch,
        rung-exact wave)."""
        budget = self.hold
        if budget <= 0.0:
            return
        if self._flip_warm():
            # the failover transient must not trade latency for depth
            self.flip_warm_bypasses += 1
            return
        start = time.monotonic()
        start_depth: Optional[int] = None
        quantum = max(min(self.window, budget / 4.0), 0.001)
        expired = False
        while True:
            now = time.monotonic()
            held = now - start
            async with self._lock:
                if self._launch_inflight or not self._pending:
                    break  # another flush task took the batch
                n = len(self._pending)
                if self._breaker_is_open:
                    if start_depth is None:
                        self.hold_stats.breaker_bypass += 1
                    break  # host fallback must not wait on predictions
                if n >= self.max_batch or self._rung_exact(n):
                    break
                if held >= budget:
                    expired = True
                    break
                if not self._tag_rates.any_imminent(now, budget - held,
                                                    budget):
                    break
                if start_depth is None:
                    start_depth = n
            await asyncio.sleep(quantum)
        if start_depth is not None:
            held_s = time.monotonic() - start
            self.hold_stats.waves_held += 1
            self.hold_stats.held_ms += 1e3 * held_s
            async with self._lock:
                gain = max(len(self._pending) - start_depth, 0)
            self.hold_stats.depth_gain_items += gain
            if expired:
                self.hold_stats.deadline_expired += 1
            if self.metrics is not None \
                    and hasattr(self.metrics, "count_waves_held"):
                self.metrics.count_waves_held.add(1)
                self.metrics.count_hold_depth_gain.add(gain)
            rec = self.recorder
            if rec.enabled:
                rec.record("verify.hold", dur=held_s,
                           extra={"depth_gain": gain, "expired": expired})

    async def _flush_after(self, delay: float) -> None:
        if delay:
            await asyncio.sleep(delay)
        await self._maybe_hold()
        # swap under the lock, verify outside it — submissions arriving
        # during the kernel launch accumulate into the NEXT batch
        async with self._lock:
            if self._launch_inflight:
                # a completion-triggered flush will pick the batch up
                self._flush_scheduled = False
                return
            pending, futures = self._pending, self._futures
            self._pending, self._futures = [], []
            self._flush_scheduled = False
            if pending:
                self._launch_inflight = True
        if not pending:
            return
        # attribution happens when the wave's composition is fixed, so a
        # failed launch still counts its shard mix
        self.shard_stats.note_wave(futures)
        self._launch_seq += 1
        launch_id = self._launch_seq
        rec = self.recorder
        t_launch = time.monotonic() if rec.enabled else 0.0
        try:
            results = await self._launch_wave(pending)
        except Exception as exc:
            if rec.enabled:
                rec.record("verify.launch", launch=launch_id,
                           dur=time.monotonic() - t_launch,
                           extra={"items": len(pending), "failed": True})
            err = exc if isinstance(exc, VerifyPlaneDown) else RuntimeError(
                f"batch verify failed: {exc!r}"
            )
            for fut, _, _, _ in futures:
                if not fut.done():
                    fut.set_exception(err)
            await self._launch_done()
            return
        if rec.enabled:
            rec.record("verify.launch", launch=launch_id,
                       dur=time.monotonic() - t_launch,
                       extra={"items": len(pending)})
        for fut, start, count, _tag in futures:
            if not fut.done():
                fut.set_result(results[start : start + count])
        await self._launch_done()

    async def _launch_done(self) -> None:
        """Completion-triggered flush: drain accumulated submissions now."""
        async with self._lock:
            self._launch_inflight = False
            if self._pending and not self._flush_scheduled:
                self._flush_scheduled = True
                create_logged_task(
                    self._flush_after(0.0), name="coalescer-flush-drain"
                )

    # -- the fault machinery -------------------------------------------------

    async def _launch_wave(self, pending: list) -> list[bool]:
        """One coalesced wave through the fault machinery: deadline ->
        retry/backoff -> host fallback.  Raises VerifyPlaneDown only when
        every stage is exhausted; transient device errors never surface to
        the protocol plane."""
        pol = self.policy
        if pol is None:  # legacy contract: one attempt, no deadline
            return await asyncio.to_thread(self._verify_batch, pending)
        self._canary = pending[0]
        attempts = 1 + max(0, pol.launch_retries)
        delay = pol.backoff_base
        last_exc: Optional[Exception] = None
        for attempt in range(attempts):
            if self._breaker_is_open:
                break  # degraded mode: don't queue waves behind a dead device
            try:
                results = await self._call_engine_with_deadline(
                    self.engine, pending, pol.launch_timeout
                )
            except Exception as exc:  # noqa: BLE001 — classified below
                last_exc = exc
                self._note_launch_failure(exc)
                if self._breaker_is_open or attempt + 1 >= attempts:
                    continue
                self.fault_stats.retries += 1
                if self.metrics is not None:
                    self.metrics.count_launch_retries.add(1)
                await asyncio.sleep(
                    delay * (1.0 + pol.backoff_jitter * random.random())
                )
                delay = min(delay * 2.0, pol.backoff_max)
                continue
            self._consecutive_failures = 0
            return results
        if self.fallback_engine is not None:
            try:
                results = await asyncio.to_thread(
                    self._verify_batch, pending, self.fallback_engine
                )
            except Exception as exc:  # noqa: BLE001 — terminal either way
                raise VerifyPlaneDown(
                    f"batch verify failed: device path exhausted "
                    f"({last_exc!r}) and the host fallback failed too: "
                    f"{exc!r}"
                ) from exc
            self.fault_stats.host_fallback_batches += 1
            if self.metrics is not None:
                self.metrics.count_host_fallback_batches.add(1)
            return results
        if last_exc is None:
            # breaker already open on entry: no device attempt was made
            raise VerifyPlaneDown(
                "batch verify failed: circuit breaker open (failing fast) "
                "and no fallback engine is configured"
            )
        raise VerifyPlaneDown(
            f"batch verify failed after {attempts} launch attempt(s) and "
            f"no fallback engine is configured: {last_exc!r}"
        ) from last_exc

    def _spawn_engine_call(self, engine, pending: list) -> asyncio.Future:
        """Run one engine call on a dedicated DAEMON thread; the returned
        future resolves with the result/exception whenever the thread
        finishes — possibly long after every awaiter gave up."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def resolve(setter, payload) -> None:
            if not fut.done():
                setter(payload)

        def run() -> None:
            try:
                res = self._verify_batch(pending, engine)
            except BaseException as exc:  # noqa: BLE001 — ferried to the loop
                setter, payload = fut.set_exception, exc
            else:
                setter, payload = fut.set_result, res
            try:
                loop.call_soon_threadsafe(resolve, setter, payload)
            except RuntimeError:
                pass  # loop closed while the launch was in flight

        threading.Thread(
            target=run, name="smartbft-verify-launch", daemon=True
        ).start()
        return fut

    def _discard_late(self, fut: asyncio.Future) -> None:
        """Mark an abandoned launch: count + log its late arrival and
        retrieve any exception so asyncio never warns at GC time."""

        def discard(f: asyncio.Future) -> None:
            self.fault_stats.abandoned_late_arrivals += 1
            exc = f.exception()
            self._log.warning(
                "abandoned verify launch completed late (%s)",
                "successfully" if exc is None else f"with {exc!r}",
            )

        fut.add_done_callback(discard)

    async def _call_engine_with_deadline(self, engine, pending: list,
                                         timeout: Optional[float]):
        """Run one engine call on a worker thread under the launch
        deadline.  On expiry the launch is ABANDONED: the (daemon) thread
        keeps running, its late result is discarded on arrival, and the
        caller gets LaunchTimeout — a stuck tunnel can no longer wedge the
        flush pipeline."""
        if timeout is None:
            return await asyncio.to_thread(self._verify_batch, pending, engine)
        fut = self._spawn_engine_call(engine, pending)
        try:
            return await asyncio.wait_for(asyncio.shield(fut), timeout)
        except asyncio.TimeoutError:
            self._discard_late(fut)
            raise LaunchTimeout(
                f"verify launch exceeded its {timeout:.3f}s deadline; "
                "wave abandoned"
            ) from None

    def _note_launch_failure(self, exc: Exception) -> None:
        self._consecutive_failures += 1
        self.fault_stats.launch_failures += 1
        timed_out = isinstance(exc, LaunchTimeout)
        if timed_out:
            self.fault_stats.launch_timeouts += 1
        if self.metrics is not None:
            self.metrics.count_launch_failures.add(1)
            if timed_out:
                self.metrics.count_launch_timeouts.add(1)
        permanent = (not timed_out
                     and JaxVerifyEngine._is_permanent_kernel_error(exc))
        self._log.warning(
            "verify launch failure (consecutive %d): %s: %s",
            self._consecutive_failures, type(exc).__name__, exc,
        )
        if permanent or (
            self._consecutive_failures >= max(1, self.policy.breaker_threshold)
        ):
            self._open_breaker(
                "permanent kernel error" if permanent
                else f"{self._consecutive_failures} consecutive launch failures"
            )

    def _open_breaker(self, reason: str) -> None:
        if self._breaker_is_open:
            return
        self._breaker_is_open = True
        self.fault_stats.breaker_opens += 1
        if self.metrics is not None:
            self.metrics.count_breaker_open.add(1)
            self.metrics.breaker_state.set(1.0)
        if self.recorder.enabled:
            self.recorder.record("ctl.breaker_open",
                                 extra={"reason": reason})
        self._log.warning(
            "verify-plane circuit breaker OPEN (%s); %s",
            reason,
            "waves degrade to the host fallback engine"
            if self.fallback_engine is not None else
            "NO fallback engine configured — waves fail fast until the "
            "device recovers",
        )
        if self._probe_task is None or self._probe_task.done():
            self._probe_task = create_logged_task(
                self._probe_loop(), name="verify-breaker-probe"
            )

    def _close_breaker(self) -> None:
        self._breaker_is_open = False
        self._consecutive_failures = 0
        self.fault_stats.breaker_closes += 1
        if self.metrics is not None:
            self.metrics.count_breaker_close.add(1)
            self.metrics.breaker_state.set(0.0)
        if self.recorder.enabled:
            self.recorder.record("ctl.breaker_close")
        self._log.warning(
            "verify-plane circuit breaker CLOSED: device engine recovered"
        )

    async def _probe_loop(self) -> None:
        """Background canary: while the breaker is open, periodically
        re-verify ONE item on the device — off the hot path, live waves
        stay on the fallback — and flip the breaker closed on the first
        call that completes.

        A probe whose thread is still PARKED in a hung device is re-awaited
        on the next round instead of spawning a fresh thread, so a
        long-lived outage holds at most one outstanding probe thread (plus
        the abandoned wave that tripped the breaker), not one per probe."""
        pol = self.policy
        delay = pol.probe_interval
        fut: Optional[asyncio.Future] = None
        try:
            while self._breaker_is_open:
                await asyncio.sleep(delay)
                item = self._canary
                if item is None:
                    continue
                self.fault_stats.probe_attempts += 1
                if fut is not None and fut.done():
                    # the parked probe concluded during the sleep: consume
                    # it — a late success still proves the device healthy,
                    # and a late failure must be retrieved (else asyncio
                    # warns at GC) before a fresh probe spawns
                    exc = fut.exception()
                    fut = None
                    if exc is None:
                        self.fault_stats.probe_successes += 1
                        self._close_breaker()
                        return
                    self._log.info(
                        "verify-plane probe completed late with %r", exc
                    )
                if fut is None:
                    fut = self._spawn_engine_call(self.engine, [item])
                try:
                    await asyncio.wait_for(
                        asyncio.shield(fut), pol.launch_timeout
                    )
                except asyncio.TimeoutError:
                    self._log.info(
                        "verify-plane probe still pending after %.2fs; "
                        "re-checking in %.2fs", pol.launch_timeout, delay,
                    )
                    delay = min(delay * 2.0, pol.probe_backoff_max)
                    continue
                except Exception as exc:  # noqa: BLE001 — device still down
                    fut = None  # concluded (handled here), not parked
                    self._log.info(
                        "verify-plane probe failed (%r); next probe in %.2fs",
                        exc, delay,
                    )
                    delay = min(delay * 2.0, pol.probe_backoff_max)
                    continue
                self.fault_stats.probe_successes += 1
                self._close_breaker()
                return
        finally:
            if fut is not None and not fut.done():
                self._discard_late(fut)  # loop torn down mid-probe

    # -- the engine call -----------------------------------------------------

    def _verify_batch(self, pending: list, engine=None) -> list[bool]:
        """One engine call for the flushed batch, optionally deduplicated."""
        engine = self.engine if engine is None else engine
        if not self.dedupe:
            return self._engine_call(engine, pending)
        try:
            first: dict = {}
            for it in pending:
                first.setdefault(it, len(first))
        except TypeError:
            # unhashable scheme items — dedupe silently degrades to 1:1
            return self._engine_call(engine, pending)
        if len(first) == len(pending):
            return self._engine_call(engine, pending)
        distinct = self._engine_call(engine, list(first))
        return [distinct[first[it]] for it in pending]

    @staticmethod
    def _engine_call(engine, items: list) -> list[bool]:
        """engine.verify + the result-length guard: a short/long result
        would silently mis-slice every submitter's future."""
        results = engine.verify(items)
        if len(results) != len(items):
            raise VerifyResultMismatch(
                f"engine {type(engine).__name__} returned {len(results)} "
                f"results for {len(items)} items — refusing to mis-slice "
                "the coalesced wave"
            )
        return results


# ---------------------------------------------------------------------------
# SPI provider
# ---------------------------------------------------------------------------

class CryptoProvider:
    """Crypto subset of the Signer/Verifier SPI over a :class:`Keyring`.

    The application's Verifier implementation delegates
    sign/verify-signature duties here and keeps request/proposal semantics
    (payload checks, request extraction) to itself.  ``scheme`` selects the
    signature system (:mod:`p256` default; :mod:`ed25519` — BASELINE.md
    configs[3] — via :class:`Ed25519CryptoProvider`); the engine must be
    built for the same scheme.
    """

    scheme = p256

    def __init__(self, keyring: Keyring, engine=None,
                 coalesce_window: Optional[float] = None,
                 coalescer: Optional[AsyncBatchCoalescer] = None,
                 fault_policy: Optional[VerifyFaultPolicy] = None,
                 fallback_engine=None):
        """``coalescer``: share one AsyncBatchCoalescer across providers —
        the cross-REPLICA batching axis of BASELINE configs[2]: when many
        replicas run against one chip, their concurrent quorum checks merge
        into shared kernel launches instead of queueing per-replica ones.

        ``fault_policy`` / ``fallback_engine``: verify-plane fault
        tolerance (see AsyncBatchCoalescer).  Device-shaped engines (those
        with a pad ladder) default to the full stack — launch deadlines,
        retry/backoff, and a host-fallback breaker built from the same
        scheme — so a hung or failing device can never wedge consensus;
        host engines keep the legacy single-attempt contract unless a
        policy is supplied (or wired later by the Consensus facade)."""
        self.keyring = keyring
        #: opaque attribution tag (the shard id in sharded deployments) —
        #: every coalesced submission from this provider carries it, so a
        #: shared coalescer can report per-shard items and cross-shard
        #: launch mixing (ShardAttribution).  Settable post-construction;
        #: None = untagged (single-group deployments).
        self.verify_tag: Optional[object] = None
        # LRU-bounded with an eviction counter: the keys are adversary-
        # chosen wire bytes, so a Byzantine flood of unique sig messages
        # churns the tail one entry at a time instead of wiping the honest
        # working set (and can never grow memory past the bound)
        self._sig_msg_memo: LruMemo[bytes, "ConsenterSigMsg"] = LruMemo(8192)
        # per-signer invalid-verdict attribution (ISSUE 18): every failed
        # consenter-sig verdict names WHO signed it instead of vanishing
        # into the aggregate failure count.  invalid_by_signer is the
        # always-on local export; the labeled counter and misbehavior
        # table are wired late (configure_fault_policy /
        # configure_misbehavior) by the Consensus facade.
        self.invalid_by_signer: dict[int, dict[str, int]] = {}
        self._invalid_vote_counter = None
        self.misbehavior = None
        if coalescer is not None and engine is not None \
                and coalescer.engine is not engine:
            raise ValueError("shared coalescer wraps a different engine")
        self.engine = (
            engine if engine is not None
            else coalescer.engine if coalescer is not None
            else HostVerifyEngine(scheme=self.scheme)
        )
        eng_scheme = getattr(self.engine, "scheme", self.scheme)
        if eng_scheme is not self.scheme:
            raise ValueError("engine scheme does not match provider scheme")
        # membership keys are static per configuration: register them with
        # the engine's comb-table path up front (no-op for other engines)
        if hasattr(self.engine, "prewarm_keys"):
            try:
                self.engine.prewarm_keys(self.keyring.public_keys.values())
            except ValueError as exc:
                # Import only on the error path: a raised CombRegistryFull
                # implies pallas_comb is already loaded, and the happy path
                # must not pull pallas machinery into configurations where
                # the comb path is disabled.
                from .pallas_comb import CombRegistryFull

                if not isinstance(exc, CombRegistryFull):
                    raise ValueError(
                        f"invalid key in keyring: {exc}") from exc
                # A long-lived shared engine can accumulate more distinct
                # keys than the comb registry holds (e.g. across many
                # reconfigs).  That only disables the comb fast path for
                # this provider's overflow keys — the generic kernel still
                # verifies them — so degrade loudly instead of failing
                # construction.
                import logging

                logging.getLogger("smartbft_tpu.crypto").warning(
                    "comb key registry full; provider %s falls back to the "
                    "generic verify kernel for unregistered keys: %s",
                    self.keyring.self_id, exc,
                )
        if coalescer is not None:
            self._coalescer = coalescer
            coalescer.configure(
                policy=fault_policy, fallback_engine=fallback_engine,
                explicit=fault_policy is not None,
            )
            return
        if coalesce_window is None:
            coalesce_window = getattr(
                self.engine, "preferred_coalesce_window", 0.002
            )
        # let one coalesced flush fill the engine's largest launch — a
        # smaller max_batch would split big quorum waves into multiple
        # launches and multiply the fixed per-launch overhead
        max_batch = getattr(self.engine, "pad_sizes", (2048,))[-1]
        default_policy = None
        if getattr(self.engine, "pad_sizes", None) is not None:
            # device-shaped engine: arm the fault stack by default — the
            # device is otherwise a single point of failure the reference's
            # per-goroutine host verify never had.  The default policy is
            # wired as NON-explicit so Configuration.verify_* knobs (via
            # Consensus._wire_verify_plane) still take effect.
            if fault_policy is None:
                default_policy = VerifyFaultPolicy()
            if fallback_engine is None:
                fallback_engine = HostVerifyEngine(scheme=self.scheme)
        self._coalescer = AsyncBatchCoalescer(
            self.engine, window=coalesce_window, max_batch=max_batch,
            policy=fault_policy, fallback_engine=fallback_engine,
        )
        if default_policy is not None:
            self._coalescer.configure(policy=default_policy)

    @property
    def coalescer(self) -> AsyncBatchCoalescer:
        return self._coalescer

    def configure_fault_policy(self, policy: Optional[VerifyFaultPolicy] = None,
                               metrics=None, fallback_engine=None) -> None:
        """Late verify-plane wiring (Consensus.start calls this with
        Configuration-derived values + the metrics bundle).  Fills only
        unset pieces, so explicit construction and shared-coalescer setups
        win.  A device-shaped engine without a fallback gets a host engine
        of the same scheme, realizing the degrade-to-CPU breaker path."""
        if (fallback_engine is None and policy is not None
                and self._coalescer.fallback_engine is None
                and getattr(self._coalescer.engine, "pad_sizes", None)
                is not None):
            fallback_engine = HostVerifyEngine(scheme=self.scheme)
        if metrics is not None and self._invalid_vote_counter is None:
            self._invalid_vote_counter = getattr(
                metrics, "count_invalid_votes", None)
        self._coalescer.configure(
            policy=policy, fallback_engine=fallback_engine, metrics=metrics
        )

    def configure_misbehavior(self, table) -> None:
        """Late misbehavior wiring (Consensus._wire_verify_plane): every
        per-signer invalid verdict this provider attributes also feeds the
        node's :class:`~smartbft_tpu.core.misbehavior.MisbehaviorTable`,
        which the Controller reads to shed shunned senders at intake."""
        self.misbehavior = table

    def _note_invalid(self, signer, cause: str) -> None:
        """Attribute one failed verdict to ``signer`` — local dict, the
        labeled ``consensus.tpu.count_invalid_votes`` counter, and the
        misbehavior table when wired.  Never raises: attribution must not
        turn a clean rejection into a verify-plane error."""
        try:
            by_cause = self.invalid_by_signer.setdefault(int(signer), {})
            by_cause[cause] = by_cause.get(cause, 0) + 1
            if self._invalid_vote_counter is not None:
                self._invalid_vote_counter.with_labels(str(signer)).add(1)
            if self.misbehavior is not None:
                self.misbehavior.note(int(signer), cause)
        except Exception:
            logging.getLogger("smartbft_tpu.crypto").warning(
                "invalid-vote attribution failed for signer %r", signer,
                exc_info=True,
            )

    def configure_flush_hold(self, hold: Optional[float],
                             explicit: bool = False) -> None:
        """Late occupancy-gating wiring: apply the
        ``Configuration.verify_flush_hold`` knob to the (possibly
        shared) coalescer.  Same precedence as the fault policy — an
        explicitly constructed hold wins over config-wired values."""
        self._coalescer.configure_hold(hold, explicit=explicit)

    def note_view_flip(self) -> None:
        """Controller seam (ISSUE 15): a view change installed a new
        view — run the (possibly shared) coalescer flip-warm so the new
        view's first quorum waves launch without coalescing latency."""
        self._coalescer.note_view_flip()

    def note_view_depose(self) -> None:
        """View seam (ISSUE 15): the view is aborting for a view change —
        flush its in-flight waves eagerly (see the coalescer's
        note_view_depose)."""
        self._coalescer.note_view_depose()

    def _quorum_threshold(self) -> int:
        """ceil((n+f+1)/2) over this keyring's membership — the quorum
        the 2D engine's psum'd vote counts decide against (the same
        expression every View uses; verdicts do NOT depend on it)."""
        n = len(self.keyring.public_keys)
        f = (n - 1) // 3
        return (n + f + 2) // 2

    def configure_verify_mesh(self, devices: int, metrics=None,
                              topology: str = "1d") -> None:
        """Graduate the coalescer's engine onto an N-device mesh — the
        ``Configuration.verify_mesh_devices`` knob, wired by
        ``Consensus._wire_verify_plane`` at start and on every reconfig.

        Idempotent and shared-coalescer-safe: the first provider wired
        swaps the engine in; colocated providers (sharded mode — S groups,
        ONE coalescer) see a mesh of the requested width already installed
        (``devices`` attribute, delegated through fault-injection wrappers)
        and no-op.  The PR 3 fault contract then holds per MESH launch for
        free: the deadline/retry/breaker machinery wraps ``engine.verify``,
        so expiry abandons the whole mesh launch, retries re-dispatch it,
        the breaker degrades every shard to the host fallback together and
        the canary recovers them back onto the mesh.

        ``topology`` selects the mesh shape (the
        ``Configuration.verify_mesh_topology`` knob): ``"1d"`` (default)
        is the batch-axis :class:`~smartbft_tpu.parallel.MeshVerifyEngine`;
        ``"2d"`` graduates onto the seq×vote
        :class:`~smartbft_tpu.parallel.QuorumMeshVerifyEngine`, whose
        per-sequence quorum counts ``psum`` across the 'vote' mesh axis —
        quorum counting rides the collective instead of the host — while
        per-item verdicts stay bit-identical to the 1D engine.

        **Degraded mode**: when the mesh is unbuildable (fewer visible
        devices than configured, or — for the 2D topology — no usable
        shard_map API) the current single-device engine stays, LOUDLY,
        with a counted downgrade (``coalescer.mesh_downgrades`` +
        ``consensus.tpu.count_mesh_downgrades``) — a mis-provisioned host
        serves at reduced width instead of dying."""
        if devices <= 0:
            return
        co = self._coalescer
        co.mesh_configured = int(devices)
        # prefer the coalescer's own metrics bundle (the shared one every
        # provider feeds) over a caller-supplied per-node bundle; fill the
        # unset slot like configure_fault_policy so later wirings and the
        # downgrade counter read the same bundle
        if metrics is not None and co.metrics is None:
            co.configure(metrics=metrics)
        metrics = co.metrics if co.metrics is not None else metrics
        current = co.engine
        if int(getattr(current, "devices", 0)) == int(devices) \
                and getattr(current, "topology", "1d") == topology:
            self.engine = current
            return  # already this mesh (possibly FaultyEngine-wrapped)
        from ..parallel.engine import (
            MeshUnavailable,
            MeshVerifyEngine,
            QuorumMeshVerifyEngine,
        )

        try:
            if topology == "2d":
                engine = QuorumMeshVerifyEngine(
                    devices=int(devices), scheme=self.scheme,
                    quorum=self._quorum_threshold(), metrics=metrics,
                )
            else:
                # the current engine donates its pad ladder ONLY when it
                # actually carries a batch ladder: a 2D engine's
                # pad_sizes is the single seq_tile*vote_tile rung, and
                # inheriting it on a 2d->1d reconfig would silently cap
                # the rebuilt 1D mesh far below the derived
                # MESH_PER_DEVICE_LANES ladder
                donor = None if getattr(current, "topology", "1d") == "2d" \
                    else getattr(current, "pad_sizes", None)
                engine = MeshVerifyEngine(
                    devices=int(devices), scheme=self.scheme,
                    pad_sizes=donor, metrics=metrics,
                )
        except MeshUnavailable as exc:
            co.mesh_downgrades += 1
            if metrics is not None and hasattr(metrics, "count_mesh_downgrades"):
                metrics.count_mesh_downgrades.add(1)
            logging.getLogger("smartbft_tpu.crypto").warning(
                "verify mesh UNBUILDABLE (%s); DOWNGRADED to the "
                "single-device %s (downgrade %d counted)",
                exc, type(current).__name__, co.mesh_downgrades,
            )
            return
        inner = getattr(current, "inner", None)
        if inner is not None:
            # a fault-injection wrapper (testing.engine_faults.FaultyEngine)
            # around a single-device engine: graduate INSIDE it — swapping
            # the wrapper out would silently disconnect chaos fault
            # injection from the live plane
            current.inner = engine
            current.scheme = engine.scheme
            current.pad_sizes = engine.pad_sizes
            current.devices = engine.devices
            current.topology = engine.topology
            engine = current
        else:
            co.engine = engine
        # one coalesced flush should be able to fill the mesh's largest
        # launch — a smaller cap would split waves and waste the new width
        co.max_batch = max(co.max_batch, engine.pad_sizes[-1])
        if co.fallback_engine is None:
            co.fallback_engine = HostVerifyEngine(scheme=self.scheme)
        if metrics is not None and hasattr(metrics, "mesh_devices"):
            metrics.mesh_devices.set(float(engine.devices))
        self.engine = engine

    # -- Signer -------------------------------------------------------------

    def sign(self, data: bytes) -> bytes:
        return self.scheme.sign_raw(self.keyring.private_key, data)

    def sign_proposal(self, proposal: Proposal, auxiliary_input: bytes) -> Signature:
        msg = encode(ConsenterSigMsg(
            proposal_digest=proposal_digest(proposal), aux=auxiliary_input
        ))
        return Signature(signer=self.keyring.self_id, value=self.sign(msg), msg=msg)

    # -- Verifier (crypto methods) -------------------------------------------

    def _item(self, signature: Signature):
        pub = self.keyring.public_keys.get(signature.signer)
        if pub is None:
            raise ValueError(f"unknown signer {signature.signer}")
        return self.scheme.make_item(signature.msg, signature.value, pub)

    def _check_binding(self, signature: Signature, proposal: Proposal,
                       digest: Optional[str] = None) -> bytes:
        """Digest binding check; returns aux.  Raises on mismatch.

        ``digest``: the proposal's digest if the caller already computed it
        — hashing a batch-sized proposal costs ~50 us, and quorum
        validation checks one proposal against dozens of signatures.  The
        sig-msg decode is memoized: every replica sharing this provider's
        process re-checks the same wire bytes (~42k decodes per n=64 bench
        run before the memo)."""
        decoded = self._sig_msg_memo.get_or(
            signature.msg, lambda: decode(ConsenterSigMsg, signature.msg)
        )
        if digest is None:
            digest = proposal_digest(proposal)
        if decoded.proposal_digest != digest:
            raise ValueError(
                f"signature of {signature.signer} binds digest "
                f"{decoded.proposal_digest[:12]}.. not the proposal's"
            )
        return decoded.aux

    def verify_consenter_sig(self, signature: Signature, proposal: Proposal) -> bytes:
        try:
            aux = self._check_binding(signature, proposal)
        except Exception:
            self._note_invalid(signature.signer, "binding_mismatch")
            raise
        try:
            item = self._item(signature)
        except Exception:
            self._note_invalid(signature.signer, "unknown_signer")
            raise
        ok = self.engine.verify([item])[0]
        if not ok:
            self._note_invalid(signature.signer, "invalid_sig")
            raise ValueError(f"invalid consenter signature from {signature.signer}")
        return aux

    # batch verification = collect/bind (shared below) + a scheme-overridable
    # mask step (_verify_items); BLS swaps in its aggregate fast path there

    def _verify_items(self, items) -> list[bool]:
        return self.engine.verify(items)

    async def _verify_items_async(self, items) -> list[bool]:
        return await self._coalescer.submit(items, tag=self.verify_tag)

    def _collect(self, signatures: Sequence[Signature], proposal: Proposal):
        auxes: list[Optional[bytes]] = []
        items, idxs = [], []
        digest = proposal_digest(proposal)  # once per batch, not per sig
        for i, sig in enumerate(signatures):
            # the two pre-engine rejections attribute separately: a digest-
            # binding forgery is a different lie than an out-of-membership
            # signer claim, and both are cheaper than the engine verdict
            # they used to be indistinguishable from
            try:
                aux = self._check_binding(sig, proposal, digest)
            except Exception:
                auxes.append(None)
                self._note_invalid(sig.signer, "binding_mismatch")
                continue
            try:
                items.append(self._item(sig))
            except Exception:
                auxes.append(None)
                self._note_invalid(sig.signer, "unknown_signer")
                continue
            idxs.append(i)
            auxes.append(aux)
        return auxes, items, idxs

    def _apply_mask(self, auxes, idxs, mask, signatures=None):
        for pos, i in enumerate(idxs):
            if not mask[pos]:
                auxes[i] = None
                if signatures is not None:
                    self._note_invalid(signatures[i].signer, "invalid_sig")
        return auxes

    def verify_consenter_sigs_batch(
        self, signatures: Sequence[Signature], proposal: Proposal
    ) -> list[Optional[bytes]]:
        auxes, items, idxs = self._collect(signatures, proposal)
        return self._apply_mask(auxes, idxs, self._verify_items(items),
                                signatures)

    async def verify_consenter_sigs_batch_async(
        self, signatures: Sequence[Signature], proposal: Proposal
    ) -> list[Optional[bytes]]:
        """Async path the View prefers: coalesces with concurrent callers."""
        auxes, items, idxs = self._collect(signatures, proposal)
        return self._apply_mask(auxes, idxs,
                                await self._verify_items_async(items),
                                signatures)

    def verify_signature(self, signature: Signature) -> None:
        try:
            item = self._item(signature)
        except Exception as exc:
            cause = ("unknown_signer"
                     if signature.signer not in self.keyring.public_keys
                     else "invalid_sig")
            self._note_invalid(signature.signer, cause)
            raise ValueError(f"malformed signature from {signature.signer}: {exc}")
        try:
            ok = self.engine.verify([item])[0]
        except Exception as exc:
            raise ValueError(f"malformed signature from {signature.signer}: {exc}")
        if not ok:
            self._note_invalid(signature.signer, "invalid_sig")
            raise ValueError(f"invalid signature from {signature.signer}")

    def auxiliary_data(self, msg: bytes) -> bytes:
        try:
            return decode(ConsenterSigMsg, msg).aux
        except Exception:
            return b""


class P256CryptoProvider(CryptoProvider):
    """ECDSA P-256 provider (the default scheme)."""

    scheme = p256


class Ed25519CryptoProvider(CryptoProvider):
    """Ed25519 provider — the alt-curve variant of BASELINE.md configs[3]."""

    scheme = ed25519


class BlsCryptoProvider(CryptoProvider):
    """BLS12-381 aggregate provider — BASELINE.md configs[4]:
    one pairing equation per quorum.

    Same-message aggregation requires every consenter to sign identical
    bytes, so this provider signs the PROPOSAL DIGEST ONLY; the per-signer
    auxiliary data (PreparesFrom witness lists, view.go:472-481) still
    travels in ``Signature.msg`` but is NOT covered by the signature.
    Deployments that rely on authenticated aux for blacklist redemption
    should use the P-256/Ed25519 providers (or treat redemption as
    advisory) — the tradeoff is the price of quorum collapse.

    Verification strategy (the FastAggregateVerify shape of the IETF BLS
    draft): aggregate the whole batch into ONE kernel lane (sum of G1 sigs,
    sum of G2 pubkeys); only if that single pairing check fails fall back to
    per-signature lanes to attribute the bad vote.  Two consequences:

    * **Rogue keys.** Same-message aggregation is sound only when every
      registered public key has a verified proof of possession (otherwise
      pk_b = b*g2 - pk_a lets b fabricate a "quorum" containing a vote a
      never cast).  Pass ``pops`` (signer id -> ``bls12381.pop_prove``
      output) to enforce this at construction; deployments that omit it
      MUST verify possession during key registration instead.
    * **Set-level attestation.** When the aggregate check passes, it
      attests that the quorum *as a set* signed the digest; the individual
      ``Signature.value`` byte strings are not separately attested (a relay
      could offset two of them by equal-and-opposite G1 points without
      changing the sum).  All quorum-cert validation in this framework goes
      through this batch path, so replicas agree; code that needs a single
      signature attributable on its own must call
      :meth:`verify_consenter_sig`, which never aggregates.
    """

    scheme = bls12381

    def __init__(self, keyring: Keyring, engine=None,
                 coalesce_window: Optional[float] = None,
                 coalescer=None, pops: Optional[dict[int, bytes]] = None):
        super().__init__(keyring, engine, coalesce_window, coalescer)
        if pops is not None:
            for nid, pub in keyring.public_keys.items():
                pop = pops.get(nid)
                if pop is None or not bls12381.pop_verify(pub, pop):
                    raise ValueError(
                        f"missing/invalid proof of possession for node {nid}"
                    )

    def _signed_bytes(self, msg: bytes) -> bytes:
        """The digest-only bytes actually covered by the BLS signature."""
        decoded = decode(ConsenterSigMsg, msg)
        return encode(ConsenterSigMsg(proposal_digest=decoded.proposal_digest))

    def sign(self, data: bytes) -> bytes:
        try:
            data = self._signed_bytes(data)
        except Exception:
            pass  # non-consenter payloads (e.g. ViewData) sign as-is
        return self.scheme.sign_raw(self.keyring.private_key, data)

    def _item(self, signature: Signature):
        pub = self.keyring.public_keys.get(signature.signer)
        if pub is None:
            raise ValueError(f"unknown signer {signature.signer}")
        try:
            msg = self._signed_bytes(signature.msg)
        except Exception:
            msg = signature.msg
        return self.scheme.make_item(msg, signature.value, pub)

    def _aggregate_lane(self, items):
        """One lane for the whole batch, or None if no collapse is possible."""
        if len(items) <= 1:
            return None
        try:
            return self.scheme.aggregate_items(items)
        except ValueError:
            return None  # mixed messages / degenerate sums

    def _quorum_minus_one(self) -> int:
        n = len(self.keyring.public_keys)
        f = (n - 1) // 3
        return max(2, (n + f + 1 + 1) // 2 - 1)  # ceil((n+f+1)/2) - 1

    def _canonical_split(self, signatures, items, idxs):
        """Canonicalized aggregation: the CANONICAL quorum subset — the
        lowest quorum-1 signer ids present — aggregates into one lane;
        leftovers get per-item lanes.

        Cross-replica dedupe (PERF.md round-5 row [4]'s named lever):
        without canonicalization every replica aggregates ITS OWN collected
        subset, so the aggregated items of two replicas checking the same
        decision never match and the shared coalescer's dedupe pass cannot
        collapse them.  Sorting by signer id and capping at quorum-1 makes
        replicas that hold the same votes produce BYTE-IDENTICAL aggregate
        items (aggregation is a commutative point sum over the canonical
        codec's byte encodings), so an n-replica wave dedupes to one lane.

        Returns (lane, chosen_positions, rest_positions) or None when no
        aggregation applies (<=1 item / mixed messages)."""
        if len(items) <= 1:
            return None
        order = sorted(range(len(items)),
                       key=lambda p: signatures[idxs[p]].signer)
        chosen = order[: self._quorum_minus_one()]
        if len(chosen) <= 1:
            return None
        rest = order[len(chosen):]
        try:
            lane = self.scheme.aggregate_items([items[p] for p in chosen])
        except ValueError:
            return None  # mixed messages / degenerate sums
        return lane, chosen, rest

    @staticmethod
    def _merge_split_verdicts(split, results, chosen_results, n_items) -> list[bool]:
        """Fan the [lane, rest...] result vector (plus, on lane failure,
        the per-item re-attribution of the chosen subset) onto positions.
        Rest verdicts are REUSED either way — a failed canonical lane only
        costs re-verifying the chosen items, never the whole batch."""
        _, chosen, rest = split
        mask = [False] * n_items
        for j, p in enumerate(rest):
            mask[p] = results[1 + j]
        if results[0]:
            for p in chosen:
                mask[p] = True
        else:
            for j, p in enumerate(chosen):
                mask[p] = chosen_results[j]
        return mask

    def _verify_items(self, items) -> list[bool]:
        lane = self._aggregate_lane(items)
        if lane is not None and self.engine.verify([lane])[0]:
            return [True] * len(items)
        return self.engine.verify(items)

    async def _verify_items_async(self, items) -> list[bool]:
        """Aggregate path with coalescing: the single aggregated lane joins
        other in-flight quorums in one shared kernel launch."""
        lane = self._aggregate_lane(items)
        if lane is not None and (
            await self._coalescer.submit([lane], tag=self.verify_tag)
        )[0]:
            return [True] * len(items)
        return await self._coalescer.submit(items, tag=self.verify_tag)

    def verify_consenter_sigs_batch(
        self, signatures: Sequence[Signature], proposal: Proposal
    ) -> list:
        auxes, items, idxs = self._collect(signatures, proposal)
        split = self._canonical_split(signatures, items, idxs)
        if split is None:
            return self._apply_mask(auxes, idxs, self._verify_items(items),
                                    signatures)
        lane, chosen, rest = split
        results = self.engine.verify([lane] + [items[p] for p in rest])
        chosen_results = None
        if not results[0]:
            # canonical lane failed: attribute only the chosen subset
            chosen_results = self.engine.verify([items[p] for p in chosen])
        mask = self._merge_split_verdicts(split, results, chosen_results, len(items))
        return self._apply_mask(auxes, idxs, mask, signatures)

    async def verify_consenter_sigs_batch_async(
        self, signatures: Sequence[Signature], proposal: Proposal
    ) -> list:
        auxes, items, idxs = self._collect(signatures, proposal)
        split = self._canonical_split(signatures, items, idxs)
        if split is None:
            return self._apply_mask(auxes, idxs,
                                    await self._verify_items_async(items),
                                    signatures)
        lane, chosen, rest = split
        results = await self._coalescer.submit(
            [lane] + [items[p] for p in rest], tag=self.verify_tag
        )
        chosen_results = None
        if not results[0]:
            chosen_results = await self._coalescer.submit(
                [items[p] for p in chosen], tag=self.verify_tag
            )
        mask = self._merge_split_verdicts(split, results, chosen_results, len(items))
        return self._apply_mask(auxes, idxs, mask, signatures)
