"""Wire and persistence message schema.

Mirrors the reference protobuf schema field-for-field
(/root/reference/smartbftprotos/messages.proto:14-129,
/root/reference/smartbftprotos/logrecord.proto:13-24) but encoded with the
canonical deterministic codec in :mod:`smartbft_tpu.codec` instead of
protobuf.  The top-level consensus ``Message`` oneof becomes the 1-byte tag
union of the ten message classes; ``SavedMessage`` (the WAL payload oneof)
likewise.

All integers are unsigned 64-bit.  ``digest`` fields are ``str`` (hex), as in
the reference.  Registration order below fixes the wire tags — append only.
"""

from __future__ import annotations

from typing import Optional, Union

from .codec import (
    decode,
    decode_tagged,
    encode,
    encode_tagged,
    wiremsg,
)


@wiremsg
class Signature:
    signer: int = 0
    value: bytes = b""
    msg: bytes = b""


@wiremsg
class Proposal:
    header: bytes = b""
    payload: bytes = b""
    metadata: bytes = b""
    verification_sequence: int = 0


@wiremsg
class ViewMetadata:
    view_id: int = 0
    latest_sequence: int = 0
    decisions_in_view: int = 0
    black_list: list[int] = None  # type: ignore[assignment]
    prev_commit_signature_digest: bytes = b""

    def __post_init__(self):
        if self.black_list is None:
            object.__setattr__(self, "black_list", [])


@wiremsg
class PrePrepare:
    view: int = 0
    seq: int = 0
    proposal: Optional[Proposal] = None
    prev_commit_signatures: list[Signature] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.prev_commit_signatures is None:
            object.__setattr__(self, "prev_commit_signatures", [])


@wiremsg
class Prepare:
    view: int = 0
    seq: int = 0
    digest: str = ""
    assist: bool = False


@wiremsg
class Commit:
    view: int = 0
    seq: int = 0
    digest: str = ""
    signature: Optional[Signature] = None
    assist: bool = False


@wiremsg
class PreparesFrom:
    ids: list[int] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.ids is None:
            object.__setattr__(self, "ids", [])


@wiremsg
class ViewChange:
    next_view: int = 0
    reason: str = ""


@wiremsg
class ViewData:
    next_view: int = 0
    last_decision: Optional[Proposal] = None
    last_decision_signatures: list[Signature] = None  # type: ignore[assignment]
    in_flight_proposal: Optional[Proposal] = None
    in_flight_prepared: bool = False
    # Pipelined-window extension (pipeline_depth > 1, no reference
    # counterpart): the in-flight LADDER above the singular rung.
    # ``in_flight_proposal`` remains the rung at last_decision_seq+1, so all
    # single-slot validation applies unchanged; ``in_flight_more[i]`` is the
    # rung at last_decision_seq+2+i with ``in_flight_more_prepared[i]``.
    in_flight_more: list[Proposal] = None  # type: ignore[assignment]
    in_flight_more_prepared: list[bool] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.last_decision_signatures is None:
            object.__setattr__(self, "last_decision_signatures", [])
        if self.in_flight_more is None:
            object.__setattr__(self, "in_flight_more", [])
        if self.in_flight_more_prepared is None:
            object.__setattr__(self, "in_flight_more_prepared", [])


@wiremsg
class SignedViewData:
    raw_view_data: bytes = b""
    signer: int = 0
    signature: bytes = b""


@wiremsg
class NewView:
    signed_view_data: list[SignedViewData] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.signed_view_data is None:
            object.__setattr__(self, "signed_view_data", [])


@wiremsg
class HeartBeat:
    view: int = 0
    seq: int = 0


@wiremsg
class HeartBeatResponse:
    view: int = 0


@wiremsg
class StateTransferRequest:
    """Empty in the reference schema (messages.proto:122-124)."""


@wiremsg
class StateTransferResponse:
    view_num: int = 0
    sequence: int = 0


#: The consensus wire "oneof": any of the ten protocol messages.
Message = Union[
    PrePrepare,
    Prepare,
    Commit,
    ViewChange,
    SignedViewData,
    NewView,
    HeartBeat,
    HeartBeatResponse,
    StateTransferRequest,
    StateTransferResponse,
]

CONSENSUS_MSG_TYPES = (
    PrePrepare,
    Prepare,
    Commit,
    ViewChange,
    SignedViewData,
    NewView,
    HeartBeat,
    HeartBeatResponse,
    StateTransferRequest,
    StateTransferResponse,
)


@wiremsg
class ProposedRecord:
    pre_prepare: Optional[PrePrepare] = None
    prepare: Optional[Prepare] = None


#: WAL payload "oneof" (messages.proto:113-120): what gets persisted at each
#: phase transition.  ``CommitRecord`` wraps the commit message; ``NewViewRecord``
#: stores the adopted ViewMetadata.
@wiremsg
class CommitRecord:
    commit: Optional[Commit] = None


@wiremsg
class NewViewRecord:
    metadata: Optional[ViewMetadata] = None


@wiremsg
class ViewChangeRecord:
    view_change: Optional[ViewChange] = None


SavedMessage = Union[ProposedRecord, CommitRecord, NewViewRecord, ViewChangeRecord]

SAVED_MSG_TYPES = (ProposedRecord, CommitRecord, NewViewRecord, ViewChangeRecord)


def marshal(msg) -> bytes:
    """Tagged canonical encoding — the wire format for Comm and the WAL."""
    return encode_tagged(msg)


def unmarshal(data: bytes):
    return decode_tagged(data)


def marshal_untagged(msg) -> bytes:
    return encode(msg)


def unmarshal_as(cls, data: bytes):
    return decode(cls, data)


# ---------------------------------------------------------------------------
# Vectorized message plane: encode-once + interned decode.
#
# A broadcast used to pay one encode per recipient and one decode per
# delivery (n-1 each at fan-out n).  ``wire_of`` memoizes the canonical
# encoding ON the frozen message instance, so a broadcast (and every
# re-broadcast/assist resend of the same object) encodes at most once;
# ``unmarshal_interned`` memoizes decode BY WIRE BYTES in a bounded LRU, so
# the n-1 identical deliveries of one broadcast decode once and every
# recipient shares the same frozen message object.  The contract that makes
# the sharing sound: ingested messages are IMMUTABLE — receivers never
# mutate a decoded message (wiremsg dataclasses are frozen; protocol code
# copies nested lists before touching them), and fault injection that wants
# to corrupt a message must deep-copy it first (``deep_copy_message``).
# ---------------------------------------------------------------------------

from time import perf_counter as _perf_counter  # noqa: E402

from .metrics import PROTOCOL_PLANE as _PLANE  # noqa: E402
from .utils.memo import LruMemo  # noqa: E402

_WIRE_MEMO_ATTR = "_wire_memo"

#: default bound for the tagged-decode intern memo: comfortably above the
#: live window of any cluster this harness runs (3k slots x a few message
#: kinds x n senders collapse to one entry per distinct broadcast), small
#: enough that a Byzantine flood of unique messages cannot grow memory
INTERN_MEMO_BOUND = 4096


def _count_intern_eviction() -> None:
    _PLANE.intern_evictions += 1


_INTERN: LruMemo[bytes, object] = LruMemo(
    INTERN_MEMO_BOUND, on_evict=_count_intern_eviction
)


def wire_of(msg, plane=None) -> bytes:
    """Canonical tagged encoding, memoized on the (frozen) instance.

    The memo makes "exactly one encode per broadcast" a structural
    invariant: the fan-out loop, re-broadcasts after view restarts, and
    lagging-replica assist resends all reuse the first encoding.

    ``plane``: the :class:`~smartbft_tpu.metrics.ProtocolPlaneTimers` the
    codec cost is attributed to — per-shard planes in sharded mode; the
    process default otherwise."""
    plane = _PLANE if plane is None else plane
    w = getattr(msg, _WIRE_MEMO_ATTR, None)
    if w is None:
        t0 = _perf_counter()
        w = encode_tagged(msg)
        plane.codec_us += (_perf_counter() - t0) * 1e6
        plane.encodes += 1
        object.__setattr__(msg, _WIRE_MEMO_ATTR, w)
    else:
        plane.encode_memo_hits += 1
    return w


def unmarshal_interned(data: bytes, plane=None):
    """Tagged decode through the bounded intern memo.

    All recipients of one broadcast receive byte-identical wire payloads,
    so the first delivery decodes and every later one is a dict hit
    returning the SAME frozen message object — receivers must treat it as
    immutable.  The memo is LRU-bounded (eviction counted in
    ``metrics.PROTOCOL_PLANE.intern_evictions``), so unique-message floods
    cannot grow memory.  ``plane``: see :func:`wire_of` — the intern memo
    itself stays process-wide (it is keyed by wire bytes, which cannot
    collide across shards), only the accounting is attributed."""
    plane = _PLANE if plane is None else plane
    msg = _INTERN.get(data)
    if msg is not None:
        plane.decode_interned_hits += 1
        return msg
    t0 = _perf_counter()
    msg = decode_tagged(data)
    plane.codec_us += (_perf_counter() - t0) * 1e6
    plane.decodes += 1
    # the decoded object already knows its own encoding — assists and
    # forwards of an ingested message re-send without re-encoding
    object.__setattr__(msg, _WIRE_MEMO_ATTR, data)
    _INTERN.put(data, msg)
    return msg


def intern_memo_len() -> int:
    return len(_INTERN)


def clear_intern_memo() -> None:
    _INTERN.clear()


def deep_copy_message(msg):
    """A genuinely fresh copy of a wire message (codec round-trip).

    For fault injection that MUTATES messages: broadcasts share one frozen
    decoded object across all recipients, so in-place corruption of the
    shared instance would leak into every replica's ingest.  A codec
    round-trip yields an independent object tree with none of the cached
    derivations (`_wire_memo`, `_digest_memo`) that an in-place mutation
    would otherwise leave stale."""
    return decode_tagged(encode_tagged(msg))
