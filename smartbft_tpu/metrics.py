"""Metrics SPI + default providers + the consensus metric bundles.

Re-design of /root/reference/pkg/metrics/provider.go:11-169 (Fabric-style
Provider/Counter/Gauge/Histogram with label support), the no-op provider
(pkg/metrics/disabled/provider.go), and the five metric bundles of
/root/reference/pkg/api/metrics.go:106-548 — plus the TPU-plane additions
required by BASELINE.json: signature-batch occupancy ("batch-fill %") and
verify-latency histograms.

The in-memory provider doubles as the benchmark introspection surface.
"""

from __future__ import annotations

import abc
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence


@dataclass(frozen=True)
class MetricOpts:
    namespace: str = ""
    subsystem: str = ""
    name: str = ""
    help: str = ""
    label_names: tuple[str, ...] = ()
    buckets: tuple[float, ...] = ()

    @property
    def full_name(self) -> str:
        return ".".join(p for p in (self.namespace, self.subsystem, self.name) if p)


class Counter(abc.ABC):
    @abc.abstractmethod
    def add(self, delta: float) -> None: ...

    @abc.abstractmethod
    def with_labels(self, *label_values: str) -> "Counter": ...


class Gauge(abc.ABC):
    @abc.abstractmethod
    def set(self, value: float) -> None: ...

    @abc.abstractmethod
    def add(self, delta: float) -> None: ...

    @abc.abstractmethod
    def with_labels(self, *label_values: str) -> "Gauge": ...


class Histogram(abc.ABC):
    @abc.abstractmethod
    def observe(self, value: float) -> None: ...

    @abc.abstractmethod
    def with_labels(self, *label_values: str) -> "Histogram": ...


class Provider(abc.ABC):
    @abc.abstractmethod
    def new_counter(self, opts: MetricOpts) -> Counter: ...

    @abc.abstractmethod
    def new_gauge(self, opts: MetricOpts) -> Gauge: ...

    @abc.abstractmethod
    def new_histogram(self, opts: MetricOpts) -> Histogram: ...


# ---------------------------------------------------------------------------
# Disabled (no-op) provider — the default, as in the reference
# (pkg/consensus/consensus.go:113-115).
# ---------------------------------------------------------------------------


class _NopCounter(Counter):
    def add(self, delta: float) -> None:
        pass

    def with_labels(self, *label_values: str) -> Counter:
        return self


class _NopGauge(Gauge):
    def set(self, value: float) -> None:
        pass

    def add(self, delta: float) -> None:
        pass

    def with_labels(self, *label_values: str) -> Gauge:
        return self


class _NopHistogram(Histogram):
    def observe(self, value: float) -> None:
        pass

    def with_labels(self, *label_values: str) -> Histogram:
        return self


class DisabledProvider(Provider):
    def new_counter(self, opts: MetricOpts) -> Counter:
        return _NopCounter()

    def new_gauge(self, opts: MetricOpts) -> Gauge:
        return _NopGauge()

    def new_histogram(self, opts: MetricOpts) -> Histogram:
        return _NopHistogram()


# ---------------------------------------------------------------------------
# In-memory provider
# ---------------------------------------------------------------------------


class _MemCounter(Counter):
    def __init__(self, store: dict, key: str):
        self._store = store
        self._key = key
        store.setdefault(key, 0.0)

    def add(self, delta: float) -> None:
        self._store[self._key] = self._store.get(self._key, 0.0) + delta

    def with_labels(self, *label_values: str) -> Counter:
        return _MemCounter(self._store, self._key + "{" + ",".join(label_values) + "}")


class _MemGauge(Gauge):
    def __init__(self, store: dict, key: str):
        self._store = store
        self._key = key
        store.setdefault(key, 0.0)

    def set(self, value: float) -> None:
        self._store[self._key] = value

    def add(self, delta: float) -> None:
        self._store[self._key] = self._store.get(self._key, 0.0) + delta

    def with_labels(self, *label_values: str) -> Gauge:
        return _MemGauge(self._store, self._key + "{" + ",".join(label_values) + "}")


class _MemHistogram(Histogram):
    def __init__(self, store: dict, key: str):
        self._store = store
        self._key = key
        store.setdefault(key, [])

    def observe(self, value: float) -> None:
        self._store.setdefault(self._key, []).append(value)

    def with_labels(self, *label_values: str) -> Histogram:
        return _MemHistogram(self._store, self._key + "{" + ",".join(label_values) + "}")


class InMemoryProvider(Provider):
    """Thread-compatible in-memory metrics, introspectable by tests/bench."""

    def __init__(self) -> None:
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.histograms: dict[str, list[float]] = {}

    def new_counter(self, opts: MetricOpts) -> Counter:
        return _MemCounter(self.counters, opts.full_name)

    def new_gauge(self, opts: MetricOpts) -> Gauge:
        return _MemGauge(self.gauges, opts.full_name)

    def new_histogram(self, opts: MetricOpts) -> Histogram:
        return _MemHistogram(self.histograms, opts.full_name)

    def histogram_quantile(self, name: str, q: float) -> Optional[float]:
        vals = sorted(self.histograms.get(name, []))
        if not vals:
            return None
        idx = min(len(vals) - 1, int(q * len(vals)))
        return vals[idx]


# ---------------------------------------------------------------------------
# Metric bundles (pkg/api/metrics.go)
# ---------------------------------------------------------------------------


def _c(p: Provider, subsystem: str, name: str, help: str = "") -> Counter:
    return p.new_counter(MetricOpts(namespace="consensus", subsystem=subsystem, name=name, help=help))


def _g(p: Provider, subsystem: str, name: str, help: str = "") -> Gauge:
    return p.new_gauge(MetricOpts(namespace="consensus", subsystem=subsystem, name=name, help=help))


def _h(p: Provider, subsystem: str, name: str, help: str = "") -> Histogram:
    return p.new_histogram(MetricOpts(namespace="consensus", subsystem=subsystem, name=name, help=help))


class RequestPoolMetrics:
    """metrics.go:106-172 — seven request-pool metrics."""

    def __init__(self, p: Provider):
        self.count_of_requests = _g(p, "pool", "count_of_requests")
        self.count_of_failed_add_requests = _c(p, "pool", "count_of_failed_add_requests")
        self.count_of_leader_forward_requests = _c(p, "pool", "count_of_leader_forward_requests")
        self.count_leader_forward_timeout = _c(p, "pool", "count_leader_forward_timeout")
        self.count_of_complain_timeout = _c(p, "pool", "count_of_complain_timeout")
        self.count_of_deleted_requests = _c(p, "pool", "count_of_deleted_requests")
        self.latency_of_requests = _h(p, "pool", "latency_of_requests")


class BlacklistMetrics:
    """metrics.go:239-258."""

    def __init__(self, p: Provider):
        self.count_black_list = _g(p, "blacklist", "count_black_list")
        self.nodes_in_black_list = _g(p, "blacklist", "nodes_in_black_list")


class ConsensusMetrics:
    """metrics.go:299-343."""

    def __init__(self, p: Provider):
        self.count_consensus_reconfig = _c(p, "consensus", "count_consensus_reconfig")
        self.latency_sync = _h(p, "consensus", "latency_sync")


class ViewMetrics:
    """metrics.go:346-460 — per-view protocol progress metrics."""

    def __init__(self, p: Provider):
        self.view_number = _g(p, "view", "number")
        self.leader_id = _g(p, "view", "leader_id")
        self.proposal_sequence = _g(p, "view", "proposal_sequence")
        self.decisions_in_view = _g(p, "view", "decisions_in_view")
        self.phase = _g(p, "view", "phase")
        self.count_txs_in_batch = _g(p, "view", "count_txs_in_batch")
        self.count_batch_all = _c(p, "view", "count_batch_all")
        self.count_txs_all = _c(p, "view", "count_txs_all")
        self.size_of_batch = _c(p, "view", "size_of_batch")
        self.latency_batch_processing = _h(p, "view", "latency_batch_processing")
        self.latency_batch_save = _h(p, "view", "latency_batch_save")


class ViewChangeMetrics:
    """metrics.go:520-548."""

    def __init__(self, p: Provider):
        self.current_view = _g(p, "viewchange", "current_view")
        self.next_view = _g(p, "viewchange", "next_view")
        self.real_view = _g(p, "viewchange", "real_view")


class TPUCryptoMetrics:
    """TPU-plane additions (BASELINE.json): batch occupancy + verify latency."""

    def __init__(self, p: Provider):
        self.batch_fill_percent = _h(p, "tpu", "batch_fill_percent")
        self.verify_latency_per_sig_us = _h(p, "tpu", "verify_latency_per_sig_us")
        self.count_sigs_verified = _c(p, "tpu", "count_sigs_verified")
        self.count_batches = _c(p, "tpu", "count_batches")


class MetricsBundle:
    """All bundles wired from one provider — what Consensus hands to components."""

    def __init__(self, p: Optional[Provider] = None):
        p = p or DisabledProvider()
        self.provider = p
        self.pool = RequestPoolMetrics(p)
        self.blacklist = BlacklistMetrics(p)
        self.consensus = ConsensusMetrics(p)
        self.view = ViewMetrics(p)
        self.view_change = ViewChangeMetrics(p)
        self.tpu = TPUCryptoMetrics(p)
