"""Metrics SPI + default providers + the consensus metric bundles.

Re-design of /root/reference/pkg/metrics/provider.go:11-169 (Fabric-style
Provider/Counter/Gauge/Histogram with label support), the no-op provider
(pkg/metrics/disabled/provider.go), and the five metric bundles of
/root/reference/pkg/api/metrics.go:106-548 — plus the TPU-plane additions
required by BASELINE.json: signature-batch occupancy ("batch-fill %") and
verify-latency histograms.

The in-memory provider doubles as the benchmark introspection surface.
"""

from __future__ import annotations

import abc
import contextvars
import math
import threading
import weakref
from dataclasses import dataclass, field
from typing import Optional, Sequence


@dataclass(frozen=True)
class MetricOpts:
    namespace: str = ""
    subsystem: str = ""
    name: str = ""
    help: str = ""
    label_names: tuple[str, ...] = ()
    buckets: tuple[float, ...] = ()
    #: statsd naming format with %{#namespace}/%{#subsystem}/%{#name} and
    #: %{label} placeholders (pkg/metrics/namer.go); empty = dotted default
    statsd_format: str = ""

    @property
    def full_name(self) -> str:
        return ".".join(p for p in (self.namespace, self.subsystem, self.name) if p)


class Counter(abc.ABC):
    @abc.abstractmethod
    def add(self, delta: float) -> None: ...

    @abc.abstractmethod
    def with_labels(self, *label_values: str) -> "Counter": ...


class Gauge(abc.ABC):
    @abc.abstractmethod
    def set(self, value: float) -> None: ...

    @abc.abstractmethod
    def add(self, delta: float) -> None: ...

    @abc.abstractmethod
    def with_labels(self, *label_values: str) -> "Gauge": ...


class Histogram(abc.ABC):
    @abc.abstractmethod
    def observe(self, value: float) -> None: ...

    @abc.abstractmethod
    def with_labels(self, *label_values: str) -> "Histogram": ...


class Provider(abc.ABC):
    @abc.abstractmethod
    def new_counter(self, opts: MetricOpts) -> Counter: ...

    @abc.abstractmethod
    def new_gauge(self, opts: MetricOpts) -> Gauge: ...

    @abc.abstractmethod
    def new_histogram(self, opts: MetricOpts) -> Histogram: ...


# ---------------------------------------------------------------------------
# Disabled (no-op) provider — the default, as in the reference
# (pkg/consensus/consensus.go:113-115).
# ---------------------------------------------------------------------------


class _NopCounter(Counter):
    def add(self, delta: float) -> None:
        pass

    def with_labels(self, *label_values: str) -> Counter:
        return self


class _NopGauge(Gauge):
    def set(self, value: float) -> None:
        pass

    def add(self, delta: float) -> None:
        pass

    def with_labels(self, *label_values: str) -> Gauge:
        return self


class _NopHistogram(Histogram):
    def observe(self, value: float) -> None:
        pass

    def with_labels(self, *label_values: str) -> Histogram:
        return self


class DisabledProvider(Provider):
    def new_counter(self, opts: MetricOpts) -> Counter:
        return _NopCounter()

    def new_gauge(self, opts: MetricOpts) -> Gauge:
        return _NopGauge()

    def new_histogram(self, opts: MetricOpts) -> Histogram:
        return _NopHistogram()


# ---------------------------------------------------------------------------
# In-memory provider
# ---------------------------------------------------------------------------


def escape_label_value(value) -> str:
    """Prometheus text-format label-value escaping (backslash, quote,
    newline) — applied when the label pair is FORMED so the exposition
    stays parseable whatever the embedder labels with."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_suffix(label_names: tuple, label_values: tuple) -> str:
    """Label key suffix.  With declared names: Prometheus-style
    {name="value",...} with text-format escaping; without: the legacy
    {v1,v2} value form."""
    if label_names:
        pairs = ",".join(
            f'{n}="{escape_label_value(v)}"'
            for n, v in zip(label_names, label_values)
        )
        return "{" + pairs + "}"
    return "{" + ",".join(str(v) for v in label_values) + "}"


class _MemCounter(Counter):
    def __init__(self, store: dict, key: str, label_names: tuple = ()):
        self._store = store
        self._key = key
        self._label_names = label_names
        store.setdefault(key, 0.0)

    def add(self, delta: float) -> None:
        self._store[self._key] = self._store.get(self._key, 0.0) + delta

    def with_labels(self, *label_values: str) -> Counter:
        return _MemCounter(
            self._store,
            self._key + _label_suffix(self._label_names, label_values),
        )


class _MemGauge(Gauge):
    def __init__(self, store: dict, key: str, label_names: tuple = ()):
        self._store = store
        self._key = key
        self._label_names = label_names
        store.setdefault(key, 0.0)

    def set(self, value: float) -> None:
        self._store[self._key] = value

    def add(self, delta: float) -> None:
        self._store[self._key] = self._store.get(self._key, 0.0) + delta

    def with_labels(self, *label_values: str) -> Gauge:
        return _MemGauge(
            self._store,
            self._key + _label_suffix(self._label_names, label_values),
        )


class _MemHistogram(Histogram):
    def __init__(self, store: dict, key: str, label_names: tuple = ()):
        self._store = store
        self._key = key
        self._label_names = label_names
        store.setdefault(key, [])

    def observe(self, value: float) -> None:
        self._store.setdefault(self._key, []).append(value)

    def with_labels(self, *label_values: str) -> Histogram:
        return _MemHistogram(
            self._store,
            self._key + _label_suffix(self._label_names, label_values),
        )


class InMemoryProvider(Provider):
    """Thread-compatible in-memory metrics, introspectable by tests/bench."""

    def __init__(self) -> None:
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.histograms: dict[str, list[float]] = {}

    def new_counter(self, opts: MetricOpts) -> Counter:
        return _MemCounter(self.counters, opts.full_name)

    def new_gauge(self, opts: MetricOpts) -> Gauge:
        return _MemGauge(self.gauges, opts.full_name)

    def new_histogram(self, opts: MetricOpts) -> Histogram:
        return _MemHistogram(self.histograms, opts.full_name)

    def histogram_quantile(self, name: str, q: float) -> Optional[float]:
        vals = sorted(self.histograms.get(name, []))
        if not vals:
            return None
        idx = min(len(vals) - 1, int(q * len(vals)))
        return vals[idx]


# ---------------------------------------------------------------------------
# Naming / format plumbing + exporters
# (pkg/metrics/provider.go:19-127, namer.go: the reference carries
# statsd-format strings and Prometheus naming on MetricOpts; here the same
# capability is two concrete exporter providers with no external deps)
# ---------------------------------------------------------------------------


def statsd_name(opts: MetricOpts, label_values: Sequence[str] = ()) -> str:
    """Expand a statsd naming format.

    ``opts.statsd_format`` supports the reference's placeholders:
    ``%{#namespace}``, ``%{#subsystem}``, ``%{#name}`` and ``%{label}`` for
    each declared label name.  Default format: dotted fqname plus dotted
    label values in declaration order.
    """
    fmt = opts.statsd_format
    if not fmt:
        parts = [p for p in (opts.namespace, opts.subsystem, opts.name) if p]
        return ".".join(list(parts) + [str(v) for v in label_values])
    out = (fmt.replace("%{#namespace}", opts.namespace)
              .replace("%{#subsystem}", opts.subsystem)
              .replace("%{#name}", opts.name))
    for lname, lval in zip(opts.label_names, label_values):
        out = out.replace("%%{%s}" % lname, str(lval))
    return out


def prometheus_name(opts: MetricOpts) -> str:
    """Prometheus fqname: namespace_subsystem_name, snake-cased."""
    parts = [p for p in (opts.namespace, opts.subsystem, opts.name) if p]
    return "_".join(parts).replace(".", "_").replace("-", "_")


class _StatsdMetric:
    def __init__(self, provider: "StatsdProvider", opts: MetricOpts,
                 kind: str, label_values: tuple = ()):
        self._p = provider
        self._opts = opts
        self._kind = kind
        self._labels = label_values

    def _emit(self, value: float) -> None:
        self._p.emit(
            f"{statsd_name(self._opts, self._labels)}:{value:g}|{self._kind}"
        )


class _StatsdCounter(_StatsdMetric, Counter):
    def add(self, delta: float) -> None:
        self._emit(delta)

    def with_labels(self, *label_values: str) -> Counter:
        return _StatsdCounter(self._p, self._opts, self._kind, label_values)


class _StatsdGauge(_StatsdMetric, Gauge):
    def set(self, value: float) -> None:
        name = statsd_name(self._opts, self._labels)
        if value < 0:
            # bare negative values are deltas in the statsd protocol; an
            # absolute negative set needs a zero-reset first (the standard
            # emitter workaround)
            self._p.emit(f"{name}:0|g")
        self._p.emit(f"{name}:{value:g}|g")

    def add(self, delta: float) -> None:
        self._p.emit(
            f"{statsd_name(self._opts, self._labels)}:{'+' if delta >= 0 else ''}{delta:g}|g"
        )

    def with_labels(self, *label_values: str) -> Gauge:
        return _StatsdGauge(self._p, self._opts, self._kind, label_values)


class _StatsdHistogram(_StatsdMetric, Histogram):
    def observe(self, value: float) -> None:
        # the library records latencies in SECONDS (time.monotonic deltas);
        # statsd timers are milliseconds by convention
        self._emit(value * 1000.0)

    def with_labels(self, *label_values: str) -> Histogram:
        return _StatsdHistogram(self._p, self._opts, self._kind, label_values)


class StatsdProvider(Provider):
    """Emits statsd wire lines (``name:value|c|g|ms``) to a sink callable.

    The embedder supplies ``sink`` (e.g. a UDP socket's sendto); the default
    collects lines in ``self.lines`` for inspection.  Naming honors
    ``MetricOpts.statsd_format`` placeholders exactly like the reference's
    statsd namer (pkg/metrics/namer.go).
    """

    def __init__(self, sink=None):
        self.lines: list[str] = []
        self._sink = sink if sink is not None else self.lines.append
        self._lock = threading.Lock()

    def emit(self, line: str) -> None:
        with self._lock:
            self._sink(line)

    def new_counter(self, opts: MetricOpts) -> Counter:
        return _StatsdCounter(self, opts, "c")

    def new_gauge(self, opts: MetricOpts) -> Gauge:
        return _StatsdGauge(self, opts, "g")

    def new_histogram(self, opts: MetricOpts) -> Histogram:
        return _StatsdHistogram(self, opts, "ms")


class PrometheusProvider(InMemoryProvider):
    """In-memory provider with a Prometheus text-format exposition surface.

    ``expose()`` renders every registered metric in the text format a
    Prometheus scrape endpoint serves (# HELP / # TYPE + samples); the
    embedder mounts it behind its own HTTP handler.
    """

    def __init__(self) -> None:
        super().__init__()
        self._meta: dict[str, tuple[str, str]] = {}  # fqname -> (type, help)

    def _register(self, opts: MetricOpts, kind: str) -> str:
        fq = prometheus_name(opts)
        self._meta[fq] = (kind, opts.help)
        return fq

    def new_counter(self, opts: MetricOpts) -> Counter:
        return _MemCounter(self.counters, self._register(opts, "counter"),
                           tuple(opts.label_names))

    def new_gauge(self, opts: MetricOpts) -> Gauge:
        return _MemGauge(self.gauges, self._register(opts, "gauge"),
                         tuple(opts.label_names))

    def new_histogram(self, opts: MetricOpts) -> Histogram:
        return _MemHistogram(self.histograms, self._register(opts, "histogram"),
                             tuple(opts.label_names))

    @staticmethod
    def _split(key: str) -> tuple[str, str]:
        """'fq{a,b}' -> (fq, 'a,b'); plain keys have no label suffix.

        Legacy value-only label suffixes (metrics built with
        ``with_labels`` but no declared ``label_names`` — the {v1,v2}
        store-key form) are rewritten to a parseable
        ``label="v1,v2"`` pair: the raw form is NOT legal text-format
        exposition, and a scraper would reject the whole page over it.
        The test is "does it parse as valid pairs", not "contains =" —
        a legacy value like ``query=slow`` carries an '=' and is still
        not exposition grammar."""
        if key.endswith("}") and "{" in key:
            base, labels = key[:-1].split("{", 1)
            if not _labels_are_valid_pairs(labels):
                labels = f'label="{escape_label_value(labels)}"'
            return base, labels
        return key, ""

    def expose(self) -> str:
        out: list[str] = []
        emitted: set[str] = set()

        def header(fq: str) -> None:
            if fq in emitted or fq not in self._meta:
                return
            kind, help_ = self._meta[fq]
            if help_:
                out.append(f"# HELP {fq} {help_}")
            out.append(f"# TYPE {fq} {kind}")
            emitted.add(fq)

        for key, val in sorted(self.counters.items()):
            fq, labels = self._split(key)
            header(fq)
            out.append(f"{fq}{{{labels}}} {val:g}" if labels else f"{fq} {val:g}")
        for key, val in sorted(self.gauges.items()):
            fq, labels = self._split(key)
            header(fq)
            out.append(f"{fq}{{{labels}}} {val:g}" if labels else f"{fq} {val:g}")
        for key, vals in sorted(self.histograms.items()):
            fq, labels = self._split(key)
            header(fq)
            suffix = f"{{{labels}}}" if labels else ""
            # a catch-all le bucket keeps strict parsers / promtool happy
            inf_labels = (labels + "," if labels else "") + 'le="+Inf"'
            out.append(f"{fq}_bucket{{{inf_labels}}} {len(vals):g}")
            out.append(f"{fq}_count{suffix} {len(vals):g}")
            out.append(f"{fq}_sum{suffix} {sum(vals):g}")
        return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# Prometheus exposition lint (ISSUE 14 satellite): a pure validator of the
# text format, so cmd=metrics stays SCRAPEABLE as counters keep accreting.
# ---------------------------------------------------------------------------

import re as _re

_METRIC_NAME_RE = _re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = _re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SAMPLE_RE = _re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)"
    r"(?:\s+(?P<ts>-?\d+))?\s*$"
)
# one label pair with text-format escapes inside the quoted value; the
# name charset is deliberately loose here — the strict check happens
# against _LABEL_NAME_RE so a bad NAME reports as such, not as syntax
_LABEL_PAIR_RE = _re.compile(
    r'\s*(?P<name>[^=,"{}\s]+)\s*=\s*'
    r'"(?P<value>(?:[^"\\\n]|\\\\|\\"|\\n)*)"\s*(?:,|$)'
)
_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}
#: suffixes a histogram/summary family's samples may carry
_HIST_SUFFIXES = ("_bucket", "_count", "_sum", "_created")


def _labels_are_valid_pairs(labels: str) -> bool:
    """True when ``labels`` fully parses as text-format label pairs
    (valid names, quoted + escaped values) — the PrometheusProvider
    legacy-suffix rewrite keys off this, and the lint uses the same
    pair grammar."""
    pos = 0
    while pos < len(labels):
        m = _LABEL_PAIR_RE.match(labels, pos)
        if m is None or not _LABEL_NAME_RE.match(m.group("name")):
            return False
        pos = m.end()
    return pos > 0


def _sample_family(name: str, types: dict) -> Optional[str]:
    """The declared family a sample name belongs to, if any."""
    if name in types:
        return name
    for suffix in _HIST_SUFFIXES:
        if name.endswith(suffix) and name[: -len(suffix)] in types:
            return name[: -len(suffix)]
    return None


def lint_prometheus_text(text: str) -> list[str]:
    """Validate a Prometheus text-format exposition; returns [] when
    clean, else one message per problem (line-numbered).

    Checks the grammar a strict scraper/promtool enforces: metric/label
    name charset, quoted + escaped label values, float-parseable sample
    values, at most ONE ``# TYPE`` (and ``# HELP``) per family with the
    TYPE preceding that family's first sample, a known type keyword, no
    duplicate (name, labelset) samples, and histogram/summary samples
    restricted to the legal suffixes of their declared family."""
    problems: list[str] = []
    types: dict[str, str] = {}
    helps: set[str] = set()
    sampled_families: set[str] = set()
    seen_samples: set[tuple] = set()
    for ln, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                continue  # free-form comment: legal
            name = parts[2]
            if not _METRIC_NAME_RE.match(name):
                problems.append(f"line {ln}: bad metric name {name!r}")
                continue
            if parts[1] == "TYPE":
                kind = parts[3].strip() if len(parts) > 3 else ""
                if kind not in _TYPES:
                    problems.append(
                        f"line {ln}: unknown TYPE {kind!r} for {name}"
                    )
                if name in types:
                    problems.append(
                        f"line {ln}: duplicate TYPE line for {name}"
                    )
                if name in sampled_families:
                    problems.append(
                        f"line {ln}: TYPE for {name} after its samples"
                    )
                types[name] = kind
            else:
                if name in helps:
                    problems.append(
                        f"line {ln}: duplicate HELP line for {name}"
                    )
                helps.add(name)
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"line {ln}: unparseable sample {line!r}")
            continue
        name = m.group("name")
        labels_raw = m.group("labels")
        labelset = ""
        if labels_raw is not None:
            pos = 0
            pairs = []
            while pos < len(labels_raw):
                pm = _LABEL_PAIR_RE.match(labels_raw, pos)
                if pm is None:
                    problems.append(
                        f"line {ln}: bad label syntax at {labels_raw[pos:]!r}"
                        " (unescaped quote/backslash/newline?)"
                    )
                    pairs = None
                    break
                if not _LABEL_NAME_RE.match(pm.group("name")):
                    problems.append(
                        f"line {ln}: bad label name {pm.group('name')!r}"
                    )
                pairs.append((pm.group("name"), pm.group("value")))
                pos = pm.end()
            if pairs is None:
                continue
            labelset = ",".join(f'{n}="{v}"' for n, v in sorted(pairs))
        try:
            float(m.group("value"))
        except ValueError:
            if m.group("value") not in ("+Inf", "-Inf", "NaN"):
                problems.append(
                    f"line {ln}: sample value {m.group('value')!r} is not "
                    "a float"
                )
        key = (name, labelset)
        if key in seen_samples:
            problems.append(
                f"line {ln}: duplicate sample {name}{{{labelset}}}"
            )
        seen_samples.add(key)
        family = _sample_family(name, types)
        if family is not None:
            sampled_families.add(family)
            kind = types.get(family)
            # summaries deliberately get no bare-sample check: quantile
            # samples legally use the bare family name
            if kind == "histogram" and name == family:
                problems.append(
                    f"line {ln}: histogram {family} exposes a bare sample "
                    f"(only {'/'.join(_HIST_SUFFIXES)} are legal)"
                )
            if kind in ("counter", "gauge") and name != family:
                problems.append(
                    f"line {ln}: {kind} {family} exposes suffixed sample "
                    f"{name}"
                )
    return problems


# ---------------------------------------------------------------------------
# Metric bundles (pkg/api/metrics.go)
# ---------------------------------------------------------------------------


def _c(p: Provider, subsystem: str, name: str, help: str = "") -> Counter:
    return p.new_counter(MetricOpts(namespace="consensus", subsystem=subsystem, name=name, help=help))


def _g(p: Provider, subsystem: str, name: str, help: str = "") -> Gauge:
    return p.new_gauge(MetricOpts(namespace="consensus", subsystem=subsystem, name=name, help=help))


def _h(p: Provider, subsystem: str, name: str, help: str = "") -> Histogram:
    return p.new_histogram(MetricOpts(namespace="consensus", subsystem=subsystem, name=name, help=help))


class RequestPoolMetrics:
    """metrics.go:106-172 — seven request-pool metrics."""

    def __init__(self, p: Provider):
        self.count_of_requests = _g(p, "pool", "count_of_requests")
        self.count_of_failed_add_requests = _c(p, "pool", "count_of_failed_add_requests")
        self.count_of_leader_forward_requests = _c(p, "pool", "count_of_leader_forward_requests")
        self.count_leader_forward_timeout = _c(p, "pool", "count_leader_forward_timeout")
        self.count_of_complain_timeout = _c(p, "pool", "count_of_complain_timeout")
        self.count_of_deleted_requests = _c(p, "pool", "count_of_deleted_requests")
        self.latency_of_requests = _h(p, "pool", "latency_of_requests")


class BlacklistMetrics:
    """metrics.go:239-258."""

    def __init__(self, p: Provider):
        self.count_black_list = _g(p, "blacklist", "count_black_list")
        self.nodes_in_black_list = _g(p, "blacklist", "nodes_in_black_list")


class ConsensusMetrics:
    """metrics.go:299-343."""

    def __init__(self, p: Provider):
        self.count_consensus_reconfig = _c(p, "consensus", "count_consensus_reconfig")
        self.latency_sync = _h(p, "consensus", "latency_sync")


class ViewMetrics:
    """metrics.go:346-460 — per-view protocol progress metrics."""

    def __init__(self, p: Provider):
        self.view_number = _g(p, "view", "number")
        self.leader_id = _g(p, "view", "leader_id")
        self.proposal_sequence = _g(p, "view", "proposal_sequence")
        self.decisions_in_view = _g(p, "view", "decisions_in_view")
        self.phase = _g(p, "view", "phase")
        self.count_txs_in_batch = _g(p, "view", "count_txs_in_batch")
        self.count_batch_all = _c(p, "view", "count_batch_all")
        self.count_txs_all = _c(p, "view", "count_txs_all")
        self.size_of_batch = _c(p, "view", "size_of_batch")
        self.latency_batch_processing = _h(p, "view", "latency_batch_processing")
        self.latency_batch_save = _h(p, "view", "latency_batch_save")


class ViewChangeMetrics:
    """metrics.go:520-548 — plus the VC-health instrumentation ISSUE 12
    wires for real: complaint traffic, rounds, sync escalations, and a
    live time-in-view-change gauge, fed from the ViewChanger (and its
    phase tracker) so Prometheus/statsd providers see failover health
    without the flight recorder enabled."""

    def __init__(self, p: Provider):
        self.current_view = _g(p, "viewchange", "current_view")
        self.next_view = _g(p, "viewchange", "next_view")
        self.real_view = _g(p, "viewchange", "real_view")
        #: ViewChange messages this node broadcast (starts + resends +
        #: lagging-node help)
        self.count_complaints_sent = _c(
            p, "viewchange", "count_complaints_sent")
        #: ViewChange messages received from peers
        self.count_complaints_received = _c(
            p, "viewchange", "count_complaints_received")
        #: view-change rounds armed on this node (a timeout escalation
        #: toward a higher view is a new round)
        self.count_view_change_rounds = _c(p, "viewchange", "count_rounds")
        #: timeout escalations that forced a sync mid-view-change
        self.count_sync_escalations = _c(
            p, "viewchange", "count_sync_escalations")
        #: seconds in the CURRENT view change (live, tick-updated) —
        #: freezes at the end-to-end total when the round completes
        self.time_in_view_change = _g(
            p, "viewchange", "time_in_view_change_seconds")
        #: complain-timer arm-to-fire time of the LAST heartbeat-timeout
        #: firing (seconds): the detection latency PERF round 15 blamed
        #: for ~99% of the failover cliff, now a first-class gauge
        self.heartbeat_detection_seconds = _g(
            p, "viewchange", "heartbeat_detection_seconds")
        #: heartbeat-timeout firings (each arms/rearms a complain)
        self.count_heartbeat_timeouts = _c(
            p, "viewchange", "count_heartbeat_timeouts")
        #: request-pool depth at the view flip (the stalled backlog the
        #: new view must drain before request p99 recovers)
        self.backlog_at_view_flip = _g(
            p, "viewchange", "backlog_at_view_flip")
        #: the EFFECTIVE (derived) complain timer and its inputs
        #: (ISSUE 15): detection_timeout_seconds is what the monitor will
        #: actually wait before complaining — the RTT/commit-EWMA-derived
        #: value after backoff and ceiling clamp; the *_input gauges are
        #: its live signal terms (0 when the signal is unmeasured) and
        #: detection_backoff_round the consecutive-complaint widening
        #: round against the current view
        self.detection_timeout_seconds = _g(
            p, "viewchange", "detection_timeout_seconds")
        self.detection_rtt_seconds = _g(
            p, "viewchange", "detection_rtt_input_seconds")
        self.detection_commit_interval_seconds = _g(
            p, "viewchange", "detection_commit_interval_input_seconds")
        self.detection_backoff_round = _g(
            p, "viewchange", "detection_backoff_round")


class TPUCryptoMetrics:
    """TPU-plane additions (BASELINE.json): batch occupancy + verify latency.

    PER-INSTANCE by construction (one bundle per provider) — nothing here
    is process-global, so counters from colocated shards/nodes never smear
    unless the embedder deliberately shares one provider.  The sharded
    harness DOES share one (the verify plane is one coalescer, so its
    fill/latency/breaker counters are inherently whole-plane); an embedder
    that instead builds per-shard providers reads the roll-up with
    :func:`tpu_counters_aggregate`."""

    def __init__(self, p: Provider):
        self.batch_fill_percent = _h(p, "tpu", "batch_fill_percent")
        self.verify_latency_per_sig_us = _h(p, "tpu", "verify_latency_per_sig_us")
        self.count_sigs_verified = _c(p, "tpu", "count_sigs_verified")
        self.count_batches = _c(p, "tpu", "count_batches")
        # verify-plane fault tolerance (launch deadlines / retry / breaker):
        # transitions are counted here AND mirrored into every bench JSON
        # row, so a degraded (host-fallback) run is never silently reported
        # as a device run
        self.count_launch_failures = _c(p, "tpu", "count_launch_failures")
        self.count_launch_timeouts = _c(p, "tpu", "count_launch_timeouts")
        self.count_launch_retries = _c(p, "tpu", "count_launch_retries")
        self.count_breaker_open = _c(p, "tpu", "count_breaker_open")
        self.count_breaker_close = _c(p, "tpu", "count_breaker_close")
        self.count_host_fallback_batches = _c(
            p, "tpu", "count_host_fallback_batches"
        )
        #: 1.0 while the host-fallback circuit breaker is open (degraded
        #: mode: waves verify on CPU), 0.0 when the device engine serves
        self.breaker_state = _g(p, "tpu", "verify_breaker_open")
        # mesh verify plane (ISSUE 10): the graduated multi-device path.
        # mesh_devices is the installed mesh width (0 = single-device);
        # per-launch accounting (launch count, pad-slot waste, the MINIMUM
        # per-device fill of each launch — padding lands on tail devices)
        # plus the loud unbuildable-mesh downgrade counter, so a degraded
        # single-device run is never mistaken for a mesh run
        self.mesh_devices = _g(p, "tpu", "mesh_devices")
        self.count_mesh_launches = _c(p, "tpu", "count_mesh_launches")
        self.count_mesh_pad_slots = _c(p, "tpu", "count_mesh_pad_slots")
        self.count_mesh_downgrades = _c(p, "tpu", "count_mesh_downgrades")
        self.mesh_device_fill_percent = _h(p, "tpu", "mesh_device_fill_percent")
        # occupancy-aware flush gating (ISSUE 11): how many flushes held
        # for predicted-inbound waves, and how many items those holds
        # actually gained — the wave-deepening payoff, mirrored in the
        # `hold` sub-block of every bench row's `mesh` block
        self.count_waves_held = _c(p, "tpu", "count_waves_held")
        self.count_hold_depth_gain = _c(p, "tpu", "count_hold_depth_gain")
        #: invalid vote verdicts ATTRIBUTED BY SIGNER (ISSUE 18): the
        #: provider increments `.with_labels(str(signer))` on every failed
        #: consenter-sig verdict (bad signature value, digest-binding
        #: forgery, unknown signer), so a forgery flood shows WHO instead
        #: of vanishing into the aggregate failure count — the export the
        #: per-sender misbehavior table and bench `byzantine` rows read
        self.count_invalid_votes = _c(
            p, "tpu", "count_invalid_votes",
            help="failed consenter-sig verdicts attributed by signer id",
        )


def tpu_counters_aggregate(providers: Sequence[InMemoryProvider]) -> dict:
    """Explicit aggregate view over per-shard TPU metric providers.

    Sums every ``.tpu.`` counter across the given
    :class:`InMemoryProvider` instances; gauges sum too (a 0/1 gauge like
    ``verify_breaker_open`` aggregates to "how many providers are
    degraded"); histograms contribute their observation counts under
    ``<name>_count``.  For an embedder that gives each shard its own
    provider, this is the one-call roll-up (the in-process harness instead
    shares one provider across the shared plane — see
    :class:`TPUCryptoMetrics`)."""
    out: dict = {}
    for p in providers:
        for store in (p.counters, p.gauges):
            for key, val in store.items():
                if ".tpu." in key:
                    out[key] = out.get(key, 0.0) + val
        for key, vals in p.histograms.items():
            if ".tpu." in key:
                out[key + "_count"] = out.get(key + "_count", 0.0) + len(vals)
    return out


# ---------------------------------------------------------------------------
# Commit-latency accounting (the open-loop service surface: README
# "Overload behavior", benchmarks/openloop.py, bench.py --open-loop)
# ---------------------------------------------------------------------------


class LogScaleHistogram:
    """Fixed-bucket log-scale histogram with BOUNDED memory.

    The in-memory provider's histograms append every observation — fine
    for bench windows, fatal for a service recording one sample per
    request forever.  This histogram is a fixed array of geometric
    buckets (default: 1 µs low edge, √2 growth, 64 buckets ≈ 1 µs..100 s
    span), so a billion observations cost the same 64 ints.  Quantiles
    come from the cumulative bucket walk and are reported at the bucket's
    geometric midpoint — ≤ ~±19% relative error at √2 growth, far inside
    the run-to-run noise of any latency measurement this repo makes."""

    __slots__ = ("low", "growth", "buckets", "count", "total", "max_seen",
                 "min_seen", "_log_low", "_log_growth")

    def __init__(self, low: float = 1e-6, growth: float = 2.0 ** 0.5,
                 nbuckets: int = 64):
        self.low = low
        self.growth = growth
        self.buckets = [0] * nbuckets
        self.count = 0
        self.total = 0.0
        self.max_seen = 0.0
        self.min_seen = float("inf")
        self._log_low = math.log(low)
        self._log_growth = math.log(growth)

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value > self.max_seen:
            self.max_seen = value
        if value < self.min_seen:
            self.min_seen = value
        if value <= self.low:
            idx = 0
        else:
            idx = int((math.log(value) - self._log_low) / self._log_growth)
            idx = min(max(idx, 0), len(self.buckets) - 1)
        self.buckets[idx] += 1

    def quantile(self, q: float) -> float:
        """The q-quantile (0..1) at the owning bucket's geometric midpoint,
        clamped into the observed [min, max] envelope; 0.0 when empty."""
        if not self.count:
            return 0.0
        rank = max(1, int(q * self.count + 0.999999))  # ceil, 1-based
        seen = 0
        for i, n in enumerate(self.buckets):
            seen += n
            if seen >= rank:
                mid = self.low * (self.growth ** (i + 0.5))
                return min(max(mid, self.min_seen), self.max_seen)
        return self.max_seen

    def delta_quantile(self, q: float, baseline: list) -> float:
        """The q-quantile of the observations recorded SINCE ``baseline``
        (a prior copy of ``buckets``) — the recency window a cumulative
        histogram cannot otherwise express.  Same-geometry buckets
        subtract element-wise exactly, so this is the true distribution
        of the delta; the [min, max] clamp uses the lifetime envelope
        (per-window extremes are not tracked — ≤ one bucket of extra
        slack at the edges).  0.0 when nothing landed since the
        baseline.  A health plane needs this: a verdict judged on the
        lifetime p99 can never clear after one bad spell."""
        counts = [n - b for n, b in zip(self.buckets, baseline)]
        total = sum(counts)
        if total <= 0:
            return 0.0
        rank = max(1, int(q * total + 0.999999))
        seen = 0
        for i, n in enumerate(counts):
            seen += n
            if seen >= rank:
                mid = self.low * (self.growth ** (i + 0.5))
                return min(max(mid, self.min_seen), self.max_seen)
        return self.max_seen

    def snapshot(self) -> dict:
        """JSON-able percentile block (milliseconds, the service unit)."""
        ms = 1e3
        return {
            "count": self.count,
            "p50_ms": round(self.quantile(0.50) * ms, 3),
            "p95_ms": round(self.quantile(0.95) * ms, 3),
            "p99_ms": round(self.quantile(0.99) * ms, 3),
            "mean_ms": round(self.total / self.count * ms, 3)
            if self.count else 0.0,
            "max_ms": round(self.max_seen * ms, 3),
        }

    def merge_from(self, other: "LogScaleHistogram") -> None:
        """Fold ``other``'s observations into this histogram EXACTLY —
        same-geometry fixed buckets sum element-wise, so a merge over N
        per-replica histograms is the true combined distribution (the
        obs.assemble_trace_block roll-up), never a
        percentile-of-percentiles."""
        if (other.low != self.low or other.growth != self.growth
                or len(other.buckets) != len(self.buckets)):
            raise ValueError("cannot merge histograms of different geometry")
        for i, n in enumerate(other.buckets):
            self.buckets[i] += n
        self.count += other.count
        self.total += other.total
        if other.max_seen > self.max_seen:
            self.max_seen = other.max_seen
        if other.min_seen < self.min_seen:
            self.min_seen = other.min_seen

    def export_state(self) -> dict:
        """JSON-able FULL state (geometry + raw buckets) — the wire shape
        the per-shard affinity-sweep workers ship to the parent so the
        merged percentiles come from :meth:`merge_from`'s exact bucket
        sum, never a percentile-of-percentiles."""
        return {
            "low": self.low,
            "growth": self.growth,
            "buckets": list(self.buckets),
            "count": self.count,
            "total": self.total,
            "max_seen": self.max_seen,
            "min_seen": self.min_seen if self.count else None,
        }

    @classmethod
    def from_state(cls, state: dict) -> "LogScaleHistogram":
        """Rebuild a histogram from :meth:`export_state` output."""
        h = cls(low=state["low"], growth=state["growth"],
                nbuckets=len(state["buckets"]))
        h.buckets = [int(n) for n in state["buckets"]]
        h.count = int(state["count"])
        h.total = float(state["total"])
        h.max_seen = float(state["max_seen"])
        if state.get("min_seen") is not None:
            h.min_seen = float(state["min_seen"])
        return h

    def nonzero_buckets(self) -> dict:
        """Sparse bucket dump for the bench row's ``histogram`` block:
        {upper_edge_ms: count} for every non-empty bucket."""
        out = {}
        for i, n in enumerate(self.buckets):
            if n:
                edge_ms = self.low * (self.growth ** (i + 1)) * 1e3
                out[f"{edge_ms:.3g}"] = n
        return out


class CommitLatencyTracker:
    """Per-request submit→commit latency for a sharded front door.

    The ShardSet stamps each request's arrival at ``submit`` (BEFORE any
    admission/backpressure wait — the latency a client experiences
    includes the queueing) and resolves the stamp when the request id
    appears in the combined committed stream.  Aggregated into
    :class:`LogScaleHistogram` buckets per shard + overall, with shed
    counters (requests refused by admission control or timed out of the
    space wait) alongside — a latency distribution without its shed rate
    is survivor bias.

    **Phases.**  ``begin_phase(name)`` opens a named window (histogram +
    shed deltas) that subsequent commits/sheds also land in — how the
    degraded-mode SLO runs attribute p99 to "breaker open" vs "view
    change" vs "reshard" without re-running the workload per fault.

    **Bounded memory.**  Histograms are fixed arrays; the pending-stamp
    map is capped at ``max_pending`` — beyond it the OLDEST stamp is
    dropped and counted (an overloaded front door sheds; it never grows
    an unbounded latency map).  ``clock`` is injectable: wall
    ``time.monotonic`` in production/bench, the logical ``Scheduler.now``
    in deterministic tests."""

    def __init__(self, clock=None, max_pending: int = 65536):
        import collections
        import time as _time

        self._clock = clock if clock is not None else _time.monotonic
        self._pending: "collections.OrderedDict[str, float]" = \
            collections.OrderedDict()
        self.max_pending = max_pending
        self.dropped_stamps = 0
        self.aggregate = LogScaleHistogram()
        self.per_shard: dict[int, LogScaleHistogram] = {}
        self.shed = {"admission": 0, "timeout": 0, "other": 0}
        self._phases: "dict[str, dict]" = {}
        self._phase_order: list[str] = []
        self._current_phase: Optional[dict] = None

    # -- stamping ----------------------------------------------------------

    def on_submitted(self, key: str) -> bool:
        """Stamp ``key``'s arrival (front-door entry, pre-queueing).

        A key already pending keeps its ORIGINAL stamp — a client
        retrying a still-in-flight request experiences latency from its
        FIRST submit, and overwriting would let the pool's dedup path
        erase the measurement of exactly the slow (hence retried)
        requests.  Returns True when a fresh stamp was created."""
        key = str(key)
        if key in self._pending:
            return False
        self._pending[key] = self._clock()
        if len(self._pending) > self.max_pending:
            self._pending.popitem(last=False)
            self.dropped_stamps += 1
        return True

    def discard(self, key: str) -> None:
        """Drop a stamp without counting anything (e.g. a submit that
        turned out to be a duplicate of an ALREADY-COMMITTED request —
        no commit is coming, and it was not shed either)."""
        self._pending.pop(str(key), None)

    def on_shed(self, key: Optional[str], kind: str) -> None:
        """The stamped submit was refused (``admission`` / ``timeout`` /
        ``other``): drop its stamp, count the shed."""
        if key is not None:
            self._pending.pop(str(key), None)
        kind = kind if kind in self.shed else "other"
        self.shed[kind] += 1
        if self._current_phase is not None:
            self._current_phase["shed"][kind] += 1

    def on_committed(self, key: str, shard_id: int) -> None:
        """Resolve a stamp against the committed stream; unstamped ids
        (barrier commands, requests submitted around the tracker) no-op."""
        t0 = self._pending.pop(str(key), None)
        if t0 is None:
            return
        dt = max(self._clock() - t0, 0.0)
        self.aggregate.observe(dt)
        hist = self.per_shard.get(shard_id)
        if hist is None:
            hist = self.per_shard[shard_id] = LogScaleHistogram()
        hist.observe(dt)
        if self._current_phase is not None:
            self._current_phase["hist"].observe(dt)

    def on_committed_batch(self, entries) -> None:
        """Resolve a whole committed wave of
        :class:`~smartbft_tpu.shard.mux.CommittedEntry` in one pass: one
        clock read and one per-shard histogram lookup per wave instead of
        per request — the egress half of the batched deliver fan-out."""
        now = None
        for e in entries:
            hist = None  # resolved lazily: entries of pure control traffic
            for key in e.request_ids:  # must not materialize a histogram
                t0 = self._pending.pop(key, None)
                if t0 is None:
                    continue
                if now is None:
                    now = self._clock()
                if hist is None:
                    hist = self.per_shard.get(e.shard_id)
                    if hist is None:
                        hist = self.per_shard[e.shard_id] = LogScaleHistogram()
                dt = max(now - t0, 0.0)
                self.aggregate.observe(dt)
                hist.observe(dt)
                if self._current_phase is not None:
                    self._current_phase["hist"].observe(dt)

    # -- phases ------------------------------------------------------------

    def begin_phase(self, name: str) -> None:
        """Open (or re-open) the named attribution window; subsequent
        commits and sheds land in it until the next begin_phase."""
        phase = self._phases.get(name)
        if phase is None:
            phase = self._phases[name] = {
                "hist": LogScaleHistogram(),
                "shed": {k: 0 for k in self.shed},
            }
            self._phase_order.append(name)
        self._current_phase = phase

    def end_phase(self) -> None:
        self._current_phase = None

    # -- reading -----------------------------------------------------------

    def pending(self) -> int:
        return len(self._pending)

    def snapshot(self) -> dict:
        """The JSON-able ``latency`` block every open-loop bench row
        carries (schema pinned by tests/test_overload.py)."""
        out = dict(self.aggregate.snapshot())
        out["shed"] = dict(self.shed)
        # the raw distribution (sparse {upper_edge_ms: count}), bounded at
        # 64 entries — what the bench row's "histogram" promise refers to
        out["histogram"] = self.aggregate.nonzero_buckets()
        out["pending_stamps"] = len(self._pending)
        out["dropped_stamps"] = self.dropped_stamps
        out["per_shard"] = {
            s: h.snapshot() for s, h in sorted(self.per_shard.items())
        }
        if self._phase_order:
            out["phases"] = {
                name: dict(self._phases[name]["hist"].snapshot(),
                           shed=dict(self._phases[name]["shed"]))
                for name in self._phase_order
            }
        return out


# ---------------------------------------------------------------------------
# Protocol-plane timers (the vectorized message plane's measurement surface)
# ---------------------------------------------------------------------------


class ProtocolPlaneTimers:
    """Process-wide accumulator for the message plane's hot-path terms.

    The round-6 ceiling decomposition (PERF.md) showed the paired ratio
    bound by the PROTOCOL plane, dominated by per-message routing, vote
    registration, and (in any real transport) per-recipient codec work.
    These counters make that cost measured instead of asserted: the
    in-process network, the controller dispatch, and the views accumulate
    wall-time (microseconds) and call counts here, and every
    ``bench.py`` / ``benchmarks/throughput.py`` JSON row exports a
    ``protocol_plane`` block from a snapshot delta.

    Accumulation is a couple of float adds per WAVE (never per message),
    so the accounting itself stays off the path it measures.  The four
    timers are DISJOINT: the network subtracts the codec time accrued
    inside a fan-out from ``route_us`` and the codec + vote-registration
    time accrued inside an ingest tick from ``ingest_us``, so
    ``ingest_us + route_us + vote_reg_us + codec_us`` is the plane total
    without double-counting.  (``route_us`` is the sender side: fault
    checks + enqueue; ``ingest_us`` is the receiver-side drain/dispatch
    remainder; ``codec_us`` covers every marshal/unmarshal wherever it
    runs; ``vote_reg_us`` is view-level wave registration.)

    **Per-instance attribution (sharded mode).**  Timers are PER-INSTANCE:
    every constructed ``ProtocolPlaneTimers`` joins a process-wide
    registry, and :func:`protocol_plane_snapshot` returns the AGGREGATE
    across all instances — so embedders that only ever touch the default
    :data:`PROTOCOL_PLANE` singleton see exactly the old behavior, while a
    sharded deployment hands each consensus group its own plane (via
    ``testing.network.Network.group(gid, plane=...)``) and can attribute
    message-plane cost per shard AND still read the whole-process
    aggregate from the same back-compat function.
    """

    __slots__ = (
        "name", "__weakref__",
        "ingest_us", "route_us", "vote_reg_us", "codec_us",
        "broadcasts", "sends", "encodes", "encode_memo_hits",
        "decodes", "decode_interned_hits", "intern_evictions",
        "batch_ingests", "msgs_ingested", "malformed_dropped",
    )

    #: process-wide registry of every live plane — the aggregate view.
    #: Weak references: a plane lives exactly as long as its owner (a
    #: Network/cluster holds a strong ref), so long-lived processes that
    #: build many clusters (benches, soaks) neither grow the registry
    #: without bound nor smear dead clusters' counters into the aggregate.
    _registry: "list[weakref.ref[ProtocolPlaneTimers]]" = []
    _registry_lock = threading.Lock()

    #: slots that carry measurement (everything except the identity field)
    _COUNTER_SLOTS: tuple[str, ...] = ()

    def __init__(self, name: str = "") -> None:
        self.name = name
        self.reset()
        with ProtocolPlaneTimers._registry_lock:
            ProtocolPlaneTimers._registry.append(weakref.ref(self))

    def reset(self) -> None:
        self.ingest_us = 0.0    # node batch-drain -> dispatch, total
        self.route_us = 0.0     # sender-side fan-out (fault checks + enqueue)
        self.vote_reg_us = 0.0  # view-level wave registration (slots/vote sets)
        self.codec_us = 0.0     # marshal + (interned) unmarshal wall time
        self.broadcasts = 0           # broadcast_consensus fan-outs
        self.sends = 0                # single-target consensus sends
        self.encodes = 0              # actual marshal() compilations
        self.encode_memo_hits = 0     # wire bytes served from the message memo
        self.decodes = 0              # actual unmarshal() runs (intern misses)
        self.decode_interned_hits = 0  # deliveries served by the intern memo
        self.intern_evictions = 0     # bounded intern memo evictions
        self.batch_ingests = 0        # node ingest ticks (batches drained)
        self.msgs_ingested = 0        # messages across those ticks
        self.malformed_dropped = 0    # undecodable wire payloads dropped

    def snapshot(self) -> dict:
        return {name: getattr(self, name)
                for name in ProtocolPlaneTimers._COUNTER_SLOTS}

    @staticmethod
    def delta(before: dict, after: dict) -> dict:
        return {
            k: round(after[k] - before[k], 1)
            if isinstance(after[k], float) else after[k] - before[k]
            for k in after
        }

    @staticmethod
    def sum_snapshots(snapshots: Sequence[dict]) -> dict:
        """Element-wise sum — the aggregate view over per-shard planes."""
        out: dict = {
            k: 0.0 if k.endswith("_us") else 0
            for k in ProtocolPlaneTimers._COUNTER_SLOTS
        }
        for snap in snapshots:
            for k, v in snap.items():
                out[k] = out.get(k, 0) + v
        return {k: round(v, 1) if isinstance(v, float) else v
                for k, v in out.items()}


ProtocolPlaneTimers._COUNTER_SLOTS = tuple(
    s for s in ProtocolPlaneTimers.__slots__
    if s not in ("name", "__weakref__")
)


#: the process-wide DEFAULT instance — what every accounting site feeds
#: unless the embedder wired a per-instance plane (one in-process cluster
#: = one plane, the single-group deployment the original benches measure)
PROTOCOL_PLANE = ProtocolPlaneTimers(name="default")


def protocol_plane_instances() -> "list[ProtocolPlaneTimers]":
    """Every live plane (default singleton first) — per-shard attribution.
    Dead weakrefs (planes whose owning cluster was collected) are pruned."""
    with ProtocolPlaneTimers._registry_lock:
        alive: list = []
        out: list = []
        for ref in ProtocolPlaneTimers._registry:
            plane = ref()
            if plane is not None:
                alive.append(ref)
                out.append(plane)
        ProtocolPlaneTimers._registry[:] = alive
        return out


def protocol_plane_snapshot() -> dict:
    """AGGREGATE snapshot across every plane instance in the process.

    Back-compat contract: when only the default :data:`PROTOCOL_PLANE`
    exists (every pre-sharding embedder), this is exactly its snapshot;
    with per-shard planes wired it is their element-wise sum, so existing
    bench/JSON consumers keep reading whole-process numbers."""
    return ProtocolPlaneTimers.sum_snapshots(
        [p.snapshot() for p in protocol_plane_instances()]
    )


#: task-context plane installed by the transport around an ingest dispatch,
#: so accounting sites deep in the protocol core (view/pipeline vote
#: registration) attribute to the right shard without plumbing a plane
#: through every constructor.  None = use the process default.
_CURRENT_PLANE: "contextvars.ContextVar[Optional[ProtocolPlaneTimers]]" = (
    contextvars.ContextVar("smartbft_protocol_plane", default=None)
)


def current_plane() -> ProtocolPlaneTimers:
    """The plane the calling context should feed: the per-shard plane the
    transport installed for this dispatch, or the process default."""
    p = _CURRENT_PLANE.get()
    return PROTOCOL_PLANE if p is None else p


def install_plane(plane: Optional[ProtocolPlaneTimers]):
    """Install ``plane`` as this context's accounting target (the network
    wraps each ingest dispatch); returns the token for :func:`reset_plane`."""
    return _CURRENT_PLANE.set(plane)


def reset_plane(token) -> None:
    _CURRENT_PLANE.reset(token)


class MetricsBundle:
    """All bundles wired from one provider — what Consensus hands to components."""

    def __init__(self, p: Optional[Provider] = None):
        p = p or DisabledProvider()
        self.provider = p
        self.pool = RequestPoolMetrics(p)
        self.blacklist = BlacklistMetrics(p)
        self.consensus = ConsensusMetrics(p)
        self.view = ViewMetrics(p)
        self.view_change = ViewChangeMetrics(p)
        self.tpu = TPUCryptoMetrics(p)
