"""Native (C++) runtime helpers, loaded via ctypes with Python fallbacks.

The reference is pure Go; the TPU-native rebuild keeps its runtime plane
(WAL framing, hashing) native where throughput demands it.  Libraries are
compiled on first import with ``g++`` into this directory and cached; any
build failure falls back to the pure-Python implementations so the framework
never hard-depends on a toolchain at runtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_NAME = "libsmartbft_native.so"
_SOURCES = ["crc32c.cc", "wal_frame.cc"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build_lib(lib_path: str) -> bool:
    srcs = [os.path.join(_DIR, s) for s in _SOURCES]
    if not all(os.path.exists(s) for s in srcs):
        return False
    tmp = lib_path + f".tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, *srcs]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, lib_path)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _stale(lib_path: str) -> bool:
    try:
        lib_mtime = os.path.getmtime(lib_path)
    except OSError:
        return True
    for s in _SOURCES:
        try:
            if os.path.getmtime(os.path.join(_DIR, s)) > lib_mtime:
                return True
        except OSError:
            pass  # source pruned from the deploy — the built lib stands
    return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None on failure."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:  # lock-free hot path
        return _lib
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("SMARTBFT_NO_NATIVE"):
            return None
        lib_path = os.path.join(_DIR, _LIB_NAME)
        if _stale(lib_path) and not _build_lib(lib_path):
            return None
        try:
            lib = ctypes.CDLL(lib_path, use_errno=True)
            lib.smartbft_crc32c_update.restype = ctypes.c_uint32
            lib.smartbft_crc32c_update.argtypes = [
                ctypes.c_uint32,
                ctypes.c_char_p,
                ctypes.c_size_t,
            ]
            lib.smartbft_wal_append.restype = ctypes.c_long
            lib.smartbft_wal_append.argtypes = [
                ctypes.c_int,
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_int,
                ctypes.c_int,
            ]
            _lib = lib
        except (OSError, AttributeError):
            _lib = None
        return _lib


# ---------------------------------------------------------------------------
# crc32c
# ---------------------------------------------------------------------------

_PY_TABLE: Optional[list[int]] = None


def _py_table() -> list[int]:
    global _PY_TABLE
    if _PY_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (poly if c & 1 else 0)
            table.append(c)
        _PY_TABLE = table
    return _PY_TABLE


def _crc32c_update_py(crc: int, data: bytes) -> int:
    table = _py_table()
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def crc32c_update(crc: int, data: bytes) -> int:
    """Castagnoli CRC with Go ``crc32.Update`` chaining semantics."""
    lib = load()
    if lib is not None:
        return lib.smartbft_crc32c_update(crc, data, len(data))
    return _crc32c_update_py(crc, data)


def using_native() -> bool:
    return load() is not None


# ---------------------------------------------------------------------------
# WAL frame append
# ---------------------------------------------------------------------------

def wal_append(fd: int, payload: bytes, crc: int, update_crc: bool,
               do_sync: bool = True) -> Optional[tuple[int, int]]:
    """One-call frame append: pack + CRC + write + fdatasync.

    Returns (frame_size, new_crc) or None when the native library is
    unavailable (caller falls back to the Python path).  Raises OSError on
    an I/O failure, mirroring what the Python path would raise.
    """
    lib = load()
    if lib is None:
        return None
    crc_io = ctypes.c_uint32(crc)
    n = lib.smartbft_wal_append(
        fd, payload, len(payload), ctypes.byref(crc_io),
        1 if update_crc else 0, 1 if do_sync else 0,
    )
    if n < 0:
        raise OSError(ctypes.get_errno(), "wal: native append failed")
    return int(n), int(crc_io.value)
