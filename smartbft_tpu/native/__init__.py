"""Native (C++) runtime helpers, loaded via ctypes with Python fallbacks.

The reference is pure Go; the TPU-native rebuild keeps its runtime plane
(WAL framing, hashing) native where throughput demands it.  Libraries are
compiled on first import with ``g++`` into this directory and cached; any
build failure falls back to the pure-Python implementations so the framework
never hard-depends on a toolchain at runtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_NAME = "libsmartbft_native.so"
_SOURCES = ["crc32c.cc", "wal_frame.cc", "bls381.cc", "ed25519_fp.cc"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build_lib(lib_path: str) -> bool:
    srcs = [os.path.join(_DIR, s) for s in _SOURCES]
    if not all(os.path.exists(s) for s in srcs):
        return False
    tmp = lib_path + f".tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, *srcs]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, lib_path)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _stale(lib_path: str) -> bool:
    try:
        lib_mtime = os.path.getmtime(lib_path)
    except OSError:
        return True
    for s in _SOURCES:
        try:
            if os.path.getmtime(os.path.join(_DIR, s)) > lib_mtime:
                return True
        except OSError:
            pass  # source pruned from the deploy — the built lib stands
    return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None on failure."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:  # lock-free hot path
        return _lib
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("SMARTBFT_NO_NATIVE"):
            return None
        lib_path = os.path.join(_DIR, _LIB_NAME)
        if _stale(lib_path) and not _build_lib(lib_path):
            return None
        try:
            lib = ctypes.CDLL(lib_path, use_errno=True)
            lib.smartbft_crc32c_update.restype = ctypes.c_uint32
            lib.smartbft_crc32c_update.argtypes = [
                ctypes.c_uint32,
                ctypes.c_char_p,
                ctypes.c_size_t,
            ]
            lib.smartbft_wal_append.restype = ctypes.c_long
            lib.smartbft_wal_append.argtypes = [
                ctypes.c_int,
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_int,
                ctypes.c_int,
            ]
            buf = ctypes.c_char_p
            sz = ctypes.c_size_t
            for name in ("smartbft_bls_g1_mul", "smartbft_bls_g1_mul_glv",
                         "smartbft_bls_g2_mul"):
                # a prebuilt .so from an older source snapshot (the
                # source-pruned deploy _stale() supports) may lack newer
                # symbols — degrade just that entry point, never the
                # whole native plane
                try:
                    fn = getattr(lib, name)
                except AttributeError:
                    continue
                fn.restype = ctypes.c_int
                fn.argtypes = [buf, sz, buf, ctypes.c_char_p]
            for name in ("smartbft_bls_g1_sum", "smartbft_bls_g2_sum"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int
                fn.argtypes = [buf, sz, ctypes.c_char_p]
            try:
                lib.smartbft_ed_decompress.restype = ctypes.c_int
                lib.smartbft_ed_decompress.argtypes = [buf, ctypes.c_char_p]
            except AttributeError:
                pass  # older prebuilt .so: ed decompress degrades to Python
            _lib = lib
        except (OSError, AttributeError):
            _lib = None
        return _lib


# ---------------------------------------------------------------------------
# crc32c
# ---------------------------------------------------------------------------

_PY_TABLE: Optional[list[int]] = None


def _py_table() -> list[int]:
    global _PY_TABLE
    if _PY_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (poly if c & 1 else 0)
            table.append(c)
        _PY_TABLE = table
    return _PY_TABLE


def _crc32c_update_py(crc: int, data: bytes) -> int:
    table = _py_table()
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def crc32c_update(crc: int, data: bytes) -> int:
    """Castagnoli CRC with Go ``crc32.Update`` chaining semantics."""
    lib = load()
    if lib is not None:
        return lib.smartbft_crc32c_update(crc, data, len(data))
    return _crc32c_update_py(crc, data)


def using_native() -> bool:
    return load() is not None


# ---------------------------------------------------------------------------
# WAL frame append
# ---------------------------------------------------------------------------

def wal_append(fd: int, payload: bytes, crc: int, update_crc: bool,
               do_sync: bool = True) -> Optional[tuple[int, int]]:
    """One-call frame append: pack + CRC + write + fdatasync.

    Returns (frame_size, new_crc) or None when the native library is
    unavailable (caller falls back to the Python path).  Raises OSError on
    an I/O failure, mirroring what the Python path would raise.
    """
    lib = load()
    if lib is None:
        return None
    crc_io = ctypes.c_uint32(crc)
    n = lib.smartbft_wal_append(
        fd, payload, len(payload), ctypes.byref(crc_io),
        1 if update_crc else 0, 1 if do_sync else 0,
    )
    if n < 0:
        raise OSError(ctypes.get_errno(), "wal: native append failed")
    return int(n), int(crc_io.value)


# ---------------------------------------------------------------------------
# BLS12-381 group arithmetic (bls381.cc)
#
# Points cross the boundary as big-endian byte buffers: G1 affine = x||y
# (96B), G2 affine = x_c0||x_c1||y_c0||y_c1 (192B); infinity is rc=0.
# Python-side points use the same representation as crypto/bls12381.py:
# G1 = (x, y) ints, G2 = ((x0, x1), (y0, y1)), None = infinity.
# ---------------------------------------------------------------------------

def bls_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "smartbft_bls_g1_mul")


def _g1_bytes(pt) -> bytes:
    if pt is None:
        return b"\x00" * 96
    return pt[0].to_bytes(48, "big") + pt[1].to_bytes(48, "big")


def _g1_point(rc: int, out) -> Optional[tuple]:
    if rc == 0:
        return None
    raw = bytes(out)
    return (int.from_bytes(raw[:48], "big"), int.from_bytes(raw[48:96], "big"))


def _g2_bytes(pt) -> bytes:
    if pt is None:
        return b"\x00" * 192
    (x0, x1), (y0, y1) = pt
    return (x0.to_bytes(48, "big") + x1.to_bytes(48, "big")
            + y0.to_bytes(48, "big") + y1.to_bytes(48, "big"))


def _g2_point(rc: int, out) -> Optional[tuple]:
    if rc == 0:
        return None
    raw = bytes(out)
    c = [int.from_bytes(raw[i * 48:(i + 1) * 48], "big") for i in range(4)]
    return ((c[0], c[1]), (c[2], c[3]))


def bls_g1_mul(k: int, pt) -> Optional[tuple]:
    """k * P on G1 (affine ints); None = infinity.  k taken as given."""
    lib = load()
    scalar = k.to_bytes(max(1, (k.bit_length() + 7) // 8), "big")
    out = ctypes.create_string_buffer(96)
    rc = lib.smartbft_bls_g1_mul(scalar, len(scalar), _g1_bytes(pt), out)
    return _g1_point(rc, out.raw)


def bls_g1_mul_torsion(k: int, pt) -> Optional[tuple]:
    """GLV-accelerated k * P — ONLY for P in the r-torsion subgroup (e.g.
    a hash-to-curve output or a validated key).  The endomorphism identity
    phi(P) = lambda*P fails off the subgroup, so subgroup checks and
    cofactor clearing must call :func:`bls_g1_mul` instead.  Falls back to
    the generic ladder when the loaded library predates the GLV symbol."""
    lib = load()
    if not hasattr(lib, "smartbft_bls_g1_mul_glv"):
        return bls_g1_mul(k, pt)
    scalar = k.to_bytes(max(1, (k.bit_length() + 7) // 8), "big")
    out = ctypes.create_string_buffer(96)
    rc = lib.smartbft_bls_g1_mul_glv(scalar, len(scalar), _g1_bytes(pt), out)
    return _g1_point(rc, out.raw)


def bls_g1_sum(points) -> Optional[tuple]:
    lib = load()
    pts = [p for p in points if p is not None]
    if not pts:
        return None
    blob = b"".join(_g1_bytes(p) for p in pts)
    out = ctypes.create_string_buffer(96)
    rc = lib.smartbft_bls_g1_sum(blob, len(pts), out)
    return _g1_point(rc, out.raw)


def bls_g2_mul(k: int, pt) -> Optional[tuple]:
    lib = load()
    scalar = k.to_bytes(max(1, (k.bit_length() + 7) // 8), "big")
    out = ctypes.create_string_buffer(192)
    rc = lib.smartbft_bls_g2_mul(scalar, len(scalar), _g2_bytes(pt), out)
    return _g2_point(rc, out.raw)


def bls_g2_sum(points) -> Optional[tuple]:
    lib = load()
    pts = [p for p in points if p is not None]
    if not pts:
        return None
    blob = b"".join(_g2_bytes(p) for p in pts)
    out = ctypes.create_string_buffer(192)
    rc = lib.smartbft_bls_g2_sum(blob, len(pts), out)
    return _g2_point(rc, out.raw)


# ---------------------------------------------------------------------------
# Ed25519 point decompression (ed25519_fp.cc)
# ---------------------------------------------------------------------------

def ed_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "smartbft_ed_decompress")


def ed_decompress(comp: bytes) -> Optional[tuple]:
    """RFC 8032 decompression; (x, y) ints or None when invalid."""
    lib = load()
    out = ctypes.create_string_buffer(64)
    if lib.smartbft_ed_decompress(comp, out) == 0:
        return None
    raw = out.raw
    return (int.from_bytes(raw[:32], "little"),
            int.from_bytes(raw[32:], "little"))
