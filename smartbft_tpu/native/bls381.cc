// BLS12-381 host-side group arithmetic: G1/G2 scalar multiplication and
// affine sums, exposed as byte-buffer C functions for ctypes.
//
// Replaces the pure-Python-int hot paths of crypto/bls12381.py — signing
// (sk * H(m), ~20 ms in Python), same-message aggregation (quorum-1 point
// adds per check), cofactor clearing, and the r-torsion subgroup checks —
// with 64-bit-limb Montgomery arithmetic (~30-80 us per scalar mult).
// Verification-side math only: no constant-time discipline is attempted
// (the reference's crypto is an app plugin; side channels are the
// embedder's concern, as with Go's non-constant-time big.Int paths).
//
// Wire format: field elements are 48-byte big-endian; G1 points are
// x||y (96 bytes), G2 points are x_c0||x_c1||y_c0||y_c1 (192 bytes);
// infinity is returned as rc=0 with the output zeroed.

#include <cstdint>
#include <cstring>

using u64 = uint64_t;
using u128 = unsigned __int128;

namespace {

constexpr int NL = 6;  // 6 x 64-bit limbs, little-endian

// p = 0x1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaaab
constexpr u64 Pmod[NL] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL,
};
// -p^-1 mod 2^64
constexpr u64 PINV = 0x89f3fffcfffcfffdULL;
// R^2 mod p (R = 2^384)
constexpr u64 R2[NL] = {
    0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL, 0x8de5476c4c95b6d5ULL,
    0x67eb88a9939d83c0ULL, 0x9a793e85b519952dULL, 0x11988fe592cae3aaULL,
};

struct Fp {
    u64 v[NL];
};

bool fp_is_zero(const Fp &a) {
    u64 acc = 0;
    for (int i = 0; i < NL; i++) acc |= a.v[i];
    return acc == 0;
}

bool fp_eq(const Fp &a, const Fp &b) {
    u64 acc = 0;
    for (int i = 0; i < NL; i++) acc |= a.v[i] ^ b.v[i];
    return acc == 0;
}

// a += b with carry out
inline u64 add_limbs(u64 *a, const u64 *b) {
    u128 c = 0;
    for (int i = 0; i < NL; i++) {
        c += (u128)a[i] + b[i];
        a[i] = (u64)c;
        c >>= 64;
    }
    return (u64)c;
}

// a -= b with borrow out
inline u64 sub_limbs(u64 *a, const u64 *b) {
    u128 br = 0;
    for (int i = 0; i < NL; i++) {
        u128 t = (u128)a[i] - b[i] - br;
        a[i] = (u64)t;
        br = (t >> 64) & 1;
    }
    return (u64)br;
}

inline bool geq_p(const u64 *a) {
    for (int i = NL - 1; i >= 0; i--) {
        if (a[i] > Pmod[i]) return true;
        if (a[i] < Pmod[i]) return false;
    }
    return true;  // equal
}

Fp fp_add(const Fp &a, const Fp &b) {
    Fp r = a;
    u64 carry = add_limbs(r.v, b.v);
    if (carry || geq_p(r.v)) sub_limbs(r.v, Pmod);
    return r;
}

Fp fp_sub(const Fp &a, const Fp &b) {
    Fp r = a;
    if (sub_limbs(r.v, b.v)) add_limbs(r.v, Pmod);
    return r;
}

Fp fp_neg(const Fp &a) {
    if (fp_is_zero(a)) return a;
    Fp r;
    for (int i = 0; i < NL; i++) r.v[i] = Pmod[i];
    sub_limbs(r.v, a.v);
    return r;
}

// CIOS Montgomery multiplication with the "no-carry" optimization: because
// p's top limb (0x1a01..) is below 2^63 - 1, the per-iteration partial sums
// fit NL limbs plus two scalar carries (c0 from the product pass, c1 from
// the reduction pass) — no NL+2 tail bookkeeping.  ~25% faster than the
// classic CIOS here: the compiler keeps t[] and both carries in registers.
// PRECONDITION: both operands < p (the dropped tail carry is only provably
// zero then).  Every byte ingress reduces first (fp_from_bytes_be), and
// all internal arithmetic is closed over [0, p).
Fp fp_mul(const Fp &A, const Fp &B) {
    const u64 *a = A.v, *b = B.v;
    u64 t[NL];
    {
        u128 p = (u128)a[0] * b[0];
        t[0] = (u64)p;
        u64 c0 = (u64)(p >> 64);
        for (int j = 1; j < NL; j++) {
            p = (u128)a[0] * b[j] + c0;
            t[j] = (u64)p;
            c0 = (u64)(p >> 64);
        }
        u64 c2 = c0;
        u64 m = t[0] * PINV;
        p = (u128)m * Pmod[0] + t[0];
        u64 c1 = (u64)(p >> 64);
        for (int j = 1; j < NL; j++) {
            p = (u128)m * Pmod[j] + t[j] + c1;
            t[j - 1] = (u64)p;
            c1 = (u64)(p >> 64);
        }
        t[NL - 1] = c1 + c2;
    }
    for (int i = 1; i < NL; i++) {
        u128 p = (u128)a[i] * b[0] + t[0];
        t[0] = (u64)p;
        u64 c0 = (u64)(p >> 64);
        for (int j = 1; j < NL; j++) {
            p = (u128)a[i] * b[j] + t[j] + c0;
            t[j] = (u64)p;
            c0 = (u64)(p >> 64);
        }
        u64 c2 = c0;
        u64 m = t[0] * PINV;
        p = (u128)m * Pmod[0] + t[0];
        u64 c1 = (u64)(p >> 64);
        for (int j = 1; j < NL; j++) {
            p = (u128)m * Pmod[j] + t[j] + c1;
            t[j - 1] = (u64)p;
            c1 = (u64)(p >> 64);
        }
        t[NL - 1] = c1 + c2;
    }
    Fp r;
    for (int i = 0; i < NL; i++) r.v[i] = t[i];
    if (geq_p(r.v)) sub_limbs(r.v, Pmod);
    return r;
}

Fp fp_sqr(const Fp &a) { return fp_mul(a, a); }

Fp fp_from_bytes_be(const uint8_t *in) {
    Fp raw;
    for (int i = 0; i < NL; i++) {
        u64 v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | in[(NL - 1 - i) * 8 + j];
        raw.v[i] = v;
    }
    // Reduce non-canonical encodings (values in [p, 2^384)) BEFORE the
    // domain conversion: the no-carry fp_mul requires both operands < p
    // (its dropped tail carry is only provably zero then), so unreduced
    // bytes fed straight through would corrupt silently.  At most 2^384/p
    // ≈ 9.8 subtractions, and canonical inputs pay one compare.
    while (geq_p(raw.v)) sub_limbs(raw.v, Pmod);
    Fp r2;
    for (int i = 0; i < NL; i++) r2.v[i] = R2[i];
    return fp_mul(raw, r2);  // into Montgomery domain
}

void fp_to_bytes_be(const Fp &a, uint8_t *out) {
    Fp one;
    for (int i = 0; i < NL; i++) one.v[i] = 0;
    one.v[0] = 1;
    Fp std = fp_mul(a, one);  // out of Montgomery domain
    for (int i = 0; i < NL; i++) {
        u64 v = std.v[i];
        for (int j = 7; j >= 0; j--) {
            out[(NL - 1 - i) * 8 + (7 - j)] = (uint8_t)(v >> (8 * j));
        }
    }
}

// Binary extended GCD inversion: ~760 shift/add iterations on 6 limbs
// (~5 us) vs ~570 Montgomery multiplications for the Fermat ladder
// (~50 us).  Input and output both in the Montgomery domain.
// Not constant-time, like everything in this file (see header note).

inline bool limbs_is_zero(const u64 *a) {
    u64 acc = 0;
    for (int i = 0; i < NL; i++) acc |= a[i];
    return acc == 0;
}

inline bool limbs_lt(const u64 *a, const u64 *b) {
    for (int i = NL - 1; i >= 0; i--) {
        if (a[i] < b[i]) return true;
        if (a[i] > b[i]) return false;
    }
    return false;
}

inline void limbs_rshift1(u64 *a) {
    for (int i = 0; i < NL - 1; i++) a[i] = (a[i] >> 1) | (a[i + 1] << 63);
    a[NL - 1] >>= 1;
}

Fp fp_inv(const Fp &a) {
    // a is aR mod p; classic binary xgcd computes (aR)^-1 mod p, then two
    // Montgomery multiplications by R^2 lift it back to (a^-1)R.
    if (fp_is_zero(a)) return a;
    u64 u[NL], v[NL], b[NL] = {1, 0, 0, 0, 0, 0}, c[NL] = {0};
    for (int i = 0; i < NL; i++) {
        u[i] = a.v[i];
        v[i] = Pmod[i];
    }
    while (!limbs_is_zero(u)) {
        while (!(u[0] & 1)) {
            limbs_rshift1(u);
            if (b[0] & 1) add_limbs(b, Pmod);
            limbs_rshift1(b);
        }
        while (!(v[0] & 1)) {
            limbs_rshift1(v);
            if (c[0] & 1) add_limbs(c, Pmod);
            limbs_rshift1(c);
        }
        // on u == v (then necessarily u == v == gcd == 1) the subtraction
        // MUST land on u so the outer loop terminates: v -= u would zero v
        // and wedge the even-stripping loop on a value that never goes odd
        if (!limbs_lt(u, v)) {
            sub_limbs(u, v);
            if (sub_limbs(b, c)) add_limbs(b, Pmod);
        } else {
            sub_limbs(v, u);
            if (sub_limbs(c, b)) add_limbs(c, Pmod);
        }
    }
    // v == gcd == 1 (p prime, a != 0); c == (aR)^-1 mod p
    Fp inv_std;
    for (int i = 0; i < NL; i++) inv_std.v[i] = c[i];
    Fp r2;
    for (int i = 0; i < NL; i++) r2.v[i] = R2[i];
    return fp_mul(fp_mul(inv_std, r2), r2);
}

Fp fp_inv_fermat(const Fp &a) {
    // Fermat: a^(p-2).  Exponent p-2 processed MSB-first.
    u64 e[NL];
    for (int i = 0; i < NL; i++) e[i] = Pmod[i];
    e[0] -= 2;  // p is odd and > 2, no borrow
    Fp one;
    for (int i = 0; i < NL; i++) one.v[i] = 0;
    one.v[0] = 1;
    Fp r2;
    for (int i = 0; i < NL; i++) r2.v[i] = R2[i];
    Fp acc = fp_mul(one, r2);  // 1 in Montgomery form
    for (int i = NL - 1; i >= 0; i--) {
        for (int b = 63; b >= 0; b--) {
            acc = fp_sqr(acc);
            if ((e[i] >> b) & 1) acc = fp_mul(acc, a);
        }
    }
    return acc;
}

// ---------------- Fp2 = Fp[u]/(u^2+1) ----------------

struct Fp2 {
    Fp c0, c1;
};

bool fp2_is_zero(const Fp2 &a) { return fp_is_zero(a.c0) && fp_is_zero(a.c1); }
bool fp2_eq(const Fp2 &a, const Fp2 &b) {
    return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}
Fp2 fp2_add(const Fp2 &a, const Fp2 &b) {
    return {fp_add(a.c0, b.c0), fp_add(a.c1, b.c1)};
}
Fp2 fp2_sub(const Fp2 &a, const Fp2 &b) {
    return {fp_sub(a.c0, b.c0), fp_sub(a.c1, b.c1)};
}
Fp2 fp2_neg(const Fp2 &a) { return {fp_neg(a.c0), fp_neg(a.c1)}; }
Fp2 fp2_mul(const Fp2 &a, const Fp2 &b) {
    Fp t0 = fp_mul(a.c0, b.c0);
    Fp t1 = fp_mul(a.c1, b.c1);
    Fp s = fp_mul(fp_add(a.c0, a.c1), fp_add(b.c0, b.c1));
    return {fp_sub(t0, t1), fp_sub(fp_sub(s, t0), t1)};
}
Fp2 fp2_sqr(const Fp2 &a) {
    // complex squaring over u^2 = -1: (c0+c1u)^2 = (c0+c1)(c0-c1) + 2c0c1 u
    Fp t = fp_mul(a.c0, a.c1);
    return {fp_mul(fp_add(a.c0, a.c1), fp_sub(a.c0, a.c1)), fp_add(t, t)};
}
Fp2 fp2_inv(const Fp2 &a) {
    // 1/(c0 + c1 u) = (c0 - c1 u) / (c0^2 + c1^2)
    Fp d = fp_add(fp_sqr(a.c0), fp_sqr(a.c1));
    Fp di = fp_inv(d);
    return {fp_mul(a.c0, di), fp_neg(fp_mul(a.c1, di))};
}

// ---------------- generic Jacobian group ops -----------------------------
// Curve y^2 = x^3 + b with a = 0 (both G1 and G2).  F supplies field ops.

template <typename F>
struct Jac {
    typename F::El X, Y, Z;
    bool inf;
};

struct OpsFp {
    using El = Fp;
    static El add(const El &a, const El &b) { return fp_add(a, b); }
    static El sub(const El &a, const El &b) { return fp_sub(a, b); }
    static El mul(const El &a, const El &b) { return fp_mul(a, b); }
    static El sqr(const El &a) { return fp_sqr(a); }
    static El inv(const El &a) { return fp_inv(a); }
    static bool is_zero(const El &a) { return fp_is_zero(a); }
    static bool eq(const El &a, const El &b) { return fp_eq(a, b); }
    static El one() {
        Fp one;
        for (int i = 0; i < NL; i++) one.v[i] = 0;
        one.v[0] = 1;
        Fp r2;
        for (int i = 0; i < NL; i++) r2.v[i] = R2[i];
        return fp_mul(one, r2);
    }
};

struct OpsFp2 {
    using El = Fp2;
    static El add(const El &a, const El &b) { return fp2_add(a, b); }
    static El sub(const El &a, const El &b) { return fp2_sub(a, b); }
    static El mul(const El &a, const El &b) { return fp2_mul(a, b); }
    static El sqr(const El &a) { return fp2_sqr(a); }
    static El inv(const El &a) { return fp2_inv(a); }
    static bool is_zero(const El &a) { return fp2_is_zero(a); }
    static bool eq(const El &a, const El &b) { return fp2_eq(a, b); }
    static El one() { return {OpsFp::one(), Fp{{0, 0, 0, 0, 0, 0}}}; }
};

template <typename F>
Jac<F> jac_dbl(const Jac<F> &p) {
    if (p.inf || F::is_zero(p.Y)) return {p.X, p.Y, p.Z, true};
    // dbl-2009-l (a = 0)
    auto A = F::sqr(p.X);
    auto Bv = F::sqr(p.Y);
    auto C = F::sqr(Bv);
    auto t = F::sub(F::sub(F::sqr(F::add(p.X, Bv)), A), C);
    auto D = F::add(t, t);
    auto E = F::add(F::add(A, A), A);
    auto Fv = F::sqr(E);
    auto X3 = F::sub(Fv, F::add(D, D));
    auto C8 = F::add(F::add(F::add(C, C), F::add(C, C)),
                     F::add(F::add(C, C), F::add(C, C)));
    auto Y3 = F::sub(F::mul(E, F::sub(D, X3)), C8);
    auto Z3 = F::mul(F::add(p.Y, p.Y), p.Z);
    return {X3, Y3, Z3, false};
}

template <typename F>
Jac<F> jac_add(const Jac<F> &p, const Jac<F> &q) {
    if (p.inf) return q;
    if (q.inf) return p;
    auto Z1Z1 = F::sqr(p.Z);
    auto Z2Z2 = F::sqr(q.Z);
    auto U1 = F::mul(p.X, Z2Z2);
    auto U2 = F::mul(q.X, Z1Z1);
    auto S1 = F::mul(F::mul(p.Y, q.Z), Z2Z2);
    auto S2 = F::mul(F::mul(q.Y, p.Z), Z1Z1);
    auto H = F::sub(U2, U1);
    auto r = F::sub(S2, S1);
    if (F::is_zero(H)) {
        if (F::is_zero(r)) return jac_dbl(p);
        return {p.X, p.Y, p.Z, true};  // P + (-P) = inf
    }
    auto H2 = F::sqr(H);
    auto H3 = F::mul(H2, H);
    auto U1H2 = F::mul(U1, H2);
    auto X3 = F::sub(F::sub(F::sqr(r), H3), F::add(U1H2, U1H2));
    auto Y3 = F::sub(F::mul(r, F::sub(U1H2, X3)), F::mul(S1, H3));
    auto Z3 = F::mul(F::mul(p.Z, q.Z), H);
    return {X3, Y3, Z3, false};
}

template <typename F>
Jac<F> jac_mul(const uint8_t *scalar, size_t slen, const Jac<F> &p) {
    // 4-bit fixed window, nibbles MSB-first: 14 table adds + (4 dbl +
    // <=1 add) per nibble — ~28% fewer point ops than double-and-add.
    Jac<F> table[16];
    table[0] = {p.X, p.Y, p.Z, true};
    table[1] = p;
    for (int i = 2; i < 16; i++) table[i] = jac_add(table[i - 1], p);
    Jac<F> acc = table[0];
    for (size_t i = 0; i < slen; i++) {
        uint8_t byte = scalar[i];  // big-endian: MSB first
        for (int half = 0; half < 2; half++) {
            for (int d = 0; d < 4; d++) acc = jac_dbl(acc);
            uint8_t nib = half == 0 ? (byte >> 4) : (byte & 0xF);
            if (nib) acc = jac_add(acc, table[nib]);
        }
    }
    return acc;
}

// ---------------- G1 GLV multiplication --------------------------------
//
// The curve has the efficient endomorphism phi(x, y) = (beta*x, y) with
// phi(P) = lambda*P for P in the r-torsion, where lambda = z^2 - 1
// satisfies lambda^2 + lambda + 1 = r exactly.  A scalar k < r splits as
// k = k1 + k2*lambda with both halves <= 128 bits (k2 = floor(k*MU/2^256)
// with MU = floor(2^256/lambda), then a <=2-step correction), so the
// double-and-add ladder runs 128 doublings instead of 255.  Each half
// walks width-5 wNAF digits against an odd-multiple table normalized to
// affine with ONE batch inversion; phi maps the table for free (scale X
// by beta).  ONLY valid for r-torsion points — subgroup checks and
// cofactor clearing must keep using the generic ladder.

constexpr u64 LAM[2] = {0x00000000ffffffffULL, 0xac45a4010001a402ULL};
constexpr u64 MU[3] = {0x63f6e522f6cfee30ULL, 0x7c6becf1e01faaddULL, 0x1ULL};
// beta (Montgomery form computed at first use)
constexpr u64 BETA_STD[NL] = {
    0x8bfd00000000aaacULL, 0x409427eb4f49fffdULL, 0x897d29650fb85f9bULL,
    0xaa0d857d89759ad4ULL, 0xec02408663d4de85ULL, 0x1a0111ea397fe699ULL,
};

// k (<= 4 limbs, little-endian) -> (k1, k2), both <= 129 bits.
void glv_split(const u64 k[4], u64 k1[3], u64 k2[3]) {
    // k2 = (k * MU) >> 256
    u64 prod[7] = {0};
    for (int i = 0; i < 4; i++) {
        u128 c = 0;
        for (int j = 0; j < 3; j++) {
            c += (u128)prod[i + j] + (u128)k[i] * MU[j];
            prod[i + j] = (u64)c;
            c >>= 64;
        }
        prod[i + 3] += (u64)c;
    }
    for (int i = 0; i < 3; i++) k2[i] = prod[4 + i];
    // k1 = k - k2 * LAM  (fits 4 limbs; result < lambda after correction)
    u64 t[5] = {0};
    for (int i = 0; i < 3; i++) {
        u128 c = 0;
        for (int j = 0; j < 2; j++) {
            c += (u128)t[i + j] + (u128)k2[i] * LAM[j];
            t[i + j] = (u64)c;
            c >>= 64;
        }
        t[i + 2] += (u64)c;
    }
    u64 r1[4];
    u128 br = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)k[i] - t[i] - br;
        r1[i] = (u64)d;
        br = (d >> 64) & 1;
    }
    // correction: while k1 >= lambda { k1 -= lambda; k2 += 1 }
    auto ge_lam = [&]() {
        if (r1[3] | r1[2]) return true;
        if (r1[1] != LAM[1]) return r1[1] > LAM[1];
        return r1[0] >= LAM[0];
    };
    while (ge_lam()) {
        u128 d = (u128)r1[0] - LAM[0];
        r1[0] = (u64)d;
        u128 b2 = (d >> 64) & 1;
        d = (u128)r1[1] - LAM[1] - b2;
        r1[1] = (u64)d;
        b2 = (d >> 64) & 1;
        d = (u128)r1[2] - b2;
        r1[2] = (u64)d;
        r1[3] -= (u64)((d >> 64) & 1);
        u128 c = (u128)k2[0] + 1;
        k2[0] = (u64)c;
        if (c >> 64) {
            c = (u128)k2[1] + 1;
            k2[1] = (u64)c;
            k2[2] += (u64)(c >> 64);
        }
    }
    for (int i = 0; i < 3; i++) k1[i] = r1[i];
}

// width-5 wNAF: odd digits in [-15, 15], ~1/6 density.  digits[i] is the
// coefficient of 2^i; returns the digit count (caller scans len-1 .. 0).
int wnaf5(const u64 k_in[3], int8_t *digits, int cap) {
    u64 k[3] = {k_in[0], k_in[1], k_in[2]};
    int len = 0;
    while (k[0] | k[1] | k[2]) {
        int8_t d = 0;
        if (k[0] & 1) {
            int v = (int)(k[0] & 31);
            d = (int8_t)(v > 16 ? v - 32 : v);
            // k -= d
            if (d > 0) {
                u128 br = 0;
                u64 dv = (u64)d;
                u128 t = (u128)k[0] - dv;
                k[0] = (u64)t;
                br = (t >> 64) & 1;
                for (int i = 1; br && i < 3; i++) {
                    t = (u128)k[i] - br;
                    k[i] = (u64)t;
                    br = (t >> 64) & 1;
                }
            } else {
                u128 c = (u128)k[0] + (u64)(-d);
                k[0] = (u64)c;
                for (int i = 1; (c >>= 64) && i < 3; i++) {
                    c += k[i];
                    k[i] = (u64)c;
                }
            }
        }
        digits[len++] = d;
        if (len >= cap) break;
        k[0] = (k[0] >> 1) | (k[1] << 63);
        k[1] = (k[1] >> 1) | (k[2] << 63);
        k[2] >>= 1;
    }
    return len;
}

struct AffG1 {
    Fp x, y;
    bool inf;
};

// mixed Jacobian + affine addition (Z2 = 1): 8M + 3S
Jac<OpsFp> jac_madd(const Jac<OpsFp> &p, const AffG1 &q) {
    if (q.inf) return p;
    if (p.inf) return {q.x, q.y, OpsFp::one(), false};
    Fp z1z1 = fp_sqr(p.Z);
    Fp u2 = fp_mul(q.x, z1z1);
    Fp s2 = fp_mul(fp_mul(q.y, p.Z), z1z1);
    Fp h = fp_sub(u2, p.X);
    Fp rr = fp_sub(s2, p.Y);
    if (fp_is_zero(h)) {
        if (fp_is_zero(rr)) return jac_dbl(p);
        return {p.X, p.Y, p.Z, true};
    }
    Fp h2 = fp_sqr(h);
    Fp h3 = fp_mul(h, h2);
    Fp v = fp_mul(p.X, h2);
    Fp x3 = fp_sub(fp_sub(fp_sqr(rr), h3), fp_add(v, v));
    Fp y3 = fp_sub(fp_mul(rr, fp_sub(v, x3)), fp_mul(p.Y, h3));
    Fp z3 = fp_mul(p.Z, h);
    return {x3, y3, z3, false};
}

// normalize 8 Jacobian points to affine with ONE inversion (Montgomery's
// batch trick: prefix products, single xgcd, unwind).
void batch_to_affine(const Jac<OpsFp> *pts, AffG1 *out, int n) {
    Fp acc = OpsFp::one();
    Fp prefix[16];
    for (int i = 0; i < n; i++) {
        prefix[i] = acc;
        if (!pts[i].inf) acc = fp_mul(acc, pts[i].Z);
    }
    Fp inv = fp_inv(acc);
    for (int i = n - 1; i >= 0; i--) {
        if (pts[i].inf) {
            out[i].inf = true;
            continue;
        }
        Fp zi = fp_mul(inv, prefix[i]);
        inv = fp_mul(inv, pts[i].Z);
        Fp zi2 = fp_sqr(zi);
        out[i].x = fp_mul(pts[i].X, zi2);
        out[i].y = fp_mul(pts[i].Y, fp_mul(zi2, zi));
        out[i].inf = false;
    }
}

// k * P for P in the r-torsion, k < 2^255 (4 limbs little-endian).
Jac<OpsFp> jac_mul_glv(const u64 k[4], const Jac<OpsFp> &p) {
    Jac<OpsFp> nothing = {p.X, p.Y, p.Z, true};
    if (p.inf) return nothing;
    u64 k1[3], k2[3];
    glv_split(k, k1, k2);

    // odd multiples 1P, 3P, ..., 15P (Jacobian), then one batch inversion
    Jac<OpsFp> tj[8];
    tj[0] = p;
    Jac<OpsFp> p2 = jac_dbl(p);
    for (int i = 1; i < 8; i++) tj[i] = jac_add(tj[i - 1], p2);
    AffG1 tp[8], tphi[8];
    batch_to_affine(tj, tp, 8);
    // phi table: x *= beta (beta in Montgomery form)
    Fp beta_std, r2;
    for (int i = 0; i < NL; i++) {
        beta_std.v[i] = BETA_STD[i];
        r2.v[i] = R2[i];
    }
    Fp beta_m = fp_mul(beta_std, r2);
    for (int i = 0; i < 8; i++) {
        tphi[i] = tp[i];
        if (!tp[i].inf) tphi[i].x = fp_mul(tp[i].x, beta_m);
    }

    int8_t d1[132], d2[132];
    int l1 = wnaf5(k1, d1, 132);
    int l2 = wnaf5(k2, d2, 132);
    int len = l1 > l2 ? l1 : l2;
    Jac<OpsFp> acc = nothing;
    for (int i = len - 1; i >= 0; i--) {
        acc = jac_dbl(acc);
        if (i < l1 && d1[i]) {
            AffG1 q = tp[(d1[i] > 0 ? d1[i] : -d1[i]) >> 1];
            if (d1[i] < 0) q.y = fp_neg(q.y);
            acc = jac_madd(acc, q);
        }
        if (i < l2 && d2[i]) {
            AffG1 q = tphi[(d2[i] > 0 ? d2[i] : -d2[i]) >> 1];
            if (d2[i] < 0) q.y = fp_neg(q.y);
            acc = jac_madd(acc, q);
        }
    }
    return acc;
}

template <typename F>
bool jac_to_affine(const Jac<F> &p, typename F::El &x, typename F::El &y) {
    if (p.inf || F::is_zero(p.Z)) return false;
    auto zi = F::inv(p.Z);
    auto zi2 = F::sqr(zi);
    x = F::mul(p.X, zi2);
    y = F::mul(p.Y, F::mul(zi2, zi));
    return true;
}

// -------- byte-interface helpers --------

Jac<OpsFp> g1_from_bytes(const uint8_t *xy96) {
    Jac<OpsFp> p;
    p.X = fp_from_bytes_be(xy96);
    p.Y = fp_from_bytes_be(xy96 + 48);
    p.Z = OpsFp::one();
    p.inf = fp_is_zero(p.X) && fp_is_zero(p.Y);
    return p;
}

int g1_to_bytes(const Jac<OpsFp> &p, uint8_t *out96) {
    Fp x, y;
    if (!jac_to_affine<OpsFp>(p, x, y)) {
        memset(out96, 0, 96);
        return 0;
    }
    fp_to_bytes_be(x, out96);
    fp_to_bytes_be(y, out96 + 48);
    return 1;
}

Jac<OpsFp2> g2_from_bytes(const uint8_t *b192) {
    Jac<OpsFp2> p;
    p.X = {fp_from_bytes_be(b192), fp_from_bytes_be(b192 + 48)};
    p.Y = {fp_from_bytes_be(b192 + 96), fp_from_bytes_be(b192 + 144)};
    p.Z = OpsFp2::one();
    p.inf = fp2_is_zero(p.X) && fp2_is_zero(p.Y);
    return p;
}

int g2_to_bytes(const Jac<OpsFp2> &p, uint8_t *out192) {
    Fp2 x, y;
    if (!jac_to_affine<OpsFp2>(p, x, y)) {
        memset(out192, 0, 192);
        return 0;
    }
    fp_to_bytes_be(x.c0, out192);
    fp_to_bytes_be(x.c1, out192 + 48);
    fp_to_bytes_be(y.c0, out192 + 96);
    fp_to_bytes_be(y.c1, out192 + 144);
    return 1;
}

}  // namespace

extern "C" {

// k * P for affine G1 P; returns 1, or 0 when the result is infinity.
int smartbft_bls_g1_mul(const uint8_t *scalar, size_t slen,
                        const uint8_t *xy96, uint8_t *out96) {
    Jac<OpsFp> p = g1_from_bytes(xy96);
    return g1_to_bytes(jac_mul<OpsFp>(scalar, slen, p), out96);
}

// GLV-accelerated k * P — ONLY for P already known to lie in the r-torsion
// (signing against a hash-to-curve output, multiplying a validated public
// key).  Subgroup checks and cofactor clearing MUST use smartbft_bls_g1_mul:
// phi(P) = lambda*P does not hold off the subgroup, which is exactly what
// those callers are probing.  Falls back to the generic ladder for scalars
// longer than 32 bytes.
int smartbft_bls_g1_mul_glv(const uint8_t *scalar, size_t slen,
                            const uint8_t *xy96, uint8_t *out96) {
    Jac<OpsFp> p = g1_from_bytes(xy96);
    if (slen > 32) return g1_to_bytes(jac_mul<OpsFp>(scalar, slen, p), out96);
    u64 k[4] = {0, 0, 0, 0};
    for (size_t i = 0; i < slen; i++) {
        k[(slen - 1 - i) / 8] |= (u64)scalar[i] << (8 * ((slen - 1 - i) % 8));
    }
    return g1_to_bytes(jac_mul_glv(k, p), out96);
}

// Sum of n affine G1 points (each 96 bytes); rc as above.
int smartbft_bls_g1_sum(const uint8_t *pts, size_t n, uint8_t *out96) {
    Jac<OpsFp> acc;
    acc.inf = true;
    acc.Z = OpsFp::one();
    acc.X = acc.Y = acc.Z;
    for (size_t i = 0; i < n; i++) {
        acc = jac_add(acc, g1_from_bytes(pts + 96 * i));
    }
    return g1_to_bytes(acc, out96);
}

int smartbft_bls_g2_mul(const uint8_t *scalar, size_t slen,
                        const uint8_t *b192, uint8_t *out192) {
    Jac<OpsFp2> p = g2_from_bytes(b192);
    return g2_to_bytes(jac_mul<OpsFp2>(scalar, slen, p), out192);
}

int smartbft_bls_g2_sum(const uint8_t *pts, size_t n, uint8_t *out192) {
    Jac<OpsFp2> acc;
    acc.inf = true;
    acc.Z = OpsFp2::one();
    acc.X = acc.Y = acc.Z;
    for (size_t i = 0; i < n; i++) {
        acc = jac_add(acc, g2_from_bytes(pts + 192 * i));
    }
    return g2_to_bytes(acc, out192);
}

}  // extern "C"
