// BLS12-381 host-side group arithmetic: G1/G2 scalar multiplication and
// affine sums, exposed as byte-buffer C functions for ctypes.
//
// Replaces the pure-Python-int hot paths of crypto/bls12381.py — signing
// (sk * H(m), ~20 ms in Python), same-message aggregation (quorum-1 point
// adds per check), cofactor clearing, and the r-torsion subgroup checks —
// with 64-bit-limb Montgomery arithmetic (~30-80 us per scalar mult).
// Verification-side math only: no constant-time discipline is attempted
// (the reference's crypto is an app plugin; side channels are the
// embedder's concern, as with Go's non-constant-time big.Int paths).
//
// Wire format: field elements are 48-byte big-endian; G1 points are
// x||y (96 bytes), G2 points are x_c0||x_c1||y_c0||y_c1 (192 bytes);
// infinity is returned as rc=0 with the output zeroed.

#include <cstdint>
#include <cstring>

using u64 = uint64_t;
using u128 = unsigned __int128;

namespace {

constexpr int NL = 6;  // 6 x 64-bit limbs, little-endian

// p = 0x1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaaab
constexpr u64 Pmod[NL] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL,
};
// -p^-1 mod 2^64
constexpr u64 PINV = 0x89f3fffcfffcfffdULL;
// R^2 mod p (R = 2^384)
constexpr u64 R2[NL] = {
    0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL, 0x8de5476c4c95b6d5ULL,
    0x67eb88a9939d83c0ULL, 0x9a793e85b519952dULL, 0x11988fe592cae3aaULL,
};

struct Fp {
    u64 v[NL];
};

bool fp_is_zero(const Fp &a) {
    u64 acc = 0;
    for (int i = 0; i < NL; i++) acc |= a.v[i];
    return acc == 0;
}

bool fp_eq(const Fp &a, const Fp &b) {
    u64 acc = 0;
    for (int i = 0; i < NL; i++) acc |= a.v[i] ^ b.v[i];
    return acc == 0;
}

// a += b with carry out
inline u64 add_limbs(u64 *a, const u64 *b) {
    u128 c = 0;
    for (int i = 0; i < NL; i++) {
        c += (u128)a[i] + b[i];
        a[i] = (u64)c;
        c >>= 64;
    }
    return (u64)c;
}

// a -= b with borrow out
inline u64 sub_limbs(u64 *a, const u64 *b) {
    u128 br = 0;
    for (int i = 0; i < NL; i++) {
        u128 t = (u128)a[i] - b[i] - br;
        a[i] = (u64)t;
        br = (t >> 64) & 1;
    }
    return (u64)br;
}

inline bool geq_p(const u64 *a) {
    for (int i = NL - 1; i >= 0; i--) {
        if (a[i] > Pmod[i]) return true;
        if (a[i] < Pmod[i]) return false;
    }
    return true;  // equal
}

Fp fp_add(const Fp &a, const Fp &b) {
    Fp r = a;
    u64 carry = add_limbs(r.v, b.v);
    if (carry || geq_p(r.v)) sub_limbs(r.v, Pmod);
    return r;
}

Fp fp_sub(const Fp &a, const Fp &b) {
    Fp r = a;
    if (sub_limbs(r.v, b.v)) add_limbs(r.v, Pmod);
    return r;
}

Fp fp_neg(const Fp &a) {
    if (fp_is_zero(a)) return a;
    Fp r;
    for (int i = 0; i < NL; i++) r.v[i] = Pmod[i];
    sub_limbs(r.v, a.v);
    return r;
}

// CIOS Montgomery multiplication
Fp fp_mul(const Fp &a, const Fp &b) {
    u64 t[NL + 2] = {0};
    for (int i = 0; i < NL; i++) {
        u128 c = 0;
        for (int j = 0; j < NL; j++) {
            c += (u128)t[j] + (u128)a.v[i] * b.v[j];
            t[j] = (u64)c;
            c >>= 64;
        }
        c += t[NL];
        t[NL] = (u64)c;
        t[NL + 1] = (u64)(c >> 64);
        u64 m = t[0] * PINV;
        c = (u128)t[0] + (u128)m * Pmod[0];
        c >>= 64;
        for (int j = 1; j < NL; j++) {
            c += (u128)t[j] + (u128)m * Pmod[j];
            t[j - 1] = (u64)c;
            c >>= 64;
        }
        c += t[NL];
        t[NL - 1] = (u64)c;
        t[NL] = t[NL + 1] + (u64)(c >> 64);
    }
    Fp r;
    for (int i = 0; i < NL; i++) r.v[i] = t[i];
    if (t[NL] || geq_p(r.v)) sub_limbs(r.v, Pmod);
    return r;
}

Fp fp_sqr(const Fp &a) { return fp_mul(a, a); }

Fp fp_from_bytes_be(const uint8_t *in) {
    Fp raw;
    for (int i = 0; i < NL; i++) {
        u64 v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | in[(NL - 1 - i) * 8 + j];
        raw.v[i] = v;
    }
    Fp r2;
    for (int i = 0; i < NL; i++) r2.v[i] = R2[i];
    return fp_mul(raw, r2);  // into Montgomery domain
}

void fp_to_bytes_be(const Fp &a, uint8_t *out) {
    Fp one;
    for (int i = 0; i < NL; i++) one.v[i] = 0;
    one.v[0] = 1;
    Fp std = fp_mul(a, one);  // out of Montgomery domain
    for (int i = 0; i < NL; i++) {
        u64 v = std.v[i];
        for (int j = 7; j >= 0; j--) {
            out[(NL - 1 - i) * 8 + (7 - j)] = (uint8_t)(v >> (8 * j));
        }
    }
}

Fp fp_inv(const Fp &a) {
    // Fermat: a^(p-2).  Exponent p-2 processed MSB-first.
    u64 e[NL];
    for (int i = 0; i < NL; i++) e[i] = Pmod[i];
    e[0] -= 2;  // p is odd and > 2, no borrow
    Fp one;
    for (int i = 0; i < NL; i++) one.v[i] = 0;
    one.v[0] = 1;
    Fp r2;
    for (int i = 0; i < NL; i++) r2.v[i] = R2[i];
    Fp acc = fp_mul(one, r2);  // 1 in Montgomery form
    for (int i = NL - 1; i >= 0; i--) {
        for (int b = 63; b >= 0; b--) {
            acc = fp_sqr(acc);
            if ((e[i] >> b) & 1) acc = fp_mul(acc, a);
        }
    }
    return acc;
}

// ---------------- Fp2 = Fp[u]/(u^2+1) ----------------

struct Fp2 {
    Fp c0, c1;
};

bool fp2_is_zero(const Fp2 &a) { return fp_is_zero(a.c0) && fp_is_zero(a.c1); }
bool fp2_eq(const Fp2 &a, const Fp2 &b) {
    return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}
Fp2 fp2_add(const Fp2 &a, const Fp2 &b) {
    return {fp_add(a.c0, b.c0), fp_add(a.c1, b.c1)};
}
Fp2 fp2_sub(const Fp2 &a, const Fp2 &b) {
    return {fp_sub(a.c0, b.c0), fp_sub(a.c1, b.c1)};
}
Fp2 fp2_neg(const Fp2 &a) { return {fp_neg(a.c0), fp_neg(a.c1)}; }
Fp2 fp2_mul(const Fp2 &a, const Fp2 &b) {
    Fp t0 = fp_mul(a.c0, b.c0);
    Fp t1 = fp_mul(a.c1, b.c1);
    Fp s = fp_mul(fp_add(a.c0, a.c1), fp_add(b.c0, b.c1));
    return {fp_sub(t0, t1), fp_sub(fp_sub(s, t0), t1)};
}
Fp2 fp2_sqr(const Fp2 &a) { return fp2_mul(a, a); }
Fp2 fp2_inv(const Fp2 &a) {
    // 1/(c0 + c1 u) = (c0 - c1 u) / (c0^2 + c1^2)
    Fp d = fp_add(fp_sqr(a.c0), fp_sqr(a.c1));
    Fp di = fp_inv(d);
    return {fp_mul(a.c0, di), fp_neg(fp_mul(a.c1, di))};
}

// ---------------- generic Jacobian group ops -----------------------------
// Curve y^2 = x^3 + b with a = 0 (both G1 and G2).  F supplies field ops.

template <typename F>
struct Jac {
    typename F::El X, Y, Z;
    bool inf;
};

struct OpsFp {
    using El = Fp;
    static El add(const El &a, const El &b) { return fp_add(a, b); }
    static El sub(const El &a, const El &b) { return fp_sub(a, b); }
    static El mul(const El &a, const El &b) { return fp_mul(a, b); }
    static El sqr(const El &a) { return fp_sqr(a); }
    static El inv(const El &a) { return fp_inv(a); }
    static bool is_zero(const El &a) { return fp_is_zero(a); }
    static bool eq(const El &a, const El &b) { return fp_eq(a, b); }
    static El one() {
        Fp one;
        for (int i = 0; i < NL; i++) one.v[i] = 0;
        one.v[0] = 1;
        Fp r2;
        for (int i = 0; i < NL; i++) r2.v[i] = R2[i];
        return fp_mul(one, r2);
    }
};

struct OpsFp2 {
    using El = Fp2;
    static El add(const El &a, const El &b) { return fp2_add(a, b); }
    static El sub(const El &a, const El &b) { return fp2_sub(a, b); }
    static El mul(const El &a, const El &b) { return fp2_mul(a, b); }
    static El sqr(const El &a) { return fp2_sqr(a); }
    static El inv(const El &a) { return fp2_inv(a); }
    static bool is_zero(const El &a) { return fp2_is_zero(a); }
    static bool eq(const El &a, const El &b) { return fp2_eq(a, b); }
    static El one() { return {OpsFp::one(), Fp{{0, 0, 0, 0, 0, 0}}}; }
};

template <typename F>
Jac<F> jac_dbl(const Jac<F> &p) {
    if (p.inf || F::is_zero(p.Y)) return {p.X, p.Y, p.Z, true};
    // dbl-2009-l (a = 0)
    auto A = F::sqr(p.X);
    auto Bv = F::sqr(p.Y);
    auto C = F::sqr(Bv);
    auto t = F::sub(F::sub(F::sqr(F::add(p.X, Bv)), A), C);
    auto D = F::add(t, t);
    auto E = F::add(F::add(A, A), A);
    auto Fv = F::sqr(E);
    auto X3 = F::sub(Fv, F::add(D, D));
    auto C8 = F::add(F::add(F::add(C, C), F::add(C, C)),
                     F::add(F::add(C, C), F::add(C, C)));
    auto Y3 = F::sub(F::mul(E, F::sub(D, X3)), C8);
    auto Z3 = F::mul(F::add(p.Y, p.Y), p.Z);
    return {X3, Y3, Z3, false};
}

template <typename F>
Jac<F> jac_add(const Jac<F> &p, const Jac<F> &q) {
    if (p.inf) return q;
    if (q.inf) return p;
    auto Z1Z1 = F::sqr(p.Z);
    auto Z2Z2 = F::sqr(q.Z);
    auto U1 = F::mul(p.X, Z2Z2);
    auto U2 = F::mul(q.X, Z1Z1);
    auto S1 = F::mul(F::mul(p.Y, q.Z), Z2Z2);
    auto S2 = F::mul(F::mul(q.Y, p.Z), Z1Z1);
    auto H = F::sub(U2, U1);
    auto r = F::sub(S2, S1);
    if (F::is_zero(H)) {
        if (F::is_zero(r)) return jac_dbl(p);
        return {p.X, p.Y, p.Z, true};  // P + (-P) = inf
    }
    auto H2 = F::sqr(H);
    auto H3 = F::mul(H2, H);
    auto U1H2 = F::mul(U1, H2);
    auto X3 = F::sub(F::sub(F::sqr(r), H3), F::add(U1H2, U1H2));
    auto Y3 = F::sub(F::mul(r, F::sub(U1H2, X3)), F::mul(S1, H3));
    auto Z3 = F::mul(F::mul(p.Z, q.Z), H);
    return {X3, Y3, Z3, false};
}

template <typename F>
Jac<F> jac_mul(const uint8_t *scalar, size_t slen, const Jac<F> &p) {
    // 4-bit fixed window, nibbles MSB-first: 14 table adds + (4 dbl +
    // <=1 add) per nibble — ~28% fewer point ops than double-and-add.
    Jac<F> table[16];
    table[0] = {p.X, p.Y, p.Z, true};
    table[1] = p;
    for (int i = 2; i < 16; i++) table[i] = jac_add(table[i - 1], p);
    Jac<F> acc = table[0];
    for (size_t i = 0; i < slen; i++) {
        uint8_t byte = scalar[i];  // big-endian: MSB first
        for (int half = 0; half < 2; half++) {
            for (int d = 0; d < 4; d++) acc = jac_dbl(acc);
            uint8_t nib = half == 0 ? (byte >> 4) : (byte & 0xF);
            if (nib) acc = jac_add(acc, table[nib]);
        }
    }
    return acc;
}

template <typename F>
bool jac_to_affine(const Jac<F> &p, typename F::El &x, typename F::El &y) {
    if (p.inf || F::is_zero(p.Z)) return false;
    auto zi = F::inv(p.Z);
    auto zi2 = F::sqr(zi);
    x = F::mul(p.X, zi2);
    y = F::mul(p.Y, F::mul(zi2, zi));
    return true;
}

// -------- byte-interface helpers --------

Jac<OpsFp> g1_from_bytes(const uint8_t *xy96) {
    Jac<OpsFp> p;
    p.X = fp_from_bytes_be(xy96);
    p.Y = fp_from_bytes_be(xy96 + 48);
    p.Z = OpsFp::one();
    p.inf = fp_is_zero(p.X) && fp_is_zero(p.Y);
    return p;
}

int g1_to_bytes(const Jac<OpsFp> &p, uint8_t *out96) {
    Fp x, y;
    if (!jac_to_affine<OpsFp>(p, x, y)) {
        memset(out96, 0, 96);
        return 0;
    }
    fp_to_bytes_be(x, out96);
    fp_to_bytes_be(y, out96 + 48);
    return 1;
}

Jac<OpsFp2> g2_from_bytes(const uint8_t *b192) {
    Jac<OpsFp2> p;
    p.X = {fp_from_bytes_be(b192), fp_from_bytes_be(b192 + 48)};
    p.Y = {fp_from_bytes_be(b192 + 96), fp_from_bytes_be(b192 + 144)};
    p.Z = OpsFp2::one();
    p.inf = fp2_is_zero(p.X) && fp2_is_zero(p.Y);
    return p;
}

int g2_to_bytes(const Jac<OpsFp2> &p, uint8_t *out192) {
    Fp2 x, y;
    if (!jac_to_affine<OpsFp2>(p, x, y)) {
        memset(out192, 0, 192);
        return 0;
    }
    fp_to_bytes_be(x.c0, out192);
    fp_to_bytes_be(x.c1, out192 + 48);
    fp_to_bytes_be(y.c0, out192 + 96);
    fp_to_bytes_be(y.c1, out192 + 144);
    return 1;
}

}  // namespace

extern "C" {

// k * P for affine G1 P; returns 1, or 0 when the result is infinity.
int smartbft_bls_g1_mul(const uint8_t *scalar, size_t slen,
                        const uint8_t *xy96, uint8_t *out96) {
    Jac<OpsFp> p = g1_from_bytes(xy96);
    return g1_to_bytes(jac_mul<OpsFp>(scalar, slen, p), out96);
}

// Sum of n affine G1 points (each 96 bytes); rc as above.
int smartbft_bls_g1_sum(const uint8_t *pts, size_t n, uint8_t *out96) {
    Jac<OpsFp> acc;
    acc.inf = true;
    acc.Z = OpsFp::one();
    acc.X = acc.Y = acc.Z;
    for (size_t i = 0; i < n; i++) {
        acc = jac_add(acc, g1_from_bytes(pts + 96 * i));
    }
    return g1_to_bytes(acc, out96);
}

int smartbft_bls_g2_mul(const uint8_t *scalar, size_t slen,
                        const uint8_t *b192, uint8_t *out192) {
    Jac<OpsFp2> p = g2_from_bytes(b192);
    return g2_to_bytes(jac_mul<OpsFp2>(scalar, slen, p), out192);
}

int smartbft_bls_g2_sum(const uint8_t *pts, size_t n, uint8_t *out192) {
    Jac<OpsFp2> acc;
    acc.inf = true;
    acc.Z = OpsFp2::one();
    acc.X = acc.Y = acc.Z;
    for (size_t i = 0; i < n; i++) {
        acc = jac_add(acc, g2_from_bytes(pts + 192 * i));
    }
    return g2_to_bytes(acc, out192);
}

}  // extern "C"
