// CRC32-Castagnoli, slicing-by-8, with Go hash/crc32.Update semantics
// (xor-in / xor-out around the table chain).  This is the WAL framing
// checksum hot path (reference: /root/reference/pkg/wal/writeaheadlog.go:454,
// hash/crc32 Castagnoli table) — implemented natively because a pure-Python
// byte loop caps WAL append throughput at a few MB/s, far below the 10 MiB
// default proposal batch size.
//
// Built as a shared library and loaded via ctypes (no pybind11 in the image).

#include <cstddef>
#include <cstdint>

namespace {

uint32_t table[8][256];
bool initialized = false;

void init_tables() {
  const uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c >> 1) ^ ((c & 1) ? poly : 0);
    table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = table[0][i];
    for (int s = 1; s < 8; s++) {
      c = table[0][c & 0xFF] ^ (c >> 8);
      table[s][i] = c;
    }
  }
  initialized = true;
}

}  // namespace

extern "C" {

uint32_t smartbft_crc32c_update(uint32_t crc, const uint8_t* data, size_t n) {
  if (!initialized) init_tables();
  crc = ~crc;
  while (n >= 8) {
    uint32_t lo = crc ^ (static_cast<uint32_t>(data[0]) |
                         (static_cast<uint32_t>(data[1]) << 8) |
                         (static_cast<uint32_t>(data[2]) << 16) |
                         (static_cast<uint32_t>(data[3]) << 24));
    uint32_t hi = static_cast<uint32_t>(data[4]) |
                  (static_cast<uint32_t>(data[5]) << 8) |
                  (static_cast<uint32_t>(data[6]) << 16) |
                  (static_cast<uint32_t>(data[7]) << 24);
    crc = table[7][lo & 0xFF] ^ table[6][(lo >> 8) & 0xFF] ^
          table[5][(lo >> 16) & 0xFF] ^ table[4][lo >> 24] ^
          table[3][hi & 0xFF] ^ table[2][(hi >> 8) & 0xFF] ^
          table[1][(hi >> 16) & 0xFF] ^ table[0][hi >> 24];
    data += 8;
    n -= 8;
  }
  while (n--) crc = table[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

}  // extern "C"
