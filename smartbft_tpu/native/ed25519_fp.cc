// Curve25519 field arithmetic + RFC 8032 point decompression.
//
// Ed25519 verification needs R (and A at registration) decompressed: a
// square root mod p = 2^255-19, which costs ~150 us per signature as a
// Python pow().  This moves it to ~5 us of 64-bit limb arithmetic so the
// host prep of crypto/pallas_ed25519.py stops dominating the batch.
//
// Wire format: 32-byte little-endian compressed point (y with the x sign
// in bit 255) in; 64 bytes out (x||y, little-endian); rc 1 ok / 0 invalid.

#include <cstdint>
#include <cstring>

namespace {

using u64 = uint64_t;
using u128 = unsigned __int128;

constexpr int NL = 4;

// p = 2^255 - 19
constexpr u64 Pmod[NL] = {
    0xffffffffffffffedULL, 0xffffffffffffffffULL,
    0xffffffffffffffffULL, 0x7fffffffffffffffULL,
};

struct Fe {
    u64 v[NL];
};

// d = -121665/121666 mod p
constexpr Fe D = {{0x75eb4dca135978a3ULL, 0x00700a4d4141d8abULL,
                   0x8cc740797779e898ULL, 0x52036cee2b6ffe73ULL}};
// sqrt(-1) = 2^((p-1)/4) mod p
constexpr Fe SQRT_M1 = {{0xc4ee1b274a0ea0b0ULL, 0x2f431806ad2fe478ULL,
                         0x2b4d00993dfbd7a7ULL, 0x2b8324804fc1df0bULL}};

inline u64 adc(u64 a, u64 b, u64 &carry) {
    u128 t = (u128)a + b + carry;
    carry = (u64)(t >> 64);
    return (u64)t;
}

bool geq_p(const Fe &a) {
    for (int i = NL - 1; i >= 0; i--) {
        if (a.v[i] > Pmod[i]) return true;
        if (a.v[i] < Pmod[i]) return false;
    }
    return true;
}

void sub_p(Fe &a) {
    u128 br = 0;
    for (int i = 0; i < NL; i++) {
        u128 t = (u128)a.v[i] - Pmod[i] - br;
        a.v[i] = (u64)t;
        br = (t >> 64) & 1;
    }
}

Fe fe_reduce_once(Fe a) {
    if (geq_p(a)) sub_p(a);
    return a;
}

// full reduction of an 8-limb product: 2^256 = 38 mod p
Fe fe_from_wide(const u64 w[2 * NL]) {
    // fold high 256 bits: lo + hi*38 (lo < 39 * 2^256)
    u64 lo[NL + 1] = {0};
    u128 c = 0;
    for (int i = 0; i < NL; i++) {
        c += (u128)w[i] + (u128)w[NL + i] * 38;
        lo[i] = (u64)c;
        c >>= 64;
    }
    lo[NL] = (u64)c;  // <= 38
    // fold again: lo[NL]*2^256 = lo[NL]*38.  The addition below can carry
    // out of limb NL-1 once more (lo's low half may be close to 2^256),
    // so propagate THAT carry with a third 38-fold — it is at most 1, and
    // after adding 38 the low half is far from 2^256, so this terminates.
    c = (u128)lo[0] + (u128)lo[NL] * 38;
    Fe r;
    r.v[0] = (u64)c;
    c >>= 64;
    for (int i = 1; i < NL; i++) {
        c += lo[i];
        r.v[i] = (u64)c;
        c >>= 64;
    }
    if (c) {  // final carry: 2^256 ≡ 38
        u128 t = (u128)r.v[0] + 38;
        r.v[0] = (u64)t;
        t >>= 64;
        for (int i = 1; i < NL && t; i++) {
            t += r.v[i];
            r.v[i] = (u64)t;
            t >>= 64;
        }
    }
    r = fe_reduce_once(r);
    return fe_reduce_once(r);
}

Fe fe_mul(const Fe &a, const Fe &b) {
    u64 w[2 * NL] = {0};
    for (int i = 0; i < NL; i++) {
        u64 carry = 0;
        for (int j = 0; j < NL; j++) {
            u128 t = (u128)a.v[i] * b.v[j] + w[i + j] + carry;
            w[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        w[i + NL] = carry;
    }
    return fe_from_wide(w);
}

Fe fe_sqr(const Fe &a) { return fe_mul(a, a); }

Fe fe_add(const Fe &a, const Fe &b) {
    Fe r;
    u64 carry = 0;
    for (int i = 0; i < NL; i++) r.v[i] = adc(a.v[i], b.v[i], carry);
    // carry can set bit 256: fold via 38
    if (carry) {
        u128 c = (u128)r.v[0] + 38;
        r.v[0] = (u64)c;
        c >>= 64;
        for (int i = 1; i < NL && c; i++) {
            c += r.v[i];
            r.v[i] = (u64)c;
            c >>= 64;
        }
    }
    return fe_reduce_once(r);
}

Fe fe_sub(const Fe &a, const Fe &b) {
    Fe r;
    u128 br = 0;
    for (int i = 0; i < NL; i++) {
        u128 t = (u128)a.v[i] - b.v[i] - br;
        r.v[i] = (u64)t;
        br = (t >> 64) & 1;
    }
    if (br) {
        u64 carry = 0;
        for (int i = 0; i < NL; i++) r.v[i] = adc(r.v[i], Pmod[i], carry);
    }
    return r;
}

bool fe_is_zero(const Fe &a) {
    Fe r = fe_reduce_once(a);
    u64 acc = 0;
    for (int i = 0; i < NL; i++) acc |= r.v[i];
    return acc == 0;
}

bool fe_eq(const Fe &a, const Fe &b) { return fe_is_zero(fe_sub(a, b)); }

// a^e for a fixed 255-bit exponent given as limbs, MSB-first scan
Fe fe_pow(const Fe &a, const u64 e[NL]) {
    Fe acc = {{1, 0, 0, 0}};
    bool started = false;
    for (int i = NL - 1; i >= 0; i--) {
        for (int b = 63; b >= 0; b--) {
            if (started) acc = fe_sqr(acc);
            if ((e[i] >> b) & 1) {
                if (started) acc = fe_mul(acc, a);
                else { acc = a; started = true; }
            }
        }
    }
    return acc;
}

Fe fe_from_bytes_le(const uint8_t *in, bool mask_high) {
    Fe r;
    for (int i = 0; i < NL; i++) {
        u64 v = 0;
        for (int j = 7; j >= 0; j--) v = (v << 8) | in[i * 8 + j];
        r.v[i] = v;
    }
    if (mask_high) r.v[NL - 1] &= 0x7fffffffffffffffULL;
    return r;
}

void fe_to_bytes_le(const Fe &a, uint8_t *out) {
    Fe r = fe_reduce_once(fe_reduce_once(a));
    for (int i = 0; i < NL; i++) {
        u64 v = r.v[i];
        for (int j = 0; j < 8; j++) {
            out[i * 8 + j] = (uint8_t)(v >> (8 * j));
        }
    }
}

}  // namespace

extern "C" {

// RFC 8032 §5.1.3 decompression.  comp32: y || sign-bit (LE).
// out64 = x || y little-endian.  Returns 1, or 0 if invalid.
int smartbft_ed_decompress(const uint8_t *comp32, uint8_t *out64) {
    Fe y = fe_from_bytes_le(comp32, true);
    if (geq_p(y)) return 0;
    int sign = comp32[31] >> 7;

    Fe yy = fe_sqr(y);
    Fe one = {{1, 0, 0, 0}};
    Fe u = fe_sub(yy, one);             // y^2 - 1
    Fe v = fe_add(fe_mul(D, yy), one);  // d y^2 + 1

    // candidate x = u v^3 (u v^7)^((p-5)/8)
    Fe v3 = fe_mul(fe_sqr(v), v);
    Fe v7 = fe_mul(fe_sqr(v3), v);
    // (p-5)/8 = 2^252 - 3
    static const u64 E[NL] = {
        0xfffffffffffffffdULL, 0xffffffffffffffffULL,
        0xffffffffffffffffULL, 0x0fffffffffffffffULL,
    };
    Fe x = fe_mul(fe_mul(u, v3), fe_pow(fe_mul(u, v7), E));

    Fe vxx = fe_mul(v, fe_sqr(x));
    if (!fe_eq(vxx, u)) {
        if (fe_eq(vxx, fe_sub(Fe{{0, 0, 0, 0}}, u))) {
            x = fe_mul(x, SQRT_M1);
        } else {
            return 0;
        }
    }
    if (fe_is_zero(x) && sign) return 0;  // -0 is invalid
    uint8_t xb[32];
    fe_to_bytes_le(x, xb);
    if ((xb[0] & 1) != sign) {
        x = fe_sub(Fe{{0, 0, 0, 0}}, x);
    }
    fe_to_bytes_le(x, out64);
    fe_to_bytes_le(y, out64 + 32);
    return 1;
}

}  // extern "C"
