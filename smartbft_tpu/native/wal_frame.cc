// WAL frame append engine: header pack + CRC chain + vectored write +
// fdatasync in ONE native call.
//
// The Python append path (smartbft_tpu/wal/log.py _append_record; reference:
// /root/reference/pkg/wal/writeaheadlog.go:440-472) costs two buffered
// writes, a flush, and an fsync with GIL round-trips between them.  Here the
// whole frame is assembled in a stack buffer and hits the kernel in one
// write(2); durability via fdatasync(2), which flushes the data and the
// size-extension metadata the reader needs.
//
// Built as a shared library and loaded via ctypes (no pybind11 in the image).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <unistd.h>

extern "C" {

uint32_t smartbft_crc32c_update(uint32_t crc, const uint8_t* data, size_t n);

// Appends one frame: 8B LE header (len | crc<<32) + payload + zero pad to 8B.
// ENTRY/CONTROL frames (update_crc=1): chain CRC over payload+pad from
// *crc_io, write it into the header, and store it back to *crc_io.
// CRC_ANCHOR frames (update_crc=0): the header carries *crc_io unchanged and
// no bytes are covered.
// Returns the frame size on success, -1 on I/O error (errno preserved).
long smartbft_wal_append(int fd, const uint8_t* payload, size_t len,
                         uint32_t* crc_io, int update_crc, int do_sync) {
  const size_t pad = (8 - len % 8) % 8;
  const size_t padded = len + pad;
  const size_t frame = 8 + padded;

  // proposal batches default to 10 MiB; heap-allocate past 64 KiB
  uint8_t stack_buf[65536];
  uint8_t* buf = frame <= sizeof(stack_buf) ? stack_buf : new uint8_t[frame];

  std::memcpy(buf + 8, payload, len);
  std::memset(buf + 8 + len, 0, pad);

  uint32_t crc = *crc_io;
  if (update_crc) crc = smartbft_crc32c_update(crc, buf + 8, padded);

  const uint64_t header =
      static_cast<uint64_t>(len) | (static_cast<uint64_t>(crc) << 32);
  for (int i = 0; i < 8; i++) buf[i] = (header >> (8 * i)) & 0xFF;  // LE

  long result = static_cast<long>(frame);
  size_t off = 0;
  while (off < frame) {
    ssize_t n = write(fd, buf + off, frame - off);
    if (n < 0) {
      if (errno == EINTR) continue;
      result = -1;
      break;
    }
    off += static_cast<size_t>(n);
  }
  if (result > 0 && do_sync && fdatasync(fd) != 0) result = -1;
  if (buf != stack_buf) delete[] buf;
  if (result > 0 && update_crc) *crc_io = crc;
  return result;
}

}  // extern "C"
