"""Real-socket transport + multi-process replica cluster.

The reference is a LIBRARY whose embedder supplies transport (PAPER.md
layer map, L4 ``Comm`` in pkg/api/dependencies.go) — it never ships one.
This package is the transport it never had: an asyncio TCP / Unix-domain-
socket implementation of the :class:`smartbft_tpu.api.Comm` SPI, plus a
process-per-replica launcher, so the engine that PRs 1–5 grew inside one
Python process escapes the single-process box.

Layout:

* :mod:`framing`   — length-prefixed frame format over the canonical
  ``messages.wire_of`` encoding, incremental :class:`FrameDecoder`,
  handshake / sync wire messages;
* :mod:`transport` — :class:`SocketComm`: encode-once broadcast,
  per-wave write coalescing (one flush per outbox drain), wave-batched
  ingest (one ``handle_message_batch`` per read), reconnect with
  exponential backoff + jitter, bounded outboxes with counted drops;
* :mod:`cluster`   — :class:`SocketCluster`: spawns one OS process per
  replica (``python -m smartbft_tpu.net.launch``) sharing only key
  material and a peer address map; control-channel client; socket-level
  chaos runner speaking the ``testing.chaos.ChaosEvent`` vocabulary
  (SIGKILL, link drop, slow link);
* :mod:`launch`    — the replica process entry point.
"""

from .framing import (
    FrameDecoder,
    FrameError,
    encode_frame,
    parse_addr,
)
from .transport import SocketComm, TransportMetrics

__all__ = [
    "FrameDecoder",
    "FrameError",
    "encode_frame",
    "parse_addr",
    "SocketComm",
    "TransportMetrics",
]
