"""Process-per-replica cluster manager + socket-level chaos runner.

:class:`SocketCluster` spawns one OS process per replica
(``python -m smartbft_tpu.net.launch``), sharing ONLY key material and
the peer address map — the processes find each other over real TCP or
Unix-domain sockets, commit through the ``smartbft_tpu.net`` transport,
and persist ledgers/WALs on disk.  The parent talks to each replica over
a line-JSON control channel (submit / height / digest / stats / fault /
stop) that never touches the consensus transport.

:func:`run_socket_schedule` replays the SAME declarative
``testing.chaos.ChaosEvent`` vocabulary against the live processes, but
the faults are now *physical*:

====================  ====================================================
chaos action          socket-level meaning
====================  ====================================================
``crash``             SIGKILL the replica process (kill -9)
``restart``           respawn it — WAL + ledger-file recovery, then
                      wire-sync catch-up from the peers
``mute``/``unmute``   transport outbound silence (control fault)
``disconnect``        blackhole every link of the node, both directions
``partition``/``heal``  drop_link on each cross-group pair, both endpoints
``slow_link``         per-flush delay on every link of the node
``crash_during_snapshot``  wait (bounded) for the node's next snapshot
                      capture to land, then SIGKILL immediately — the
                      process dies with the fresh snapshot on disk and
                      the compaction/offer plumbing at an arbitrary
                      point (ISSUE 17; the deterministic between-write-
                      and-truncate points are pinned by the unit tests
                      over SnapshotStore + LedgerFile)
====================  ====================================================

(Framing poison — garbage bytes on a live connection — is exercised by
the frame-robustness tests in ``tests/test_net_framing.py``, where the
blast radius of one corrupted stream is pinned to that connection.)

Offsets are WALL-CLOCK seconds (real processes have no logical clock).
``socket_soak`` is the ``python -m smartbft_tpu.testing.chaos --soak
--sockets`` entry point: SIGKILL-and-rejoin and slow-link rounds over a
UDS cluster, invariant-checked (all committed, fork-free).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Optional

from ..testing.chaos import ChaosEvent


class ControlError(RuntimeError):
    pass


class ControlRejected(ControlError):
    """A control-channel submit was SHED by the replica's admission
    machinery (PR 8 contract over the socket): ``kind`` is "admission" or
    "timeout", ``retry_after`` the drain-rate hint in seconds (0.0 = no
    hint), ``occupancy`` the pool snapshot at rejection time.  A socket
    client that backs off by ``retry_after`` arrives when capacity
    plausibly exists; one that hammers gets shed again."""

    def __init__(self, message: str, *, kind: str = "",
                 retry_after: float = 0.0, occupancy: Optional[dict] = None):
        super().__init__(message)
        self.kind = kind
        self.retry_after = retry_after
        self.occupancy = occupancy or {}


class ControlClient:
    """Line-JSON client for one replica's control channel.

    Keeps ONE persistent connection and reconnects on error (ISSUE 20
    satellite; PR 19 residual): the server side of the channel already
    served many requests per connection, but this client used to connect
    per call — and that TCP/UDS handshake was the floor under the read
    path's p99 once reads themselves got cheap.  A call that fails on a
    REUSED connection retries exactly once on a fresh one (the replica
    may have been SIGKILLed and respawned since the last call — the PR 19
    reachability property, now one reconnect away instead of free); a
    failure on a fresh connection propagates, since retrying it would
    just fail the same way.  ``stats`` counts connects / calls / reuses /
    reconnects so benches can prove the pooling actually pools.

    The one-retry policy is safe for ``cmd=submit`` because the request
    pool deduplicates by (client_id, request_id): if the first attempt's
    bytes actually landed before the connection died, the retry is
    absorbed, not double-ordered.
    """

    def __init__(self, addr: str, timeout: float = 10.0):
        self.addr = addr
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self.stats = {"connects": 0, "calls": 0, "reuses": 0,
                      "reconnects": 0}

    def _connect(self) -> socket.socket:
        from .framing import parse_addr

        scheme, hostpath, port = parse_addr(self.addr)
        if scheme == "tcp":
            sock = socket.create_connection((hostpath, port), self.timeout)
        else:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self.timeout)
            sock.connect(hostpath)
        self.stats["connects"] += 1
        return sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._buf = b""

    def _roundtrip(self, payload: bytes) -> dict:
        sock = self._sock
        assert sock is not None
        sock.sendall(payload)
        while b"\n" not in self._buf:
            chunk = sock.recv(65536)
            if not chunk:
                raise ControlError(f"control channel EOF from {self.addr}")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return json.loads(line)

    def call(self, **req) -> dict:
        self.stats["calls"] += 1
        payload = (json.dumps(req) + "\n").encode()
        reused = self._sock is not None
        if not reused:
            self._sock = self._connect()
        try:
            resp = self._roundtrip(payload)
            if reused:
                self.stats["reuses"] += 1
        except (OSError, ControlError, json.JSONDecodeError):
            self.close()
            if not reused:
                raise
            # the cached connection went stale (replica restarted, idle
            # teardown): one fresh attempt, whose failure propagates
            self.stats["reconnects"] += 1
            self._sock = self._connect()
            try:
                resp = self._roundtrip(payload)
            except (OSError, ControlError, json.JSONDecodeError):
                self.close()
                raise
        if not resp.get("ok"):
            if resp.get("rejected"):
                raise ControlRejected(
                    resp.get("error", "request shed"),
                    kind=resp["rejected"],
                    retry_after=resp.get("retry_after_ms", 0) / 1000.0,
                    occupancy=resp.get("occupancy"),
                )
            raise ControlError(resp.get("error", "control command failed"))
        return resp


@dataclass
class ReplicaHandle:
    node_id: int
    spec_path: str
    control: ControlClient
    listen: str
    proc: Optional[subprocess.Popen] = None


class SocketCluster:
    """n replica processes over real sockets on this host.

    ``transport``: ``"uds"`` (default; sockets live in a short private
    tempdir — UDS paths are capped at ~107 bytes, pytest tmp dirs are
    not) or ``"tcp"`` (127.0.0.1, ephemeral ports reserved up front).
    ``config_overrides``: JSON-safe Configuration field overrides applied
    on top of ``launch.proc_config`` in every replica.
    """

    def __init__(
        self,
        root,
        *,
        n: int = 4,
        transport: str = "uds",
        config_overrides: Optional[dict] = None,
        cluster_key: bytes = b"smartbft-cluster-key",
        env: Optional[dict] = None,
        trace: bool = False,
        trace_capacity: int = 2048,
    ):
        if transport not in ("uds", "tcp"):
            raise ValueError(f"transport must be 'uds' or 'tcp', got {transport!r}")
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.n = n
        self.transport = transport
        self.cluster_key = cluster_key
        #: flight recorder armed per replica (ISSUE 12): each process
        #: keeps a bounded TraceRecorder the parent can pull with
        #: cmd=trace and dump as run artifacts on invariant failure
        self.trace = trace
        self.trace_capacity = trace_capacity
        self.env = dict(os.environ, JAX_PLATFORMS="cpu", **(env or {}))
        self._sockdir = (
            tempfile.mkdtemp(prefix="sbft-", dir="/tmp")
            if transport == "uds" else None
        )
        if transport == "uds":
            listen = {i: f"uds://{self._sockdir}/n{i}.sock" for i in self._ids}
            control = {i: f"uds://{self._sockdir}/c{i}.sock" for i in self._ids}
        else:
            listen = {i: f"tcp://127.0.0.1:{_free_port()}" for i in self._ids}
            control = {i: f"tcp://127.0.0.1:{_free_port()}" for i in self._ids}
        self.replicas: dict[int, ReplicaHandle] = {}
        for i in self._ids:
            spec = {
                "node_id": i,
                "listen": listen[i],
                "control": control[i],
                "peers": {str(j): listen[j] for j in self._ids if j != i},
                "cluster_key": cluster_key.hex(),
                "wal_dir": os.path.join(self.root, f"wal-{i}"),
                "ledger_path": os.path.join(self.root, f"ledger-{i}.bin"),
                "config": dict(config_overrides or {}),
                "trace": bool(trace),
                "trace_capacity": int(trace_capacity),
            }
            spec_path = os.path.join(self.root, f"spec-{i}.json")
            with open(spec_path, "w") as fh:
                json.dump(spec, fh)
            self.replicas[i] = ReplicaHandle(
                node_id=i, spec_path=spec_path,
                control=ControlClient(control[i]), listen=listen[i],
            )
        self.down: set[int] = set()

    @property
    def _ids(self) -> list[int]:
        return list(range(1, self.n + 1))

    # ------------------------------------------------------------ lifecycle

    def spawn(self, node_id: int) -> None:
        h = self.replicas[node_id]
        if h.proc is not None and h.proc.poll() is None:
            # A second spawn would fork a TWIN replica sharing the same
            # ledger/WAL/socket paths — the twin survives kill() and
            # silently keeps committing, wrecking every chaos oracle.
            raise RuntimeError(
                f"replica {node_id} is already running (pid "
                f"{h.proc.pid}); kill() it before spawning again"
            )
        # Popen dups the log fd into the child; close the parent's handle
        # so restart-heavy soaks don't accumulate one fd per spawn
        with open(os.path.join(self.root, f"replica-{node_id}.log"), "ab") as log:
            h.proc = subprocess.Popen(
                [sys.executable, "-m", "smartbft_tpu.net.launch",
                 "--spec-file", h.spec_path],
                env=self.env,
                stdout=subprocess.DEVNULL,
                stderr=log,
            )
        self.down.discard(node_id)

    def start(self, *, ready_timeout: float = 30.0) -> None:
        for i in self._ids:
            self.spawn(i)
        for i in self._ids:
            self.wait_ready(i, timeout=ready_timeout)

    def wait_ready(self, node_id: int, timeout: float = 30.0) -> None:
        h = self.replicas[node_id]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if h.proc is not None and h.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {node_id} exited rc={h.proc.returncode} "
                    f"(see {self.root}/replica-{node_id}.log)"
                )
            try:
                if h.control.call(cmd="ping")["running"]:
                    return
            except (OSError, ControlError, json.JSONDecodeError):
                pass
            time.sleep(0.05)
        raise TimeoutError(f"replica {node_id} not ready within {timeout}s")

    def kill(self, node_id: int) -> None:
        """kill -9: the SIGKILL chaos fault — no shutdown path runs."""
        h = self.replicas[node_id]
        if h.proc is not None and h.proc.poll() is None:
            h.proc.send_signal(signal.SIGKILL)
            h.proc.wait()
        # drop the pooled control connection now: the next call would
        # discover the stale socket anyway, but burning a reconnect on a
        # KNOWN-dead replica skews the reuse stats for no information
        h.control.close()
        self.down.add(node_id)

    def restart(self, node_id: int, *, ready_timeout: float = 30.0) -> None:
        self.spawn(node_id)
        self.wait_ready(node_id, timeout=ready_timeout)

    def stop(self) -> None:
        """Graceful where possible, forceful where not; always reaps."""
        for i, h in self.replicas.items():
            if h.proc is None or h.proc.poll() is not None:
                continue
            try:
                h.control.call(cmd="stop")
            except (OSError, ControlError, json.JSONDecodeError):
                pass
        deadline = time.monotonic() + 10.0
        for h in self.replicas.values():
            if h.proc is None:
                continue
            while h.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if h.proc.poll() is None:
                h.proc.kill()
                h.proc.wait()
        for h in self.replicas.values():
            h.control.close()
        if self._sockdir is not None:
            import shutil

            shutil.rmtree(self._sockdir, ignore_errors=True)

    def __enter__(self) -> "SocketCluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ operations

    def live_ids(self) -> list[int]:
        return [i for i in self._ids if i not in self.down]

    def control(self, node_id: int) -> ControlClient:
        return self.replicas[node_id].control

    def control_stats(self) -> dict:
        """Aggregate pooled-control-channel stats across every replica's
        client: connects / calls / reuses / reconnects, plus the reuse
        fraction the read benches report (1.0 = after the first call,
        every call rode an existing connection)."""
        total = {"connects": 0, "calls": 0, "reuses": 0, "reconnects": 0}
        for h in self.replicas.values():
            for k in total:
                total[k] += h.control.stats[k]
        total["reuse_fraction"] = (
            total["reuses"] / total["calls"] if total["calls"] else 0.0
        )
        return total

    def leader_of(self) -> int:
        for i in self.live_ids():
            try:
                lead = self.control(i).call(cmd="leader")["leader"]
                if lead:
                    return lead
            except (OSError, ControlError):
                continue
        return 0

    def wait_leader(self, timeout: float = 20.0) -> int:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            lead = self.leader_of()
            if lead:
                return lead
            time.sleep(0.05)
        raise TimeoutError("no leader elected")

    def submit(self, via: int, client: str, rid: str, payload: bytes = b"") -> None:
        self.control(via).call(cmd="submit", client=client, rid=rid,
                               payload=payload.hex())

    def trigger_reshard(self, epoch: int, old_shards: int, new_shards: int,
                        *, via: Optional[int] = None,
                        timeout: float = 30.0) -> dict:
        """Control-plane reshard trigger for a multi-process group: order
        epoch ``epoch``'s barrier command through the (leader's) ordered
        stream, then wait until EVERY live replica's ledger carries it —
        the resize decision is then durable cluster-wide, and the manager
        of S such groups can proceed with drain + flip exactly like the
        in-process ShardSet.  Returns ``{"epoch": e, "barriers": {node:
        ledger seq}}``; raises TimeoutError if any replica fails to order
        it in time (re-triggering is idempotent — pool client dedup)."""
        deadline = time.monotonic() + timeout
        barriers: dict[int, int] = {}
        while time.monotonic() < deadline:
            # (re-)issue the trigger every tick — idempotent under pool
            # client dedup, and exactly what survives the ordering replica
            # dying with the command still pooled (the in-process
            # _barrier_step re-submits on every poll for the same reason)
            try:
                target = via if via is not None else self.wait_leader(
                    timeout=2.0)
                self.control(target).call(cmd="reshard", epoch=epoch,
                                          old=old_shards, new=new_shards)
            except (OSError, ControlError, TimeoutError):
                pass  # leaderless interregnum / target down: retry next tick
            barriers = {}
            for i in self.live_ids():
                try:
                    resp = self.control(i).call(cmd="barrier", epoch=epoch)
                    barriers[i] = int(resp.get("barrier_seq", 0))
                except (OSError, ControlError):
                    barriers[i] = 0
            if barriers and all(v > 0 for v in barriers.values()):
                return {"epoch": epoch, "barriers": barriers}
            time.sleep(0.1)
        raise TimeoutError(
            f"epoch {epoch} barrier not committed on every replica within "
            f"{timeout}s: {barriers}"
        )

    def committed(self, node_id: int) -> int:
        return self.control(node_id).call(cmd="committed")["committed"]

    def heights(self) -> dict[int, int]:
        return {i: h for i, (h, _p) in self.heights_and_pools().items()}

    def heights_and_pools(self) -> dict[int, tuple[int, int]]:
        """node -> (ledger height, request-pool size); (-1, -1) when down."""
        out = {}
        for i in self.live_ids():
            try:
                resp = self.control(i).call(cmd="height")
                out[i] = (resp["height"], resp.get("pool", 0))
            except (OSError, ControlError):
                out[i] = (-1, -1)
        return out

    def wait_committed(self, total: int, timeout: float = 60.0,
                       nodes: Optional[list[int]] = None) -> None:
        """Block until every targeted replica committed >= total requests."""
        targets = nodes if nodes is not None else self.live_ids()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if all(self.committed(i) >= total for i in targets):
                    return
            except (OSError, ControlError):
                pass
            time.sleep(0.1)
        raise TimeoutError(
            f"cluster did not commit {total} requests within {timeout}s: "
            f"{[(i, self._committed_or(i)) for i in targets]}"
        )

    def _committed_or(self, i: int) -> object:
        try:
            return self.committed(i)
        except (OSError, ControlError) as e:
            return f"down({type(e).__name__})"

    def check_fork_free(self) -> None:
        """Pairwise-identical ledger prefixes via control-channel digests.

        Snapshot-aware (ISSUE 17): a replica that compacted PAST the
        comparison height cannot recompute that prefix digest (the
        decisions are gone — by design), so it is skipped for the
        prefix comparison; replicas at EQUAL heights are additionally
        compared on their full chained digest AND their chained
        request-id digest, which survive compaction at any horizon.
        """
        heights = self.heights()
        live = [i for i, h in heights.items() if h >= 0]
        if len(live) < 2:
            return
        m = min(heights[i] for i in live)
        resp = {
            i: self.control(i).call(cmd="ledger_digest", upto=m)
            for i in live
        }
        comparable = [i for i in live if int(resp[i].get("base", 0)) <= m]
        if len(comparable) >= 2:
            ref = resp[comparable[0]]["digest"]
            for i in comparable[1:]:
                assert resp[i]["digest"] == ref, (
                    f"ledger fork: node {comparable[0]} and node {i} "
                    f"diverge within the first {m} decisions"
                )
        # equal-height replicas must agree on the FULL digests too —
        # this is the check that still bites when compaction horizons
        # differ (and the exactly-once oracle across snapshot installs)
        by_height: dict[int, list[int]] = {}
        for i in live:
            by_height.setdefault(heights[i], []).append(i)
        for h, group in by_height.items():
            if len(group) < 2:
                continue
            full = {
                i: self.control(i).call(cmd="ledger_digest", upto=h)
                for i in group
            }
            ref_i = group[0]
            for i in group[1:]:
                assert full[i]["digest"] == full[ref_i]["digest"], (
                    f"ledger fork at height {h}: node {ref_i} vs node {i}"
                )
                assert (full[i].get("ids_digest")
                        == full[ref_i].get("ids_digest")), (
                    f"request-id stream diverges at height {h}: "
                    f"node {ref_i} vs node {i} (lost or doubled "
                    f"delivery across a snapshot install)"
                )

    def committed_ids(self, node_id: int) -> list[str]:
        return self.control(node_id).call(cmd="committed_ids")["ids"]

    def wait_quiescent(self, *, quiet: float = 2.0, timeout: float = 60.0,
                       nodes: Optional[list[int]] = None) -> None:
        """Block until the targeted replicas' heights are equal, their
        request pools are EMPTY, and both have held for ``quiet`` seconds.

        The pool condition is what makes the honest-client resubmission
        contract exactly-once-safe: "heights stable" alone can be reached
        mid-view-change while uncommitted requests still sit in follower
        pools waiting to be forwarded to the next leader — resubmitting
        one of those races its original copy into a second decision (the
        forwarded copy reaches the new leader after the resubmission
        committed and cleared the pools, so dedup never sees the pair).
        Pools empty + heights equal means every submitted request either
        committed or died with a killed process's volatile pool."""
        targets = nodes if nodes is not None else self.live_ids()
        deadline = time.monotonic() + timeout
        last: Optional[tuple] = None
        stable_since = time.monotonic()
        while time.monotonic() < deadline:
            hp = self.heights_and_pools()
            hs = tuple(sorted(hp.get(i, (-1, -1))[0] for i in targets))
            drained = all(hp.get(i, (-1, -1))[1] == 0 for i in targets)
            if hs != last or len(set(hs)) != 1 or not drained:
                last = hs
                stable_since = time.monotonic()
            elif time.monotonic() - stable_since >= quiet:
                return
            time.sleep(0.1)
        raise TimeoutError(
            f"cluster never quiesced: heights/pools {self.heights_and_pools()}"
        )

    def transport_stats(self) -> dict[int, dict]:
        out = {}
        for i in self.live_ids():
            try:
                out[i] = self.control(i).call(cmd="stats")["transport"]
            except (OSError, ControlError):
                pass
        return out

    def snapshot_stats(self, node_id: int) -> dict:
        """One replica's snapshot/disk posture (cmd=snapshot, ISSUE 17):
        ``height``, ``base_height``, ``snapshot_height``,
        ``snapshot_age_decisions``, ``snapshot_disk_bytes``,
        ``ledger_disk_bytes``, ``wal_disk_bytes``, ``sync_poisoned``."""
        return self.control(node_id).call(cmd="snapshot")

    def fault(self, node_id: int, action: str, peer: int = 0,
              delay: float = 0.0) -> None:
        self.control(node_id).call(cmd="fault", action=action, peer=peer,
                                   delay=delay)

    # ------------------------------------------------------------ observability

    def trace_pull(self, node_id: int, last: Optional[int] = None,
                   since: Optional[int] = None) -> dict:
        """Pull one replica's flight-recorder state over the control
        channel: ``{"node", "trace": <summary block>, "events": [...],
        "next_since": <cursor>}`` — the per-replica timeline a
        SocketCluster run can fetch without touching the consensus
        transport.  Pass ``since`` (a previous pull's ``next_since``) to
        ship only NEW events: repeated pulls cost O(new), never a re-send
        of the whole ring."""
        req = {"cmd": "trace"}
        if last is not None:
            req["last"] = last
        if since is not None:
            req["since"] = since
        return self.control(node_id).call(**req)

    def estimate_clock_offsets(self, samples: int = 5) -> dict:
        """Per-replica monotonic-clock offset vs THIS process's clock,
        over the existing control-channel ping (line JSON, PR 6).

        Classic request/response-midpoint estimation: the replica's
        ``now`` (monotonic, returned by cmd=ping) is assumed to have been
        read at the midpoint of the round trip; ``offset = now_replica -
        midpoint_parent``, and any replica timestamp maps onto the
        parent's timeline as ``t - offset``.  The LOWEST-RTT sample of
        ``samples`` wins (least queueing noise) and the error is bounded
        by RTT/2 — reported per node so the merged timeline's precision
        is stated, not implied.  Returns ``{"n<i>": {"offset_s",
        "rtt_s", "err_bound_s"}}`` for every live, answering replica."""
        out: dict = {}
        for i in self.live_ids():
            best: Optional[tuple[float, float]] = None
            for _ in range(max(1, samples)):
                t0 = time.monotonic()
                try:
                    resp = self.control(i).call(cmd="ping")
                except (OSError, ControlError, json.JSONDecodeError):
                    break
                t1 = time.monotonic()
                now = resp.get("now")
                if now is None:
                    break  # pre-offset replica build: skip
                rtt = t1 - t0
                if best is None or rtt < best[1]:
                    best = (float(now) - (t0 + t1) / 2.0, rtt)
            if best is not None:
                out[f"n{i}"] = {
                    "offset_s": best[0],
                    "rtt_s": round(best[1], 6),
                    "err_bound_s": round(best[1] / 2.0, 6),
                }
        return out

    def cluster_timeline(self, out_dir: Optional[str] = None,
                         last: Optional[int] = None) -> dict:
        """Pull every live replica's flight recorder plus clock offsets
        and merge them into ONE causally-ordered cluster timeline:
        skew-adjusted timestamps (each dump carries its
        ``clock_offset_s``; the merge subtracts it) and per-directed-link
        network time (receiver ingest minus sender send, both mapped onto
        the parent clock).  ``last=None`` (default) pulls each replica's
        WHOLE ring: a deep (e.g. 16k) ring would otherwise be silently
        tail-trimmed, dropping early requests' submit marks from the
        critical-path join with no truncation signal.  Returns
        ``{"offsets", "dumps", "events",
        "hops"}``; with ``out_dir`` the dumps (and an ``offsets.json``)
        are also written in the ``obs.report`` shape so ``python -m
        smartbft_tpu.obs.report out/flight-*.json`` renders the merged
        timeline offline."""
        from ..obs.report import link_summary, merged_events

        offsets = self.estimate_clock_offsets()
        dumps: list[dict] = []
        offsets_missing: list[str] = []
        for i in self.live_ids():
            try:
                resp = self.trace_pull(i, last=last)
            except (OSError, ControlError):
                continue
            node = resp.get("node", f"n{i}")
            known = node in offsets
            if not known:
                # a replica whose ping failed mid-estimation merges with
                # an UNKNOWN clock: flag it loudly (offset_known) instead
                # of silently pretending 0.0 skew — on a real multi-host
                # deployment that skew is unbounded, and link_summary
                # excludes the node's hop rows rather than polluting them
                offsets_missing.append(node)
            dumps.append({
                "node": node,
                "capacity": resp.get("trace", {}).get("capacity", 0),
                "recorded": resp.get("trace", {}).get("recorded", 0),
                "dropped": resp.get("dropped", 0),
                "clock_offset_s": offsets.get(node, {}).get("offset_s", 0.0),
                "offset_known": known,
                "events": resp.get("events", []),
            })
        events = merged_events(dumps)
        hops = link_summary(
            events, {n: o["offset_s"] for n, o in offsets.items()}
        )
        if out_dir is not None:
            os.makedirs(out_dir, exist_ok=True)
            for d in dumps:
                with open(os.path.join(out_dir,
                                       f"flight-{d['node']}.json"), "w") as fh:
                    json.dump(d, fh)
            with open(os.path.join(out_dir, "offsets.json"), "w") as fh:
                json.dump(offsets, fh)
        return {"offsets": offsets, "offsets_missing": offsets_missing,
                "dumps": dumps, "events": len(events), "hops": hops,
                # the merged (skew-adjusted, sorted) event list itself —
                # callers feeding the critical-path assemble must not pay
                # a second O(E log E) merge over the same dumps
                "merged": events}

    def metrics_text(self, node_id: int) -> str:
        """One replica's Prometheus text exposition (cmd=metrics)."""
        return self.control(node_id).call(cmd="metrics")["text"]

    def health(self, node_id: int) -> dict:
        """One replica's live SLO verdict (cmd=health)."""
        return self.control(node_id).call(cmd="health")

    def cluster_health(self) -> dict:
        """ONE aggregated cluster verdict from a single control-channel
        sweep (ISSUE 14): poll every live replica's cmd=health, fold the
        per-replica verdicts with
        :func:`~smartbft_tpu.obs.health.aggregate_cluster_verdict` —
        replicas that are down or unreachable degrade the verdict
        themselves (a majority gone is critical).  Returns ``{"status",
        "replicas", "reasons", "unreachable"}``."""
        from ..obs.health import aggregate_cluster_verdict

        verdicts: dict[str, dict] = {}
        unreachable: list[str] = []
        for i in self._ids:
            if i in self.down:
                unreachable.append(f"n{i}")
                continue
            try:
                resp = self.health(i)
                verdicts[resp.get("node", f"n{i}")] = resp["health"]
            except (OSError, ControlError, KeyError,
                    json.JSONDecodeError):
                unreachable.append(f"n{i}")
        return aggregate_cluster_verdict(verdicts, unreachable=unreachable)

    def dump_flight_recorders(self, out_dir: Optional[str] = None,
                              last: int = 2048) -> list[str]:
        """Write each LIVE replica's last ``last`` spans to
        ``out_dir`` (default: the cluster root) as ``flight-n<i>.json``
        — the dump shape ``python -m smartbft_tpu.obs.report`` renders.
        Replicas that are down or untraced are skipped; returns the
        written paths."""
        if not self.trace:
            return []
        out_dir = out_dir or self.root
        os.makedirs(out_dir, exist_ok=True)
        paths = []
        for i in self.live_ids():
            try:
                resp = self.trace_pull(i, last=last)
            except (OSError, ControlError):
                continue
            path = os.path.join(out_dir, f"flight-n{i}.json")
            with open(path, "w") as fh:
                json.dump({
                    "node": resp.get("node", f"n{i}"),
                    "capacity": resp.get("trace", {}).get("capacity", 0),
                    "recorded": resp.get("trace", {}).get("recorded", 0),
                    "dropped": resp.get("dropped", 0),
                    "events": resp.get("events", []),
                }, fh)
            paths.append(path)
        return paths


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# --------------------------------------------------------------------------
# socket-level chaos: the ChaosEvent vocabulary against live processes
# --------------------------------------------------------------------------


@dataclass
class SocketChaosReport:
    submitted: int = 0
    final_committed: int = 0
    heights: dict = field(default_factory=dict)
    events_fired: list = field(default_factory=list)
    #: (t_offset_s, status, [breaching slo names]) — one entry per
    #: cluster-verdict CHANGE observed by the periodic health sweep
    verdicts: list = field(default_factory=list)
    #: (first_event_t, last_event_t) run offsets of the fault window
    fault_span: Optional[tuple] = None
    final_health: Optional[dict] = None


def assert_no_critical_outside_faults(report: SocketChaosReport,
                                      *, recovery_s: float = 30.0) -> None:
    """The soak's health gate (ISSUE 14): a ``critical`` cluster verdict
    is only acceptable while an injected fault (plus a bounded recovery
    window) explains it; any other critical sample fails the run.  The
    final verdict must not be critical at all — the run ends quiesced.
    (Same rule as the logical-clock runner: testing.chaos
    assert_health_verdicts.)"""
    from ..testing.chaos import assert_health_verdicts

    assert_health_verdicts(report.verdicts, report.fault_span,
                           report.final_health, recovery_s=recovery_s)


def run_socket_schedule(
    cluster: SocketCluster,
    schedule: list[ChaosEvent],
    *,
    requests: int = 16,
    submit_every: float = 0.15,
    settle_timeout: float = 90.0,
    health_every: float = 0.5,
) -> SocketChaosReport:
    """Replay a ``testing.chaos`` schedule against real processes.

    Same dynamic-target semantics as the in-process harness: ``"leader"``
    resolves to the live leader when the event fires, ``"faulty"`` to the
    run's first leader resolution.  ``at`` offsets are wall-clock seconds
    from the start of the run.  After the last event and submission, the
    run blocks until every LIVE replica has committed every request, then
    fork-checks the ledgers.
    """
    report = SocketChaosReport()
    pending = sorted(schedule, key=lambda e: e.at)
    faulted: set[int] = set()
    faulty_node: Optional[int] = None
    start = time.monotonic()
    submitted = 0
    next_submit = 0.0
    next_health = 0.0
    last_status: Optional[str] = None

    def sample_health(now: float) -> None:
        """Periodic cluster-verdict sweep; only CHANGES are recorded.
        Health is advisory — a sweep that fails (replica mid-restart)
        must never fail the schedule it observes."""
        nonlocal last_status
        try:
            verdict = cluster.cluster_health()
        except Exception:  # noqa: BLE001 — advisory
            return
        report.final_health = verdict
        if verdict["status"] != last_status:
            last_status = verdict["status"]
            report.verdicts.append((
                round(now, 2), verdict["status"],
                sorted({r.get("slo", "?") for r in verdict["reasons"]}),
            ))

    def resolve(spec) -> Optional[int]:
        nonlocal faulty_node
        if spec == "leader":
            node = cluster.wait_leader()
            if faulty_node is None:
                faulty_node = node
            return node
        if spec == "faulty":
            if faulty_node is None:
                raise RuntimeError('"faulty" used before any "leader" resolution')
            return faulty_node
        return spec

    def fire(evt: ChaosEvent) -> None:
        node = resolve(evt.node) if evt.node is not None else None
        if evt.action == "crash":
            cluster.kill(node)
            faulted.add(node)
        elif evt.action == "restart":
            cluster.restart(node)
            faulted.discard(node)
        elif evt.action == "mute":
            cluster.fault(node, "mute")
            faulted.add(node)
        elif evt.action == "unmute":
            cluster.fault(node, "unmute")
            faulted.discard(node)
        elif evt.action == "disconnect":
            cluster.fault(node, "drop_link")  # peer=0: every link
            for other in cluster.live_ids():
                if other != node:
                    cluster.fault(other, "drop_link", peer=node)
            faulted.add(node)
        elif evt.action == "reconnect":
            cluster.fault(node, "heal_links")
            for other in cluster.live_ids():
                if other != node:
                    cluster.fault(other, "restore_link", peer=node)
            faulted.discard(node)
        elif evt.action == "partition":
            groups = [[resolve(m) for m in g] for g in evt.groups]
            named = {m for g in groups for m in g}
            rest = [i for i in cluster._ids if i not in named]
            allg = groups + ([rest] if rest else [])
            side = {m: gi for gi, g in enumerate(allg) for m in g}
            for a in cluster.live_ids():
                for b in cluster.live_ids():
                    if a < b and side.get(a) != side.get(b):
                        cluster.fault(a, "drop_link", peer=b)
                        cluster.fault(b, "drop_link", peer=a)
            from ..core.util import compute_quorum

            q, _ = compute_quorum(cluster.n)
            for g in allg:
                if len(g) < q:
                    faulted.update(g)
        elif evt.action == "heal":
            for i in cluster.live_ids():
                cluster.fault(i, "heal_links")
            faulted.clear()
        elif evt.action == "slow_link":
            cluster.fault(node, "slow_link", delay=evt.fraction)
        elif evt.action == "unslow_link":
            cluster.fault(node, "slow_link", delay=0.0)
        elif evt.action == "crash_during_snapshot":
            _kill_at_next_snapshot(cluster, node,
                                   window=evt.fraction or 10.0)
            faulted.add(node)
        else:
            raise ValueError(f"unsupported socket chaos action: {evt.action}")
        report.events_fired.append((evt.action, node))
        now = time.monotonic() - start
        lo, hi = report.fault_span or (now, now)
        report.fault_span = (min(lo, now), max(hi, now))

    while True:
        now = time.monotonic() - start
        while pending and pending[0].at <= now:
            fire(pending.pop(0))
        if submitted < requests and now >= next_submit:
            healthy = [i for i in cluster.live_ids() if i not in faulted]
            if healthy:
                via = healthy[submitted % len(healthy)]
                try:
                    cluster.submit(via, "chaos", f"chaos-{submitted}")
                    submitted += 1
                except (OSError, ControlError):
                    pass  # no leader yet / pool full: retry next tick
            next_submit = now + submit_every
        report.submitted = submitted
        if now >= next_health:
            sample_health(now)
            next_health = now + health_every
        if not pending and submitted >= requests:
            break
        time.sleep(0.02)

    # drain to quiescence, then act as an honest BFT client: a request
    # whose only copy sat in a SIGKILLed replica's (volatile) pool is gone
    # — after the heights stop moving, anything absent from the ledgers is
    # absent from every live pool too, so resubmitting it through another
    # replica is exactly-once-safe (and exactly what the reference's
    # client contract prescribes on request timeout)
    expected = {f"chaos:chaos-{k}" for k in range(submitted)}
    deadline = time.monotonic() + settle_timeout
    try:
        while True:
            cluster.wait_quiescent(
                timeout=max(deadline - time.monotonic(), 1.0),
                nodes=[i for i in cluster.live_ids() if i not in faulted],
            )
            probe = [i for i in cluster.live_ids() if i not in faulted][0]
            missing = sorted(expected - set(cluster.committed_ids(probe)))
            if not missing:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"requests never committed after resubmission: {missing}"
                )
            healthy = [i for i in cluster.live_ids() if i not in faulted]
            for j, rid in enumerate(missing):
                cluster.submit(healthy[j % len(healthy)], "chaos",
                               rid.split(":", 1)[1])
            time.sleep(0.5)
        cluster.wait_committed(submitted, timeout=settle_timeout,
                               nodes=[i for i in cluster.live_ids()
                                      if i not in faulted])
        # stragglers that healed late (e.g. a restarted replica) get a
        # bounded grace window to catch up before the invariant checks
        try:
            cluster.wait_committed(submitted, timeout=settle_timeout / 2)
        except TimeoutError:
            pass
        cluster.check_fork_free()
        live = cluster.live_ids()
        # exactly-once: resubmission must never double-deliver
        ids = cluster.committed_ids(live[0])
        dupes = {i for i in ids if ids.count(i) > 1}
        assert not dupes, \
            f"duplicate deliveries after resubmission: {sorted(dupes)}"
    except (AssertionError, TimeoutError):
        # invariant failure: preserve each replica's flight recorder as a
        # run artifact (no-op unless the cluster was built with trace=True)
        try:
            paths = cluster.dump_flight_recorders()
            if paths:
                print(f"flight-recorder dumps written: {paths}",
                      file=sys.stderr)
        except Exception:  # noqa: BLE001 — never mask the real failure
            pass
        raise
    live = cluster.live_ids()
    report.final_committed = cluster.committed(live[0]) if live else 0
    report.heights = cluster.heights()
    sample_health(time.monotonic() - start)
    return report


def _snapshot_height_or(cluster: SocketCluster, node_id: int,
                        default: int = -1) -> int:
    try:
        return int(cluster.snapshot_stats(node_id).get("snapshot_height", 0))
    except (OSError, ControlError, json.JSONDecodeError):
        return default


def _kill_at_next_snapshot(cluster: SocketCluster, node_id: int,
                           *, window: float = 10.0) -> None:
    """SIGKILL ``node_id`` the moment its NEXT snapshot capture lands
    (bounded by ``window`` seconds — kills at the deadline regardless, so
    a schedule can never hang on a capture that does not come).  The
    process dies with the fresh snapshot file on disk and the
    compaction/truncation/offer plumbing interrupted at whatever point
    the race hits; recovery must reconcile."""
    before = _snapshot_height_or(cluster, node_id)
    deadline = time.monotonic() + max(window, 0.1)
    while time.monotonic() < deadline:
        if _snapshot_height_or(cluster, node_id) > before:
            break
        time.sleep(0.01)
    cluster.kill(node_id)


def kill_rejoin_schedule(*, crash_at: float = 2.0,
                         restart_at: float = 5.0) -> list[ChaosEvent]:
    """SIGKILL the current leader mid-burst; respawn it; it must recover
    from WAL + ledger file, wire-sync the gap, and rejoin as a follower."""
    return [
        ChaosEvent(at=crash_at, action="crash", node="leader"),
        ChaosEvent(at=restart_at, action="restart", node="faulty"),
    ]


def slow_link_schedule(*, slow_at: float = 1.0, heal_at: float = 6.0,
                       delay: float = 0.05) -> list[ChaosEvent]:
    """Throttle every link of one non-leader replica (per-flush delay) —
    the cluster must keep committing at quorum speed, and the slow node
    must still converge once healed."""
    return [
        ChaosEvent(at=slow_at, action="slow_link", node=2, fraction=delay),
        ChaosEvent(at=heal_at, action="unslow_link", node=2),
    ]


def socket_soak(*, rounds: int = 2, n: int = 4, transport: str = "uds",
                requests: int = 16, verbose: bool = True) -> None:
    """``chaos --soak --sockets``: the socket-fault matrix end-to-end.
    Each round runs SIGKILL-and-rejoin then slow-link against a fresh
    multi-process cluster, checking commit + fork-free invariants AND
    the continuous SLO verdict (ISSUE 14): the default spec is evaluated
    on every replica throughout, verdict transitions ride the report,
    and a critical verdict outside the injected-fault window (plus a
    bounded recovery) fails the round."""
    for r in range(rounds):
        for name, schedule in (
            ("kill-rejoin", kill_rejoin_schedule()),
            ("slow-link", slow_link_schedule()),
        ):
            with tempfile.TemporaryDirectory(prefix="sbft-soak-") as root:
                cluster = SocketCluster(root, n=n, transport=transport)
                try:
                    cluster.start()
                    cluster.wait_leader()
                    report = run_socket_schedule(
                        cluster, schedule, requests=requests
                    )
                    assert_no_critical_outside_faults(report)
                finally:
                    cluster.stop()
                if verbose:
                    print(
                        f"socket round {r} [{name}]: events="
                        f"{report.events_fired} committed="
                        f"{report.final_committed} heights={report.heights}"
                        f" verdicts={report.verdicts} — OK"
                    )


# --------------------------------------------------------------------------
# snapshot state transfer: O(1) rejoin over real sockets (ISSUE 17)
# --------------------------------------------------------------------------


@dataclass
class SnapshotRejoinReport:
    """What a snapshot-rejoin run observed (the oracle inputs)."""

    victim: int = 0
    victim_height_at_kill: int = 0
    donor_snapshot_height: int = 0
    victim_base_after: int = 0
    victim_height_after: int = 0
    snap_chunks_received: int = 0
    snap_chunks_sent_total: int = 0
    snap_bytes_received: int = 0
    sync_poisoned_total: int = 0
    rejoin_seconds: float = 0.0
    requests: int = 0
    events: list = field(default_factory=list)


def run_snapshot_rejoin(
    cluster: SocketCluster,
    *,
    victim: int = 2,
    warmup: int = 8,
    history: int = 48,
    crash_during_snapshot: bool = False,
    mid_fetch_donor_kill: bool = False,
    settle_timeout: float = 180.0,
) -> SnapshotRejoinReport:
    """Drive the snapshot state-transfer rejoin end-to-end over real
    processes: commit ``warmup``, SIGKILL ``victim`` (optionally racing
    its own snapshot capture), grow the chain by ``history`` until every
    donor's snapshot horizon has moved PAST the victim's crash height —
    the donors have by then also COMPACTED past it, so a chain-replay
    tail is no longer even possible — then respawn the victim and require
    it to come back via snapshot install + tail.

    ``mid_fetch_donor_kill`` SIGKILLs the serving donor while the victim
    is mid-chunk (then respawns it): the fetch must resume or fail over
    to another offer, never wedge.

    The cluster MUST be built with ``snapshot_interval_decisions > 0``
    in ``config_overrides``.  NOTE: :func:`run_socket_schedule`'s
    resubmission oracle reads ``committed_ids`` (suffix-only once a
    replica compacts) and is NOT snapshot-safe; this runner uses the
    count/ids-digest oracles, which survive compaction.

    Returns the report; raises AssertionError/TimeoutError on any
    violated invariant (rejoined-but-not-via-snapshot counts as one).
    """
    report = SnapshotRejoinReport(victim=victim)
    lead = cluster.wait_leader()
    if victim == lead:
        victim = next(i for i in cluster.live_ids() if i != lead)
        report.victim = victim
    total = 0

    def _submit_one() -> None:
        nonlocal total
        cluster.submit(lead, "snaprejoin", f"sr-{total}")
        total += 1

    for _ in range(warmup):
        _submit_one()
    cluster.wait_committed(total, timeout=settle_timeout)

    # -- kill the victim (racing its own capture when asked) ------------
    victim_h = cluster.heights().get(victim, 0)
    if crash_during_snapshot:
        before = _snapshot_height_or(cluster, victim)
        deadline = time.monotonic() + settle_timeout / 3
        while (_snapshot_height_or(cluster, victim) <= before
               and time.monotonic() < deadline):
            _submit_one()
            try:
                victim_h = cluster.control(victim).call(cmd="height")["height"]
            except (OSError, ControlError):
                pass
            time.sleep(0.05)
        cluster.kill(victim)
        report.events.append("crash_during_snapshot")
    else:
        cluster.kill(victim)
        report.events.append("crash")
    report.victim_height_at_kill = victim_h
    donors = [i for i in cluster.live_ids() if i != victim]

    # -- grow history until every donor's horizon passed the victim -----
    for _ in range(history):
        _submit_one()
    cluster.wait_committed(total, timeout=settle_timeout, nodes=donors)
    deadline = time.monotonic() + settle_timeout / 2
    while min(_snapshot_height_or(cluster, d) for d in donors) <= victim_h:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"donor snapshot horizon never passed the victim's crash "
                f"height {victim_h}: "
                f"{[(d, _snapshot_height_or(cluster, d)) for d in donors]}"
            )
        _submit_one()
        cluster.wait_committed(total, timeout=settle_timeout, nodes=donors)
        time.sleep(0.05)
    report.donor_snapshot_height = min(
        _snapshot_height_or(cluster, d) for d in donors
    )

    # -- respawn: the rejoin itself --------------------------------------
    t0 = time.monotonic()
    cluster.restart(victim)
    report.events.append("restart")
    if mid_fetch_donor_kill:
        fetch_deadline = time.monotonic() + settle_timeout / 2
        while time.monotonic() < fetch_deadline:
            try:
                st = cluster.control(victim).call(cmd="stats")["transport"]
                if int(st.get("snap_chunks_received", 0)) > 0:
                    break
            except (OSError, ControlError):
                pass
            time.sleep(0.005)
        # kill the busiest non-leader donor mid-transfer, then respawn it
        stats = cluster.transport_stats()
        candidates = [d for d in donors if d != lead] or donors
        serving = max(
            candidates,
            key=lambda d: stats.get(d, {}).get("snap_chunks_sent", 0),
        )
        cluster.kill(serving)
        report.events.append(f"donor_kill:{serving}")
        time.sleep(1.0)
        cluster.restart(serving)
        report.events.append(f"donor_restart:{serving}")
    cluster.wait_committed(total, timeout=settle_timeout)
    cluster.wait_quiescent(timeout=settle_timeout)
    report.rejoin_seconds = round(time.monotonic() - t0, 3)
    report.requests = total

    # -- oracles ---------------------------------------------------------
    vs = cluster.snapshot_stats(victim)
    report.victim_base_after = int(vs.get("base_height", 0))
    report.victim_height_after = int(vs.get("height", 0))
    stats = cluster.transport_stats()
    report.snap_chunks_received = int(
        stats.get(victim, {}).get("snap_chunks_received", 0))
    report.snap_bytes_received = int(
        stats.get(victim, {}).get("snap_bytes_received", 0))
    report.snap_chunks_sent_total = sum(
        int(s.get("snap_chunks_sent", 0)) for s in stats.values())
    report.sync_poisoned_total = sum(
        int(s.get("sync_poisoned", 0)) for s in stats.values())
    assert report.victim_base_after > victim_h, (
        f"victim rejoined by CHAIN REPLAY, not snapshot install: base "
        f"{report.victim_base_after} <= crash height {victim_h}"
    )
    assert report.snap_chunks_received > 0, (
        "victim caught up without receiving a single snapshot chunk"
    )
    assert report.snap_chunks_sent_total > 0, "no donor served chunks"
    assert report.sync_poisoned_total == 0, (
        f"honest-cluster run tripped the poisoning guard "
        f"{report.sync_poisoned_total} times"
    )
    heights = cluster.heights()
    assert len(set(heights.values())) == 1, f"heights diverge: {heights}"
    cluster.check_fork_free()
    return report


def snapshot_soak(*, rounds: int = 2, n: int = 4, transport: str = "uds",
                  interval: int = 8, verbose: bool = True) -> None:
    """``chaos --soak --snapshots`` (ISSUE 17): the truncating soak.
    Each round runs rejoin-via-snapshot then crash-during-snapshot (with
    a donor SIGKILLed mid-chunk in the second) against a fresh cluster
    captured every ``interval`` decisions with deliberately tiny chunks
    (multi-chunk transfers even for small states).  Beyond the rejoin
    oracles, each round pins the DISK BOUND: every replica's live ledger
    suffix stays within ~2 capture intervals of its snapshot horizon no
    matter how long the chain grows, and the final cluster verdict is
    not critical (snapshot.lag_intervals unbreached)."""
    overrides = {
        "snapshot_interval_decisions": interval,
        "snapshot_chunk_bytes": 1024,
    }
    for r in range(rounds):
        for name, kwargs in (
            ("rejoin-via-snapshot", {}),
            ("crash-during-snapshot",
             {"crash_during_snapshot": True, "mid_fetch_donor_kill": True}),
        ):
            with tempfile.TemporaryDirectory(prefix="sbft-snap-") as root:
                cluster = SocketCluster(root, n=n, transport=transport,
                                        config_overrides=overrides)
                try:
                    cluster.start()
                    cluster.wait_leader()
                    report = run_snapshot_rejoin(cluster, **kwargs)
                    for i in cluster.live_ids():
                        s = cluster.snapshot_stats(i)
                        suffix = int(s["height"]) - int(s["base_height"])
                        assert suffix <= 2 * interval + 8, (
                            f"node {i} ledger suffix unbounded: {suffix} "
                            f"decisions past its snapshot horizon "
                            f"(interval {interval})"
                        )
                        assert int(s["ledger_disk_bytes"]) > 0
                    verdict = cluster.cluster_health()
                    assert verdict["status"] != "critical", verdict
                finally:
                    cluster.stop()
                if verbose:
                    print(
                        f"snapshot round {r} [{name}]: events="
                        f"{report.events} requests={report.requests} "
                        f"victim_h@kill={report.victim_height_at_kill} "
                        f"base_after={report.victim_base_after} "
                        f"chunks={report.snap_chunks_received} "
                        f"rejoin={report.rejoin_seconds}s — OK"
                    )
