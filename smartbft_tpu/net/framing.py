"""Length-prefixed framing over the canonical wire encoding.

One frame on the socket is::

    u32 big-endian length L  |  1-byte frame type  |  payload (L-1 bytes)

``L`` counts the type byte plus the payload, so ``L >= 1`` always; a
length of zero, a length above the negotiated cap, or an unknown frame
type is a :class:`FrameError` — the transport treats any of them as a
poisoned stream and drops THAT connection loudly (counted in its
metrics) without crashing the replica.  TCP gives no other framing
recovery point: once a length prefix is wrong, every later byte is
garbage, so closing and letting the peer reconnect is the only sound
move.

Frame types:

* ``FT_HELLO``      — first frame on every connection: identifies the
  dialing node and carries the shared cluster key (replicas share ONLY
  key material and the peer address map);
* ``FT_CONSENSUS``  — one consensus message in the canonical tagged
  encoding (exactly the bytes ``messages.wire_of`` produces; the
  receive side decodes through ``messages.unmarshal_interned``);
* ``FT_REQUEST``    — a raw client request (the ``send_transaction``
  SPI surface, also how the pool forwards requests to the leader);
* ``FT_SYNC_REQ`` / ``FT_SYNC_RESP`` — ledger catch-up for the
  multi-process cluster (a restarted replica has no in-process shared
  ledger to sync from), correlated by nonce; a SYNC_RESP may carry a
  snapshot OFFER instead of (or alongside) a tail when the requester is
  behind the responder's snapshot horizon (ISSUE 17);
* ``FT_SNAP_REQ`` / ``FT_SNAP_RESP`` — chunked snapshot state transfer
  (ISSUE 17): byte-offset paging of one snapshot file under the frame
  cap, nonce-correlated, resumable after reconnect by re-requesting
  from the current offset;
* ``FT_REJECT``     — structured shed notice travelling the REVERSE
  direction of an ``FT_REQUEST``: the receiving replica's pool refused
  the request (admission gate / bounded-wait timeout), and the sender —
  which fronts the client — gets the PR 8 admission contract (shed kind,
  retry-after hint, occupancy snapshot) instead of silence.  Advisory:
  the protocol's forward/complain timers keep running either way.
* ``FT_READ_REQ`` / ``FT_READ_RESP`` — the read/serving plane
  (ISSUE 19): a keyed read executed at a replica against COMMITTED
  state only — no pool, no proposer, no verify launch.  The reply is
  stamped ``(value, height, state_digest, anchor_height)`` so a client
  can either fan the read to several replicas and accept on ``f+1``
  bit-identical stamps (quorum read) or accept a single reply under an
  explicit staleness bound (follower read).  Reads have their own
  token-bucket gate at the serving replica: a shed reply carries the
  FT_REJECT contract fields (kind / retry-after / occupancy) inline,
  correlated by nonce instead of request digest, and NEVER touches the
  write-path admission gate — a read storm degrades reads, not writes;
* ``FT_TRACE``      — cluster-tracing SIDECAR (ISSUE 13): a batch of
  compact correlation contexts (request key / (view, seq), origin node,
  monotonic hop counter) describing the data frames of the SAME
  write-coalesced flush, stamped with the sender's monotonic clock at
  flush time.  Strictly advisory telemetry: it rides only when the
  sender's flight recorder is armed, the canonical signed consensus
  encoding is untouched (same rule as FT_REJECT — the sidecar is a
  separate untagged frame, never a trailer on a consensus frame), and a
  receiver without tracing just updates its hop memory and moves on.
  Loss is tolerated by construction — a dropped sidecar frame costs
  timeline coverage, never correctness.

The handshake / sync payloads are encoded with the UNTAGGED canonical
codec (``codec.encode`` / ``codec.decode``): the frame type already
names the class, and keeping them out of the tagged-union registry
means their registration order can never perturb the consensus tag
space that every replica must agree on byte-exactly.
"""

from __future__ import annotations

import struct

from ..codec import wiremsg
from ..messages import Proposal, Signature

_U32 = struct.Struct(">I")

#: hard cap on one frame (length prefix included payload), matching the
#: Configuration default ``transport_max_frame_bytes``.  A proposal is
#: bounded by request_batch_max_bytes (default 10 MiB) plus headers, so
#: 16 MiB passes every legitimate frame while a hostile/corrupt length
#: prefix (e.g. 4 GiB) is rejected before any allocation.
DEFAULT_MAX_FRAME_BYTES = 16 * 1024 * 1024

FT_HELLO = 1
FT_CONSENSUS = 2
FT_REQUEST = 3
FT_SYNC_REQ = 4
FT_SYNC_RESP = 5
FT_REJECT = 6
FT_TRACE = 7
FT_SNAP_REQ = 8
FT_SNAP_RESP = 9
FT_READ_REQ = 10
FT_READ_RESP = 11

_KNOWN_TYPES = frozenset(
    (FT_HELLO, FT_CONSENSUS, FT_REQUEST, FT_SYNC_REQ, FT_SYNC_RESP,
     FT_REJECT, FT_TRACE, FT_SNAP_REQ, FT_SNAP_RESP, FT_READ_REQ,
     FT_READ_RESP)
)


class FrameError(Exception):
    """Unrecoverable stream corruption: the connection must be dropped."""


def encode_frame(ftype: int, payload: bytes) -> bytes:
    """``u32 length | type | payload`` — the only writer of the format."""
    return _U32.pack(1 + len(payload)) + bytes([ftype]) + payload


class FrameDecoder:
    """Incremental frame extraction over arbitrary read() chunk boundaries.

    ``feed`` accepts ANY split of the byte stream — one byte at a time,
    half a length prefix, three frames in one chunk — and returns every
    complete ``(type, payload)`` it can; partial frames wait in the
    buffer for more bytes.  Raises :class:`FrameError` on a zero /
    oversized length prefix or an unknown frame type, leaving the caller
    exactly one sound option: drop the connection.
    """

    __slots__ = ("_buf", "_max_frame")

    def __init__(self, max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self._buf = bytearray()
        self._max_frame = max_frame_bytes

    def __len__(self) -> int:
        return len(self._buf)

    def feed(self, data: bytes) -> list[tuple[int, bytes]]:
        buf = self._buf
        buf += data
        frames: list[tuple[int, bytes]] = []
        off = 0
        try:
            while len(buf) - off >= 4:
                length = _U32.unpack_from(buf, off)[0]
                if length == 0:
                    raise FrameError("zero-length frame")
                if length > self._max_frame:
                    raise FrameError(
                        f"frame length {length} exceeds cap {self._max_frame}"
                    )
                if len(buf) - off - 4 < length:
                    break  # partial frame: wait for more bytes
                ftype = buf[off + 4]
                if ftype not in _KNOWN_TYPES:
                    raise FrameError(f"unknown frame type {ftype}")
                payload = bytes(buf[off + 5 : off + 4 + length])
                frames.append((ftype, payload))
                off += 4 + length
        finally:
            # consume what we parsed even when raising: diagnostics read
            # cleaner when the poisoned prefix is at offset 0
            del buf[:off]
        return frames


# --------------------------------------------------------------------------
# handshake / sync wire messages (untagged encoding; see module docstring)
# --------------------------------------------------------------------------


@wiremsg
class Hello:
    """First frame on every connection (both directions are dialed
    separately: each node's outbound connection carries only its sends)."""

    node_id: int = 0
    group: int = 0
    key: bytes = b""


def reject_digest(request: bytes) -> bytes:
    """Constant-size correlation id for a rejected request: echoing the
    FULL request back would roughly double per-request bandwidth exactly
    when the link is already saturated (rejects fire under overload)."""
    import hashlib

    return hashlib.blake2b(bytes(request), digest_size=16).digest()


@wiremsg
class RejectFrame:
    """Structured shed notice for one FT_REQUEST (untagged encoding, like
    every control-plane frame).  ``kind`` is the PR 8 shed cause
    ("admission" | "timeout"); ``retry_after_ms`` the drain-rate-derived
    hint (0 = no hint, as for bounded-wait timeouts); ``request_digest``
    is :func:`reject_digest` of the rejected raw request — a fixed-size
    correlation id the forwarder can match against its in-flight set
    without any shared nonce state (and without the overload-amplifying
    full echo); ``occupancy``/``high_water`` snapshot the gate's inputs
    at rejection time (0/0 when unavailable)."""

    kind: str = ""
    reason: str = ""
    retry_after_ms: int = 0
    occupancy: int = 0
    high_water: int = 0
    request_digest: bytes = b""


@wiremsg
class TraceCtx:
    """One correlation context riding an FT_TRACE sidecar (untagged
    encoding).  ``kind`` is the traced frame's flavor — the consensus
    message class name (``"PrePrepare"``/``"Prepare"``/``"Commit"``/…)
    or ``"request"`` for an FT_REQUEST — ``key`` the request key
    (``"client:rid"``) when the embedder supplied a
    ``request_key_fn``, ``(view, seq)`` the consensus correlator,
    ``origin`` the node that CREATED the context (not necessarily the
    sender of this hop), and ``hop`` the monotonic wire-hop counter:
    1 for a first send, incremented each time a replica re-forwards a
    request whose inbound context it remembered."""

    kind: str = ""
    key: str = ""
    view: int = 0
    seq: int = 0
    origin: int = 0
    hop: int = 0


@wiremsg
class TraceFrame:
    """The FT_TRACE sidecar payload: every data frame of ONE
    write-coalesced flush described in one batch, stamped with the
    sender's ``time.monotonic`` at flush time (microseconds).  The
    receiver's ingest timestamp minus ``sent_us`` — after the control-
    channel clock-offset alignment maps both onto one timeline — is the
    per-hop network time."""

    origin: int = 0
    sent_us: int = 0
    entries: list[TraceCtx] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.entries is None:
            object.__setattr__(self, "entries", [])


@wiremsg
class SyncRequest:
    """Fetch committed decisions from ``from_height`` (0-based) onward."""

    nonce: int = 0
    from_height: int = 0


@wiremsg
class WireDecision:
    """One committed decision (types.Decision) in wire form."""

    proposal: Proposal = None  # type: ignore[assignment]
    signatures: list[Signature] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.proposal is None:
            object.__setattr__(self, "proposal", Proposal())
        if self.signatures is None:
            object.__setattr__(self, "signatures", [])


@wiremsg
class SyncBatch:
    """Response to :class:`SyncRequest` — the responder's ledger tail,
    capped per round trip in BOTH decisions (``MAX_SYNC_DECISIONS``) and
    encoded bytes (a margin under ``transport_max_frame_bytes`` — a deep
    tail must page across nonce-correlated continuation requests, never
    exceed the frame cap in one reply).

    Snapshot offer (ISSUE 17): when the responder has compacted its
    ledger behind a snapshot horizon above the requested height — or the
    requester is simply too far behind — ``snapshot_height`` /
    ``snapshot_bytes`` / ``snapshot_digest`` describe the snapshot the
    requester should fetch over FT_SNAP_REQ/FT_SNAP_RESP instead of
    paging the whole chain.  ``snapshot_height == 0`` means no offer;
    ``decisions`` then starts at ``from_height`` as before.  An offer
    can ride WITH a (possibly empty) tail: the requester installs the
    snapshot first, then pages the suffix."""

    nonce: int = 0
    from_height: int = 0
    total_height: int = 0
    decisions: list[WireDecision] = None  # type: ignore[assignment]
    snapshot_height: int = 0
    snapshot_bytes: int = 0
    snapshot_digest: bytes = b""

    def __post_init__(self):
        if self.decisions is None:
            object.__setattr__(self, "decisions", [])


@wiremsg
class SnapshotFetchRequest:
    """Fetch one chunk of the peer's snapshot at ``height`` starting at
    byte ``offset`` (nonce-correlated like :class:`SyncRequest`).
    Resume-after-reconnect = re-issuing from the current offset — the
    requester buffers received chunks in memory only, so a crashed
    transfer restarts clean."""

    nonce: int = 0
    height: int = 0
    offset: int = 0
    max_bytes: int = 0


@wiremsg
class SnapshotChunk:
    """One bounded slice of snapshot file bytes (manifest + state blob,
    exactly the on-disk format).  ``total_bytes`` lets the requester
    pre-size and detect completion; ``last`` marks the final chunk.  A
    responder whose snapshot at ``height`` is gone (superseded mid-
    transfer) answers ``total_bytes == 0`` — the requester restarts
    against the peer's CURRENT offer."""

    nonce: int = 0
    height: int = 0
    total_bytes: int = 0
    offset: int = 0
    data: bytes = b""
    last: bool = False


@wiremsg
class ReadRequest:
    """One keyed read against a replica's COMMITTED state
    (nonce-correlated like :class:`SyncRequest`).  ``key`` names the
    committed-state entry to read (the test embedders key by client id);
    ``at_base`` asks the replica to answer from its latest verified
    snapshot BASE instead of live state — the snapshot-anchored path a
    client uses when it wants a reply whose digest is pinned by an
    anchor certificate rather than by the live chain."""

    nonce: int = 0
    key: str = ""
    at_base: bool = False


@wiremsg
class ReadResponse:
    """The read-plane reply.  ``value`` is the committed value for
    ``key`` (empty when the key has never been written — ``found``
    disambiguates an empty value from a missing key); ``height`` is the
    delivered-decision count the value reflects; ``state_digest`` the
    chained ledger digest at that height (bit-identical across honest
    replicas, so ``f+1`` matching ``(value, height, state_digest)``
    stamps prove the value is committed); ``anchor_height`` the height
    of the newest snapshot anchor certificate at answer time (0 = none
    yet).  A gated read comes back with ``shed=True`` and the FT_REJECT
    contract fields (``shed_kind``/``retry_after_ms``/``occupancy``/
    ``high_water``) instead of a value — the nonce correlates it, so no
    request digest is needed."""

    nonce: int = 0
    key: str = ""
    found: bool = False
    value: bytes = b""
    height: int = 0
    state_digest: bytes = b""
    anchor_height: int = 0
    at_base: bool = False
    shed: bool = False
    shed_kind: str = ""
    retry_after_ms: int = 0
    occupancy: int = 0
    high_water: int = 0


# --------------------------------------------------------------------------
# addresses
# --------------------------------------------------------------------------


def parse_addr(addr: str) -> tuple[str, str, int]:
    """``tcp://host:port`` or ``uds:///path`` -> (scheme, host_or_path, port).

    Raises ValueError on anything else — addresses come from operator
    config and must fail loudly, not fall back.
    """
    if addr.startswith("tcp://"):
        rest = addr[len("tcp://") :]
        host, sep, port = rest.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(f"malformed tcp address: {addr!r}")
        return "tcp", host, int(port)
    if addr.startswith("uds://"):
        path = addr[len("uds://") :]
        if not path:
            raise ValueError(f"malformed uds address: {addr!r}")
        return "uds", path, 0
    raise ValueError(f"unsupported transport address scheme: {addr!r}")
