"""One-replica process entry point: ``python -m smartbft_tpu.net.launch``.

A replica process is a :class:`ReplicaApp` (every SPI interface,
implemented for a process that shares NOTHING in memory with its peers)
wired to a :class:`~smartbft_tpu.net.transport.SocketComm` and a
Consensus facade running on its own wall-clock driver.  Processes share
only key material and the peer address map — exactly the deployment
contract of the paper's embedder.

What replaces the in-process harness's shared state:

* **Ledger** — each committed decision is appended (length-prefixed
  frame, ``framing.WireDecision``) to a per-replica ledger file.  On
  restart the file is replayed with torn-tail tolerance (a SIGKILL
  mid-append loses at most the partial tail record; the replica then
  catches up over the wire like any lagging peer).
* **Synchronizer** — ``sync()`` asks every peer for its ledger tail over
  the transport's SYNC_REQ/SYNC_RESP frames (nonce-correlated, batched
  at ``MAX_SYNC_DECISIONS`` per round trip) and applies the longest
  consistent extension.  This is what makes SIGKILL-and-rejoin a real
  scenario instead of a shared-memory illusion.
* **Control channel** — a tiny line-JSON server (its own UDS/TCP
  listener, NOT the consensus transport) the parent cluster manager
  uses to submit requests, read heights/digests/transport stats, inject
  socket-level faults, and request graceful shutdown.

Crypto is trivial (signature = node id), matching the in-process
harness's default: this subsystem proves the TRANSPORT, the crypto
planes are proven elsewhere and plug in through the same SPI.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import threading
from typing import Optional

from .. import wal as walmod
from ..api import (
    Application,
    Assembler,
    Comm,
    MembershipNotifier,
    RequestInspector,
    Signer,
    Synchronizer,
    Verifier,
)
from ..codec import decode, encode, wiremsg
from ..config import Configuration
from ..consensus import Consensus
from ..core.readplane import (
    ReadStats,
    TokenBucket,
    follower_read_accept,
    quorum_read_decide,
    session_retry_after_ms,
)
from ..core.util import compute_quorum
from ..messages import Proposal, Signature, ViewMetadata
from ..snapshot import (
    CHAIN_SEED,
    RECENT_IDS_CAP,
    AppState,
    SnapshotStore,
    chain_update,
    fold_ids,
    make_manifest,
    parse_snapshot_blob,
    verify_snapshot,
    verify_tail,
)
from ..types import Decision, Reconfig, RequestInfo, SyncResponse
from ..utils.logging import StdLogger
from ..utils.memo import BoundedMemo
from .framing import (
    FrameDecoder,
    FrameError,
    ReadRequest,
    ReadResponse,
    WireDecision,
    encode_frame,
    parse_addr,
)
from .transport import MAX_SYNC_DECISIONS, SocketComm

#: ledger-file frame types (framing reserves 1..9 for the socket
#: protocol; the ledger file is a private on-disk format, any tag works
#: as long as the reader and writer agree — but reusing FrameDecoder
#: keeps torn-tail handling in one place, so the tags must be known
#: ones).  _FT_LEDGER frames one committed decision; _FT_LEDGER_BASE is
#: the optional LEADING frame of a compacted file: the snapshot
#: reference that replaces the deleted pre-horizon prefix.
from .framing import FT_SYNC_RESP as _FT_LEDGER  # noqa: E402
from .framing import FT_SNAP_REQ as _FT_LEDGER_BASE  # noqa: E402

#: donor-shun threshold (ISSUE 18): once a peer has served this many
#: poisoned sync tails / snapshot blobs, the synchronizer stops asking it
#: at all — a liar that keeps lying costs one request timeout per sync
#: round forever otherwise.  Certificate checks already make the lies
#: harmless; this just stops paying for them.  Deliberately small and
#: not config-plumbed: honest donors score 0 (stale races skip QUIETLY in
#: phase 1 and never count), so any nonzero streak is a tamperer.
SYNC_DONOR_SHUN_THRESHOLD = 3


@wiremsg
class LedgerBaseRef:
    """The compacted ledger's leading frame: decisions ``1..height`` were
    replaced by the snapshot at ``height`` whose chained ledger digest is
    ``chain_digest`` — recovery seeds the chain there and replays only
    the suffix, arriving at a digest bit-identical to a full replay.

    ``app_state`` (an encoded :class:`~smartbft_tpu.snapshot.AppState`)
    and ``anchor`` (an encoded :class:`WireDecision` — the certificate at
    ``height``) duplicate the snapshot file's seeding material INSIDE the
    ledger: a replica whose snapshot directory is lost or corrupted after
    compaction can still recover its app counters and its consensus
    metadata instead of restarting at sequence zero."""

    height: int = 0
    chain_digest: bytes = b""
    app_state: bytes = b""
    anchor: bytes = b""


def proc_config(self_id: int) -> Configuration:
    """Wall-clock configuration for a localhost multi-process cluster:
    the socket twin of ``testing.app.fast_config`` — timeouts sized for
    real time on one machine (RTT ~50 us), snappy enough that the smoke
    gate's kill/rejoin cycles finish inside the tier-1 budget."""
    return Configuration(
        self_id=self_id,
        request_batch_max_count=10,
        request_batch_max_bytes=10 * 1024 * 1024,
        request_batch_max_interval=0.02,
        incoming_message_buffer_size=400,
        request_pool_size=800,
        request_forward_timeout=1.0,
        # round-16 fix: derive the EFFECTIVE forward timeout from the
        # transport's measured RTT (localhost: µs → clamped to the 10 ms
        # floor) instead of waiting out the full constant above — which
        # the cluster timeline measured as 97.6% of follower-submitted
        # request latency.  The constant stays the ceiling/fallback.
        request_forward_rtt_multiplier=20.0,
        request_complain_timeout=4.0,
        request_auto_remove_timeout=60.0,
        view_change_resend_interval=1.0,
        view_change_timeout=6.0,
        leader_heartbeat_timeout=3.0,
        leader_heartbeat_count=10,
        num_of_ticks_behind_before_syncing=10,
        collect_timeout=0.5,
        # off, like the in-process fast_config: a fresh replica starts at
        # its recovered height and catches up through the behind-by-
        # heartbeat sync path; sync_on_start=True measurably destabilizes
        # the first seconds of a wall-clock cluster (start-time syncs
        # contend with the first commit waves for the sync lock)
        sync_on_start=False,
        speed_up_view_change=False,
        leader_rotation=False,
        decisions_per_leader=0,
        transport_outbox_cap=4096,
        transport_reconnect_backoff_base=0.02,
        transport_reconnect_backoff_max=0.5,
    )


class LedgerFile:
    """Append-only committed-decision log with torn-tail-tolerant replay
    and snapshot-horizon compaction (ISSUE 17).

    Frames are ``framing`` frames; a truncated/corrupt tail record (the
    SIGKILL case) ends the replay instead of raising — the replica simply
    restarts a few decisions behind and syncs the rest from its peers.

    A COMPACTED file begins with a :class:`LedgerBaseRef` frame: the
    decisions behind the snapshot horizon were deleted and replaced by
    the reference (height + chained digest).  ``read_all`` then returns
    only the suffix, with ``base_height``/``base_digest`` exposing where
    it starts.  ``compact`` rewrites the file (temp + fsync + atomic
    rename — the same crash contract as the snapshot store) so a crash
    mid-compaction leaves either the old full file or the new compacted
    one, never a truncated hybrid."""

    def __init__(self, path: str):
        self.path = path
        self._fh = None
        #: decisions compacted away: the file's suffix starts at
        #: base_height (0 = never compacted, full chain on disk)
        self.base_height = 0
        #: chained ledger digest at base_height (CHAIN_SEED when 0)
        self.base_digest = CHAIN_SEED
        #: encoded AppState / WireDecision at the base (b"" when 0)
        self.base_state = b""
        self.base_anchor = b""

    def read_all(self) -> list[Decision]:
        decisions: list[Decision] = []
        self.base_height = 0
        self.base_digest = CHAIN_SEED
        self.base_state = b""
        self.base_anchor = b""
        if not os.path.exists(self.path):
            return decisions
        decoder = FrameDecoder()
        with open(self.path, "rb") as fh:
            data = fh.read()
        try:
            frames = decoder.feed(data)
        except FrameError:
            frames = []  # poisoned mid-file: at worst we resync everything
        for i, (ftype, payload) in enumerate(frames):
            if ftype == _FT_LEDGER_BASE:
                if i != 0:
                    break  # a base ref anywhere but first is corruption
                try:
                    ref = decode(LedgerBaseRef, payload)
                except Exception:
                    break  # torn base frame: treat as empty suffix
                self.base_height = ref.height
                self.base_digest = ref.chain_digest
                self.base_state = ref.app_state
                self.base_anchor = ref.anchor
                continue
            try:
                wd = decode(WireDecision, payload)
            except Exception:
                break  # torn tail
            decisions.append(
                Decision(proposal=wd.proposal, signatures=tuple(wd.signatures))
            )
        return decisions

    def open_append(self) -> None:
        self._fh = open(self.path, "ab")

    def append(self, decision: Decision) -> None:
        wd = WireDecision(
            proposal=decision.proposal, signatures=list(decision.signatures)
        )
        self._fh.write(encode_frame(_FT_LEDGER, encode(wd)))
        self._fh.flush()

    def compact(self, base_height: int, base_digest: bytes,
                suffix: list[Decision], *, app_state: bytes = b"",
                anchor: bytes = b"") -> None:
        """Replace the pre-horizon prefix with a snapshot reference:
        rewrite the file as ``[LedgerBaseRef, suffix...]`` atomically and
        reopen the append handle on the new file."""
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as fh:
            ref = LedgerBaseRef(height=base_height, chain_digest=base_digest,
                                app_state=app_state, anchor=anchor)
            fh.write(encode_frame(_FT_LEDGER_BASE, encode(ref)))
            for d in suffix:
                wd = WireDecision(proposal=d.proposal,
                                  signatures=list(d.signatures))
                fh.write(encode_frame(_FT_LEDGER, encode(wd)))
            fh.flush()
            os.fsync(fh.fileno())
        reopen = self._fh is not None
        if reopen:
            self._fh.close()
            self._fh = None
        os.replace(tmp, self.path)
        dir_fd = os.open(os.path.dirname(os.path.abspath(self.path)),
                         os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        self.base_height = base_height
        self.base_digest = base_digest
        self.base_state = app_state
        self.base_anchor = anchor
        if reopen:
            self.open_append()

    def disk_bytes(self) -> int:
        try:
            if self._fh is not None:
                self._fh.flush()
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class _SnapshotServer:
    """The transport's duck-typed snapshot hook: serves the replica's
    current snapshot offer as bounded chunks read straight off the file
    (never materializing the blob in memory per request)."""

    def __init__(self, replica: "ReplicaApp"):
        self.replica = replica

    def describe(self):
        return self.replica._snap_offer

    def read_chunk(self, height: int, offset: int,
                   max_bytes: int) -> tuple[int, bytes, bool]:
        offer = self.replica._snap_offer
        if offer is None or offer[0] != height:
            return 0, b"", False  # gone/superseded: requester restarts
        # satellite 2 (ISSUE 19): byte access goes through the store's
        # single file-open surface, shared with the read-at-base path
        total, data, last = self.replica.snapshot_store.read_range(
            height, offset, max_bytes
        )
        if total == 0:
            return 0, b"", False
        return total, data, last


class ReplicaApp(Application, Assembler, Comm, Signer, Verifier,
                 RequestInspector, Synchronizer, MembershipNotifier):
    """The multi-process embedder: one OS process, no shared memory."""

    #: ledger appends are a buffered write + flush — cheap enough to run
    #: inline on the event loop instead of paying an executor round-trip
    blocking_deliver = False

    def __init__(self, spec: dict):
        self.spec = spec
        self.id = int(spec["node_id"])
        self.logger = StdLogger(f"replica-{self.id}")
        self.config = _config_from_spec(spec)
        self.peers = {int(k): v for k, v in spec["peers"].items()}
        self.transport = SocketComm.from_config(
            self.config,
            self.peers,
            listen=spec["listen"],
            cluster_key=bytes.fromhex(spec.get("cluster_key", "")),
            logger=self.logger,
        )
        self.transport.sync_server = self._serve_sync
        # per-replica pull-based observability (ISSUE 12): a Prometheus
        # text-exposition provider ALWAYS (counters are cheap and the
        # control channel's cmd=metrics needs something to read), the
        # flight recorder only when the spec asks (cmd=trace then serves
        # the per-replica timeline to SocketCluster / operators)
        from ..metrics import MetricsBundle, PrometheusProvider
        from ..obs import NOP_RECORDER, TraceRecorder

        self.metrics_provider = PrometheusProvider()
        self.metrics = MetricsBundle(self.metrics_provider)
        if spec.get("trace"):
            self.recorder = TraceRecorder(
                node=f"n{self.id}",
                capacity=int(spec.get("trace_capacity", 2048)),
            )
        else:
            self.recorder = NOP_RECORDER
        self.transport.recorder = self.recorder
        # cluster health plane (ISSUE 14): every replica judges itself
        # against the declarative SLO spec on a periodic tick; cmd=health
        # serves the verdict, SocketCluster.cluster_health aggregates n
        # of them.  Breach/clear transitions land in the flight recorder
        # (when armed) so SLO violations show on the merged timeline.
        from ..obs.health import HealthMonitor

        self.health = HealthMonitor(recorder=self.recorder,
                                    node=f"n{self.id}")
        self.health_interval = float(spec.get("health_interval", 0.25))
        self._health_task = None
        # FT_TRACE sidecars carry the SAME "client:rid" correlator the
        # recorder stamps on req.submit/req.deliver (request_id memoizes,
        # so the per-forward cost is a dict hit once warm)
        self.transport.request_key_fn = \
            lambda raw: str(self.request_id(raw))
        self.ledger_file = LedgerFile(spec["ledger_path"])
        self.lock = threading.Lock()
        #: committed-decision SUFFIX: ledger[i] is the decision at
        #: absolute sequence _base_height + i + 1.  Before the first
        #: compaction _base_height is 0 and this is the whole chain.
        self.ledger: list[Decision] = []
        self._base_height = 0
        self._base_chain = CHAIN_SEED
        #: chained ledger digest over ALL committed decisions (compacted
        #: prefix included) — the fork detector that survives compaction
        self._chain = CHAIN_SEED
        #: bounded app state (what a snapshot carries): delivered-request
        #: count, chained request-id digest, recent-id dedup window
        self._request_count = 0
        self._ids_digest = CHAIN_SEED
        from collections import deque

        self._recent_ids: deque = deque(maxlen=RECENT_IDS_CAP)
        #: the certificate at _base_height — serves as SyncResponse.latest
        #: when the suffix is empty (a freshly installed snapshot)
        self._anchor_decision: Optional[Decision] = None
        self.snapshot_store = SnapshotStore(
            spec.get("snap_dir") or spec["ledger_path"] + "-snapshots"
        )
        #: (height, total_bytes, digest) of the snapshot on offer + its
        #: file path — what the transport's FT_SNAP plane serves
        self._snap_offer: Optional[tuple[int, int, bytes]] = None
        self._snap_path = ""
        self._snap_inflight = False
        self._last_snapshot_height = 0
        #: per-peer count of LOUDLY rejected sync material (tampered
        #: tails / snapshots that failed certificate verification)
        self.sync_poisoned: dict[int, int] = {}
        self.transport.snapshot_server = _SnapshotServer(self)
        # read plane (ISSUE 19): the committed KV view (key = client id,
        # value = that client's latest committed payload — deterministic
        # over the committed order, so honest replicas' read stamps match
        # bit-exactly), its token-bucket gate (reads bypass the write
        # pool's admission entirely; a read storm drains THIS bucket and
        # sheds reads, never writes), serving counters, and the bounded
        # watch registry for committed-stream subscriptions
        self._kv: dict[str, bytes] = {}
        self._read_gate = TokenBucket(self.config.read_gate_rate,
                                      self.config.read_gate_burst)
        self.read_stats = ReadStats()
        self.transport.read_server = self._serve_read
        self._watches: dict[int, dict] = {}
        self._watch_seq = 0
        # ISSUE 17 disk gauges (promlint-clean: consensus_<sub>_<name>)
        from ..metrics import MetricOpts

        _g = self.metrics_provider.new_gauge
        self.snapshot_age_gauge = _g(MetricOpts(
            namespace="consensus", subsystem="snapshot",
            name="age_decisions",
            help="decisions committed since the last snapshot"))
        self.snapshot_disk_gauge = _g(MetricOpts(
            namespace="consensus", subsystem="snapshot", name="disk_bytes",
            help="bytes of snapshot files on disk"))
        self.ledger_disk_gauge = _g(MetricOpts(
            namespace="consensus", subsystem="ledger", name="disk_bytes",
            help="bytes of the (compacted) ledger file on disk"))
        self.wal_disk_gauge = _g(MetricOpts(
            namespace="consensus", subsystem="wal", name="disk_bytes",
            help="bytes of live WAL segments on disk"))
        self.verification_seq = 0
        self.membership_changed = False
        self.consensus: Optional[Consensus] = None
        self._wal = None
        self._request_id_cache: BoundedMemo[bytes, RequestInfo] = BoundedMemo()
        #: epoch -> committed barrier ledger seq (immutable once found) and
        #: epoch -> ledger index already scanned without finding it — the
        #: reshard manager polls barrier_seq every ~100 ms, so each poll
        #: must cost O(new entries), not O(ledger)
        self._barrier_seqs: dict[int, int] = {}
        self._barrier_scan: dict[int, int] = {}
        #: ISSUE 19 satellite 1: committed_ids / ledger_digest polling
        #: memos, same discipline as the barrier memo above — each poll
        #: costs O(new entries), and a base move (compaction or snapshot
        #: install re-bases the suffix) invalidates the whole memo
        self._ids_cache: list[str] = []
        self._ids_scan = 0
        self._ids_cache_base = -1
        self._chain_prefix: list[bytes] = []
        self._chain_prefix_base = -1

    # ------------------------------------------------------------ app SPI

    def deliver(self, proposal: Proposal, signatures) -> Reconfig:
        decision = Decision(proposal=proposal, signatures=tuple(signatures))
        try:
            ids = [str(i) for i in self.requests_from_proposal(proposal)]
        except Exception:  # noqa: BLE001 — foreign payload: no request ids
            ids = []
        kv_updates = self._kv_updates(proposal)
        with self.lock:
            self.ledger.append(decision)
            self.ledger_file.append(decision)
            self._chain = chain_update(self._chain, proposal.payload,
                                       proposal.metadata)
            self._ids_digest = fold_ids(self._ids_digest, ids)
            self._recent_ids.extend(ids)
            self._request_count += len(ids)
            for client, _rid, payload in kv_updates:
                self._kv[client] = payload
            height = self._base_height + len(self.ledger)
        if self._watches and kv_updates:
            self._publish_watches(height, kv_updates)
        self._maybe_capture()
        return self._reconfig_in(proposal)

    def _kv_updates(self, proposal: Proposal) -> list[tuple[str, str, bytes]]:
        """The committed KV view's delta for one decision: one
        ``(client_id, request_id, payload)`` per well-formed TestRequest
        in the batch, in batch order.  Foreign payloads contribute
        nothing (mirrors ``_reconfig_in``'s tolerance)."""
        from ..testing.app import BatchPayload, TestRequest

        if not proposal.payload:
            return []
        try:
            batch = decode(BatchPayload, proposal.payload)
        except Exception:  # noqa: BLE001 — foreign payload
            return []
        out: list[tuple[str, str, bytes]] = []
        for raw in batch.requests:
            try:
                req = decode(TestRequest, raw)
            except Exception:  # noqa: BLE001 — foreign request
                continue
            out.append((req.client_id, req.request_id, bytes(req.payload)))
        return out

    # ------------------------------------------------------- snapshots (ISSUE 17)

    def _maybe_capture(self) -> None:
        """Kick an async snapshot capture when the configured interval of
        decisions has accumulated since the last horizon.  Runs after
        every deliver; cheap when disabled (one int compare)."""
        interval = self.config.snapshot_interval_decisions
        if interval <= 0 or self._snap_inflight:
            return
        with self.lock:
            height = self._base_height + len(self.ledger)
        if height - self._last_snapshot_height < interval:
            return
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return  # not on the loop; the next on-loop deliver triggers
        from ..utils.tasks import create_logged_task

        self._snap_inflight = True
        create_logged_task(self._capture_snapshot(),
                           name=f"snapshot-{self.id}", logger=self.logger)

    async def _capture_snapshot(self) -> None:
        """Capture + truncate, each step crash-safe:

        1. freeze (height H, chain digest at H, anchor certificate at H,
           bounded app state) under the lock;
        2. write the snapshot file (temp + fsync + atomic rename — a kill
           here leaves the old snapshot + the full ledger: recovery sees
           nothing unusual);
        3. compact the ledger file (atomic rewrite: base ref + suffix)
           and prune WAL segments behind the horizon — a kill between 2
           and 3 leaves snapshot AND full ledger, which recovery
           reconciles by seeding from the snapshot and folding the
           suffix past it."""
        import time as _time

        try:
            with self.lock:
                height = self._base_height + len(self.ledger)
                if height <= self._last_snapshot_height or not self.ledger:
                    return
                anchor = self.ledger[-1]
                chain_at = self._chain
                state = AppState(
                    request_count=self._request_count,
                    ids_digest=self._ids_digest,
                    recent_ids=list(self._recent_ids),
                    kv_keys=list(self._kv.keys()),
                    kv_values=list(self._kv.values()),
                )
            blob = encode(state)
            manifest = make_manifest(height, chain_at, blob,
                                     anchor.proposal,
                                     list(anchor.signatures))
            t0 = _time.monotonic()
            path = self.snapshot_store.save(manifest, blob)
            if self.recorder.enabled:
                self.recorder.record("snapshot.capture", seq=height,
                                     dur=_time.monotonic() - t0,
                                     extra={"bytes": os.path.getsize(path)})
            anchor_wire = encode(WireDecision(
                proposal=anchor.proposal, signatures=list(anchor.signatures)
            ))
            t0 = _time.monotonic()
            with self.lock:
                cut = height - self._base_height
                suffix = self.ledger[cut:]
                self.ledger_file.compact(height, chain_at, suffix,
                                         app_state=blob, anchor=anchor_wire)
                self.ledger = suffix
                self._base_height = height
                self._base_chain = chain_at
                self._anchor_decision = anchor
            dropped = 0
            if self._wal is not None and hasattr(self._wal,
                                                 "drop_stale_segments"):
                dropped = self._wal.drop_stale_segments()
            if self.recorder.enabled:
                self.recorder.record("snapshot.truncate", seq=height,
                                     dur=_time.monotonic() - t0,
                                     extra={"wal_segments_dropped": dropped})
            self._snap_offer = (height, os.path.getsize(path),
                                manifest.state_digest)
            self._snap_path = path
            self._last_snapshot_height = height
        except Exception as e:  # noqa: BLE001 — capture must never kill consensus
            self.logger.warnf("snapshot capture failed: %r", e)
        finally:
            self._snap_inflight = False

    def _reconfig_in(self, proposal: Proposal) -> Reconfig:
        from ..testing.app import BatchPayload, TestRequest
        from ..testing.reconfig import RECONFIG_MAGIC, detect_reconfig

        found = Reconfig(in_latest_decision=False)
        if not proposal.payload or RECONFIG_MAGIC not in proposal.payload:
            return found
        try:
            batch = decode(BatchPayload, proposal.payload)
        except Exception:
            return found
        for raw in batch.requests:
            try:
                req = decode(TestRequest, raw)
            except Exception:
                continue
            reconfig = detect_reconfig(req.payload)
            if reconfig is not None:
                found = reconfig
        return found

    def assemble_proposal(self, metadata: bytes, requests) -> Proposal:
        from ..testing.app import BatchPayload

        return Proposal(
            header=b"",
            payload=encode(BatchPayload(requests=list(requests))),
            metadata=metadata,
            verification_sequence=self.verification_seq,
        )

    # ------------------------------------------------------------ Comm

    def send_consensus(self, target_id: int, msg) -> None:
        self.transport.send_consensus(target_id, msg)

    def broadcast_consensus(self, msg, targets=None) -> None:
        self.transport.broadcast_consensus(msg, targets)

    def send_transaction(self, target_id: int, request: bytes) -> None:
        self.transport.send_transaction(target_id, request)

    def nodes(self) -> list[int]:
        return self.transport.nodes()

    def rtt_seconds(self):
        """Expose the transport's measured RTT through the Comm seam —
        the forward-timeout derivation reads it off whatever object
        Consensus holds as ``comm`` (this embedder)."""
        return self.transport.rtt_seconds()

    # ------------------------------------------------------------ crypto (trivial)

    def sign(self, data: bytes) -> bytes:
        return b"sig-%d" % self.id

    def sign_proposal(self, proposal: Proposal, auxiliary_input: bytes) -> Signature:
        return Signature(signer=self.id, value=b"sig-%d" % self.id,
                         msg=auxiliary_input)

    def verify_proposal(self, proposal: Proposal) -> list[RequestInfo]:
        return self.requests_from_proposal(proposal)

    def verify_request(self, raw_request: bytes) -> RequestInfo:
        return self.request_id(raw_request)

    def verify_consenter_sig(self, signature: Signature, proposal: Proposal) -> bytes:
        return signature.msg

    def verify_signature(self, signature: Signature) -> None:
        return None

    def verification_sequence(self) -> int:
        return self.verification_seq

    def requests_from_proposal(self, proposal: Proposal) -> list[RequestInfo]:
        from ..testing.app import BatchPayload

        if not proposal.payload:
            return []
        batch = decode(BatchPayload, proposal.payload)
        return [self.request_id(r) for r in batch.requests]

    def auxiliary_data(self, msg: bytes) -> bytes:
        return msg

    def request_id(self, raw_request: bytes) -> RequestInfo:
        from ..testing.app import TestRequest

        def compute() -> RequestInfo:
            req = decode(TestRequest, raw_request)
            return RequestInfo(client_id=req.client_id, request_id=req.request_id)

        return self._request_id_cache.get_or(raw_request, compute)

    def membership_change(self) -> bool:
        return self.membership_changed

    # ------------------------------------------------------------ sync (over the wire)

    def _serve_sync(self, from_height: int) -> tuple[list, int]:
        """Transport sync-server hook (runs on the event loop).  Heights
        are ABSOLUTE; a request from behind our compaction horizon gets
        an empty tail — the transport attaches the snapshot offer, which
        is the only way past the deleted prefix."""
        with self.lock:
            base = self._base_height
            total = base + len(self.ledger)
            if from_height >= base:
                lo = from_height - base
                tail = self.ledger[lo:lo + MAX_SYNC_DECISIONS]
            else:
                tail = []
        return (
            [WireDecision(proposal=d.proposal, signatures=list(d.signatures))
             for d in tail],
            total,
        )

    def sync(self) -> SyncResponse:
        """Synchronizer SPI — called on an executor thread; the socket
        round trips run on the event loop via run_coroutine_threadsafe."""
        try:
            fut = asyncio.run_coroutine_threadsafe(self._sync_over_wire(),
                                                   self._loop)
            fut.result(timeout=30.0)
        except Exception as e:  # noqa: BLE001 — sync must not kill the caller
            self.logger.warnf("wire sync failed: %r", e)
        with self.lock:
            mine = list(self.ledger)
            anchor = self._anchor_decision
        # a freshly installed snapshot leaves an empty suffix: the anchor
        # certificate IS the latest decision (Consensus re-anchors its
        # view/sequence off its metadata, exactly as after a replay)
        if mine:
            latest = mine[-1]
        elif anchor is not None:
            latest = anchor
        else:
            latest = Decision(proposal=Proposal())
        reconfig = (
            self._reconfig_in(latest.proposal) if latest.proposal.payload
            else Reconfig(in_latest_decision=False)
        )
        return SyncResponse(latest=latest, reconfig=reconfig)

    def _poisoned(self, peer: int, reason: str) -> None:
        """A peer served sync material that failed verification: reject
        LOUDLY, count per-peer, never install.  (Satellite 2: the guard
        that keeps one compromised peer from rewriting a rejoiner.)"""
        self.sync_poisoned[peer] = self.sync_poisoned.get(peer, 0) + 1
        self.transport.metrics.sync_poisoned += 1
        if self.recorder.enabled:
            self.recorder.record("sync.poisoned", key=f"peer-{peer}",
                                 extra={"reason": reason[:160]})
        self.logger.warnf(
            "SYNC POISONING: rejecting material from peer %d (%d so far): %s",
            peer, self.sync_poisoned[peer], reason,
        )

    async def _sync_over_wire(self) -> None:
        """Pull our peers' ledger tails until no peer is ahead of us.

        Every tail is verified BEFORE any decision is applied: sequence
        continuity always, and the commit certificate (>= quorum distinct
        known signers per decision) — a tampered tail increments the
        poisoning counters and is dropped whole.  When every usable peer
        answers from past its compaction horizon (empty tail + snapshot
        offer), the snapshot branch fetches, verifies against the anchor
        certificate, and installs — then loops to pull the tail beyond
        the snapshot."""
        members = frozenset([self.id, *self.peers])
        quorum, _f = compute_quorum(len(members))
        for _round in range(64):  # bound: 64 * MAX_SYNC_DECISIONS decisions
            with self.lock:
                my_height = self._base_height + len(self.ledger)
            # donor shun (ISSUE 18): peers with a poisoning streak are not
            # even asked — unless EVERY peer is shunned, in which case ask
            # all of them (a fully partitioned rejoiner must still be able
            # to make progress off whichever donor has stopped lying; the
            # certificate checks below stay the actual safety boundary)
            peers = [p for p in self.peers
                     if self.sync_poisoned.get(p, 0)
                     < SYNC_DONOR_SHUN_THRESHOLD]
            if not peers:
                peers = list(self.peers)
            results = await asyncio.gather(*[
                self.transport.request_sync(p, my_height, timeout=1.0)
                for p in peers
            ])
            batches = [(p, r) for p, r in zip(peers, results)
                       if r is not None]
            usable = []
            for peer, batch in batches:
                if not batch.decisions:
                    continue
                # phase 1 — continuity from OUR height: failure is the
                # normal stale-batch race (we moved on), skip quietly
                if verify_tail(batch.decisions, my_height) is not None:
                    continue
                # phase 2 — certificates: failure here is tampering
                err = verify_tail(batch.decisions, my_height,
                                  quorum=quorum, members=members)
                if err is not None:
                    self._poisoned(peer, f"sync tail: {err}")
                    continue
                usable.append(batch)
            if usable:
                best = max(usable, key=lambda b: len(b.decisions))
                applied = 0
                for wd in best.decisions:
                    md = (decode(ViewMetadata, wd.proposal.metadata)
                          if wd.proposal.metadata else ViewMetadata())
                    with self.lock:
                        expect = self._base_height + len(self.ledger) + 1
                    if md.latest_sequence != expect:
                        break  # raced a live commit: re-request from new height
                    self.deliver(wd.proposal, list(wd.signatures))
                    self._drop_synced_from_pool(wd.proposal)
                    applied += 1
                if applied == 0:
                    return
                continue
            # no usable tail: are we behind somebody's compaction horizon?
            installed = await self._try_snapshot_catchup(
                batches, my_height, quorum, members
            )
            if not installed:
                return

    async def _try_snapshot_catchup(self, batches, my_height: int,
                                    quorum: int, members) -> bool:
        """Fetch + verify + install the best snapshot on offer; True when
        one was installed (the caller loops to pull the tail past it)."""
        offers = [(p, b) for p, b in batches
                  if b.snapshot_height > my_height and b.snapshot_bytes > 0
                  # donor shun (ISSUE 18): a peer can cross the threshold
                  # MID-ROUND (poisoned tail above, then its offer lands
                  # here), so re-check before paying for a chunked
                  # multi-frame snapshot transfer from a known tamperer
                  and self.sync_poisoned.get(p, 0)
                  < SYNC_DONOR_SHUN_THRESHOLD]
        offers.sort(key=lambda pb: pb[1].snapshot_height, reverse=True)
        for peer, batch in offers:
            data = await self.transport.fetch_snapshot(
                peer, batch.snapshot_height,
                chunk_bytes=self.config.snapshot_chunk_bytes,
            )
            if data is None:
                continue  # transfer abandoned/superseded: try next offer
            parsed = parse_snapshot_blob(data)
            if parsed is None:
                self._poisoned(peer, "snapshot blob failed integrity checks")
                continue
            manifest, state = parsed
            err = verify_snapshot(manifest, state, quorum, members)
            if err is not None:
                self._poisoned(peer, f"snapshot: {err}")
                continue
            self._install_snapshot(manifest, state)
            return True
        return False

    def _install_snapshot(self, manifest, state: bytes) -> None:
        """Adopt a VERIFIED foreign snapshot as our new base: persist it
        first (crash between persist and ledger reset = recovery seeds
        from the saved snapshot), then swap the in-memory state and
        compact the ledger file down to just the base reference."""
        import time as _time

        t0 = _time.monotonic()
        app = decode(AppState, state)
        anchor = Decision(proposal=manifest.anchor_proposal,
                          signatures=tuple(manifest.anchor_signatures))
        path = self.snapshot_store.save(manifest, state)
        anchor_wire = encode(WireDecision(
            proposal=manifest.anchor_proposal,
            signatures=list(manifest.anchor_signatures),
        ))
        from collections import deque

        with self.lock:
            self.ledger = []
            self._base_height = manifest.height
            self._base_chain = manifest.chain_digest
            self._chain = manifest.chain_digest
            self._request_count = app.request_count
            self._ids_digest = app.ids_digest
            self._recent_ids = deque(app.recent_ids, maxlen=RECENT_IDS_CAP)
            self._kv = dict(zip(app.kv_keys, app.kv_values))
            self._anchor_decision = anchor
            self.ledger_file.compact(manifest.height, manifest.chain_digest,
                                     [], app_state=state, anchor=anchor_wire)
        if self._wal is not None and hasattr(self._wal,
                                             "drop_stale_segments"):
            self._wal.drop_stale_segments()
        self._snap_offer = (manifest.height, os.path.getsize(path),
                            manifest.state_digest)
        self._snap_path = path
        self._last_snapshot_height = manifest.height
        # purge the pool of anything the snapshot already covers — the
        # recent-id window is bounded, so at worst a long-pooled request
        # older than the window waits out its auto-remove timeout
        if self.consensus is not None and self.consensus.pool is not None:
            from ..core.pool import remove_delivered_requests

            infos = []
            for rid in app.recent_ids:
                client, _, req_id = rid.partition(":")
                infos.append(RequestInfo(client_id=client, request_id=req_id))
            remove_delivered_requests(self.consensus.pool, infos, self.logger)
        if self.recorder.enabled:
            self.recorder.record("snapshot.install", seq=manifest.height,
                                 dur=_time.monotonic() - t0,
                                 extra={"bytes": len(state)})
        self.logger.infof(
            "installed snapshot at height %d (%d state bytes): "
            "rejoin skipped the compacted prefix",
            manifest.height, len(state),
        )

    def _drop_synced_from_pool(self, proposal: Proposal) -> None:
        """Remove a wire-synced decision's requests from the local pool.

        Wire sync delivers around consensus (the decisions never pass
        through Controller._decide), so without this a request that sat in
        OUR pool while the cluster committed it stays pooled forever: the
        pool keeps forwarding it, the leader keeps rejecting it as already
        processed, the forward-timeout keeps complaining — observed as the
        restarted kill-rejoin replica complaining about a healthy leader
        until request_auto_remove_timeout (60 s) finally fired."""
        if self.consensus is None or self.consensus.pool is None:
            return
        from ..core.pool import remove_delivered_requests

        try:
            infos = self.requests_from_proposal(proposal)
        except Exception:  # noqa: BLE001 — foreign payload: nothing pooled
            return
        remove_delivered_requests(self.consensus.pool, infos, self.logger)

    # ------------------------------------------------------ read plane (ISSUE 19)

    def _serve_read(self, req: ReadRequest) -> ReadResponse:
        """Serve one keyed read from COMMITTED state — no pool, no
        proposer, no verify launch (the Castro–Liskov read-only path).
        The read gate sheds BEFORE any state is touched, with the
        FT_REJECT contract inline (kind + drain-rate retry-after +
        occupancy): a read storm degrades reads, never writes."""
        if not self._read_gate.allow():
            self.read_stats.sheds += 1
            spent, burst = self._read_gate.occupancy()
            return ReadResponse(
                nonce=req.nonce, key=req.key, shed=True,
                shed_kind="read_gate",
                retry_after_ms=int(self._read_gate.retry_after() * 1000),
                occupancy=spent, high_water=burst,
            )
        if req.at_base:
            return self._read_at_base(req)
        with self.lock:
            height = self._base_height + len(self.ledger)
            digest = self._chain
            value = self._kv.get(req.key)
            anchor = self._last_snapshot_height
        found = value is not None
        self.read_stats.note_served(at_base=False, found=found)
        return ReadResponse(
            nonce=req.nonce, key=req.key, found=found,
            value=value if found else b"", height=height,
            state_digest=digest, anchor_height=anchor, at_base=False,
        )

    def _read_at_base(self, req: ReadRequest) -> ReadResponse:
        """Snapshot-anchored read: serve from the latest PERSISTED base,
        stamped with the snapshot's height, its chained ledger digest,
        and its own height as the anchor certificate.  ``load`` re-runs
        the store's full integrity verification on every read — a torn
        or tampered base is refused LOUDLY (counted, per the
        sync-poisoning precedent), never silently served."""
        height = self._last_snapshot_height
        snap = self.snapshot_store.load(height) if height > 0 else None
        app = None
        if snap is not None:
            try:
                app = decode(AppState, snap.state)
            except Exception:  # noqa: BLE001 — foreign state blob
                app = None
        if app is None:
            self.read_stats.base_refused += 1
            self.transport.metrics.read_base_refused += 1
            self.logger.warnf(
                "READ-AT-BASE REFUSED: no verifiable snapshot at height %d "
                "(%d refusals so far)", height, self.read_stats.base_refused)
            return ReadResponse(nonce=req.nonce, key=req.key, shed=True,
                                shed_kind="base_refused")
        kv = dict(zip(app.kv_keys, app.kv_values))
        value = kv.get(req.key)
        found = value is not None
        with self.lock:
            live = self._base_height + len(self.ledger)
        self.read_stats.note_served(
            at_base=True, found=found,
            lag=max(0, live - snap.manifest.height),
        )
        return ReadResponse(
            nonce=req.nonce, key=req.key, found=found,
            value=value if found else b"",
            height=snap.manifest.height,
            state_digest=snap.manifest.chain_digest,
            anchor_height=snap.manifest.height, at_base=True,
        )

    def _read_committed_hook(self, key: str):
        """The Consensus facade's ``read_hook``: the committed-state
        answer as ``(value, height, state_digest, anchor_height)``, or
        None when the key was never written."""
        with self.lock:
            value = self._kv.get(key)
            if value is None:
                return None
            height = self._base_height + len(self.ledger)
            return value, height, self._chain, self._last_snapshot_height

    def add_watch(self, prefix: str) -> Optional[int]:
        """Register a committed-stream subscription on a key prefix;
        None once the per-replica watch cap is reached (the registry is
        bounded like every other per-peer resource)."""
        from collections import deque

        if len(self._watches) >= self.config.read_max_watches:
            return None
        self._watch_seq += 1
        wid = self._watch_seq
        self._watches[wid] = {"prefix": prefix, "events": deque(),
                              "dropped": 0}
        return wid

    def _publish_watches(self, height: int, updates) -> None:
        """Fan one decision's KV delta to matching watches, bounded per
        subscriber: a slow poller drops its OLDEST events and is told
        how many (the transport outbox's drop-oldest-with-counts
        discipline) — backpressure never reaches the commit path."""
        cap = self.config.read_watch_buffer
        for w in self._watches.values():
            prefix = w["prefix"]
            events = w["events"]
            for client, rid, _payload in updates:
                if not client.startswith(prefix):
                    continue
                if len(events) >= cap:
                    events.popleft()
                    w["dropped"] += 1
                    self.read_stats.watch_dropped += 1
                events.append({"key": client, "rid": rid, "height": height})
                self.read_stats.watch_notifications += 1

    def poll_watch(self, wid: int):
        """Drain a watch's buffered events: ``(events, dropped)`` since
        the previous poll, or None for an unknown watch id."""
        w = self._watches.get(wid)
        if w is None:
            return None
        events = list(w["events"])
        w["events"].clear()
        dropped = w["dropped"]
        w["dropped"] = 0
        return events, dropped

    def remove_watch(self, wid: int) -> bool:
        return self._watches.pop(wid, None) is not None

    # ------------------------------------------------------------ lifecycle

    def _recover_local_state(self) -> None:
        """Rebuild chain/app state from disk: ledger suffix + the best
        seeding source (newest verified snapshot if its height lands
        inside [base, base+len(suffix)], else the base ref's embedded
        app state).  Every crash point of the capture/install flows
        resolves here:

        * killed before the snapshot rename — old snapshot + old ledger,
          nothing unusual;
        * killed between snapshot rename and ledger compaction — the
          snapshot exists at H with the FULL ledger still on disk: seed
          app state from the snapshot, fold only ``suffix[H-base:]``
          into it, fold the chain over the whole suffix — bit-identical
          to a replica that replayed everything;
        * killed mid-compaction — ``os.replace`` leaves old or new file;
        * snapshot directory lost/corrupted after compaction — the base
          ref's embedded app_state/anchor seed recovery instead."""
        self.ledger = self.ledger_file.read_all()
        self.ledger_file.open_append()
        base = self.ledger_file.base_height
        self._base_height = base
        self._base_chain = self.ledger_file.base_digest
        suffix = self.ledger
        snap = self.snapshot_store.latest()
        seed_height: Optional[int] = None
        app = AppState()
        if snap is not None and \
                base <= snap.manifest.height <= base + len(suffix):
            try:
                app = decode(AppState, snap.state)
                seed_height = snap.manifest.height
            except Exception:  # noqa: BLE001 — foreign state blob
                self.logger.warnf("snapshot state undecodable; ignoring")
        if seed_height is not None:
            m = snap.manifest
            self._anchor_decision = Decision(
                proposal=m.anchor_proposal,
                signatures=tuple(m.anchor_signatures),
            )
            self._last_snapshot_height = m.height
            self._snap_offer = (m.height, os.path.getsize(snap.path),
                                m.state_digest)
            self._snap_path = snap.path
        elif base > 0:
            # no usable snapshot but the ledger IS compacted: fall back
            # to the base ref's embedded seeding material
            try:
                if self.ledger_file.base_state:
                    app = decode(AppState, self.ledger_file.base_state)
                seed_height = base
                if self.ledger_file.base_anchor:
                    wd = decode(WireDecision, self.ledger_file.base_anchor)
                    self._anchor_decision = Decision(
                        proposal=wd.proposal,
                        signatures=tuple(wd.signatures),
                    )
                self._last_snapshot_height = base
            except Exception:  # noqa: BLE001 — torn base material
                self.logger.warnf(
                    "compacted ledger with no seeding material: app "
                    "counters restart at zero (consensus state is safe)"
                )
                seed_height = base
        from collections import deque

        self._request_count = app.request_count
        self._ids_digest = app.ids_digest or CHAIN_SEED
        self._recent_ids = deque(app.recent_ids or [],
                                 maxlen=RECENT_IDS_CAP)
        self._kv = dict(zip(app.kv_keys or [], app.kv_values or []))
        fold_from = (seed_height - base) if seed_height is not None else 0
        for d in suffix[fold_from:]:
            try:
                ids = [str(i)
                       for i in self.requests_from_proposal(d.proposal)]
            except Exception:  # noqa: BLE001 — foreign payload
                ids = []
            self._ids_digest = fold_ids(self._ids_digest, ids)
            self._recent_ids.extend(ids)
            self._request_count += len(ids)
            for client, _rid, payload in self._kv_updates(d.proposal):
                self._kv[client] = payload
        chain = self._base_chain
        for d in suffix:
            chain = chain_update(chain, d.proposal.payload,
                                 d.proposal.metadata)
        self._chain = chain

    def disk_snapshot(self) -> dict:
        """The disk-bound observables (control cmd=snapshot + the SLO
        signal source): on-disk byte totals and snapshot staleness."""
        with self.lock:
            height = self._base_height + len(self.ledger)
            base = self._base_height
        wal_bytes = 0
        if self._wal is not None and hasattr(self._wal, "disk_bytes"):
            wal_bytes = self._wal.disk_bytes()
        return {
            "height": height,
            "base_height": base,
            "snapshot_height": self._last_snapshot_height,
            "snapshot_age_decisions": height - self._last_snapshot_height,
            "snapshot_interval": self.config.snapshot_interval_decisions,
            "snapshot_disk_bytes": self.snapshot_store.disk_bytes(),
            "snapshot_rejected_files": self.snapshot_store.rejected_files,
            "ledger_disk_bytes": self.ledger_file.disk_bytes(),
            "wal_disk_bytes": wal_bytes,
            "sync_poisoned": dict(self.sync_poisoned),
        }

    def _refresh_disk_gauges(self) -> None:
        disk = self.disk_snapshot()
        self.snapshot_age_gauge.set(disk["snapshot_age_decisions"])
        self.snapshot_disk_gauge.set(disk["snapshot_disk_bytes"])
        self.ledger_disk_gauge.set(disk["ledger_disk_bytes"])
        self.wal_disk_gauge.set(disk["wal_disk_bytes"])

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        kw = {}
        if self.spec.get("wal_file_size_bytes"):
            kw["file_size_bytes"] = int(self.spec["wal_file_size_bytes"])
        self._wal, entries = walmod.initialize_and_read_all(
            self.spec["wal_dir"], self.logger, **kw
        )
        self._recover_local_state()
        with self.lock:
            suffix = list(self.ledger)
            anchor = self._anchor_decision
        if suffix:
            last = suffix[-1]
            md = decode(ViewMetadata, last.proposal.metadata)
            last_proposal, last_sigs = last.proposal, list(last.signatures)
        elif anchor is not None:
            # compacted-to-empty ledger: consensus re-anchors at the
            # snapshot's certificate, exactly as if it had replayed to it
            md = decode(ViewMetadata, anchor.proposal.metadata)
            last_proposal = anchor.proposal
            last_sigs = list(anchor.signatures)
        else:
            md, last_proposal, last_sigs = ViewMetadata(), Proposal(), []
        self.consensus = Consensus(
            config=self.config,
            application=self,
            assembler=self,
            wal=self._wal,
            wal_initial_content=entries,
            comm=self,
            signer=self,
            verifier=self,
            membership_notifier=self,
            request_inspector=self,
            synchronizer=self,
            logger=self.logger,
            metadata=md,
            last_proposal=last_proposal,
            last_signatures=last_sigs,
            scheduler=None,  # own wall-clock driver: this is production mode
            metrics=self.metrics,
            viewchanger_tick_interval=0.1,
            heartbeat_tick_interval=0.1,
            recorder=self.recorder,
        )
        # the read plane's committed-state hook: embedder-owned state,
        # exposed through the facade so in-process callers read the same
        # (value, height, digest, anchor) stamps the wire plane serves
        self.consensus.read_hook = self._read_committed_hook
        self.transport.attach(self.consensus)
        await self.transport.start()
        await self.consensus.start()
        # health sources wire AFTER start: the pool and WAL exist now
        self.health.watch_consensus(self.consensus)
        from ..obs.health import (
            read_signal_source,
            snapshot_signal_source,
            wal_signal_source,
        )

        self.health.add_source(wal_signal_source(self._wal))
        self.health.add_source(snapshot_signal_source(self.disk_snapshot))
        self.health.add_source(read_signal_source(self.read_stats.snapshot))
        from ..utils.tasks import create_logged_task

        self._health_task = create_logged_task(
            self._health_loop(), name=f"health-{self.id}",
            logger=self.logger,
        )

    async def _health_loop(self) -> None:
        """Periodic SLO tick — the burn windows need a steady sample
        cadence, not just whenever an operator polls cmd=health."""
        while True:
            try:
                self._refresh_disk_gauges()
                self.health.tick()
            except Exception as e:  # noqa: BLE001 — judged, never judging
                self.logger.warnf("health tick failed: %r", e)
            await asyncio.sleep(self.health_interval)

    async def stop(self) -> None:
        if self._health_task is not None:
            self._health_task.cancel()
            import contextlib

            with contextlib.suppress(asyncio.CancelledError):
                await self._health_task
            self._health_task = None
        if self.consensus is not None:
            await self.consensus.stop()
        await self.transport.close()
        if self._wal is not None and hasattr(self._wal, "close"):
            self._wal.close()
        self.ledger_file.close()

    # ------------------------------------------------------------ control queries

    def height(self) -> int:
        with self.lock:
            return self._base_height + len(self.ledger)

    def committed_requests(self) -> int:
        """Delivered-request count over the WHOLE history — O(1) now:
        maintained incrementally (and carried across compaction inside
        the snapshot's AppState) instead of re-decoding the ledger."""
        with self.lock:
            return self._request_count

    def committed_ids(self) -> list[str]:
        """Every committed request as "client:rid", in ledger order — the
        chaos runner's exactly-once oracle and the client-resubmission
        check (a request in NO live ledger after quiescence died with a
        killed replica's pool and must be resubmitted, like any BFT
        client would).  Covers the SUFFIX after the compaction horizon:
        with snapshots enabled the full-history oracle is ids_digest
        (chained, O(1) per replica) — the harness picks per scenario.

        Memoized with the ``barrier_seq`` discipline (ISSUE 19 satellite
        1): the harness polls this every settle tick, so each poll
        decodes only the NEW suffix entries; a base move (compaction /
        snapshot install) rebuilds from the new suffix."""
        with self.lock:
            base = self._base_height
            ledger = list(self.ledger)
        if base != self._ids_cache_base:
            self._ids_cache = []
            self._ids_scan = 0
            self._ids_cache_base = base
        for idx in range(self._ids_scan, len(ledger)):
            infos = self.requests_from_proposal(ledger[idx].proposal)
            self._ids_cache.extend(str(i) for i in infos)
            self._ids_scan = idx + 1
        return list(self._ids_cache)

    def ids_digest(self) -> str:
        """Chained digest over every delivered request id — the
        exactly-once oracle that survives compaction (equal digests =
        identical delivered sequences, without any replica holding the
        full id list)."""
        with self.lock:
            return self._ids_digest.hex()

    def ledger_digest(self, upto: int) -> str:
        """Fork detector, chained semantics: the running chain digest at
        absolute height ``upto`` (0 = current height).  For heights at or
        behind the compaction horizon the BASE digest answers — the
        caller (check_fork_free) reads ``base`` off the same control
        response and compares only heights both replicas can still
        compute.

        Mid-height answers memoize the running prefix digests (ISSUE 19
        satellite 1): ``_chain_prefix[k]`` is the digest after ``k``
        suffix decisions, extended lazily to the requested height, so
        the fork checker's repeated common-height probes cost O(new
        entries) instead of re-hashing the prefix every call."""
        with self.lock:
            base = self._base_height
            if upto == 0 or upto >= base + len(self.ledger):
                return self._chain.hex()
            if upto <= base:
                return self._base_chain.hex()
            base_chain = self._base_chain
            ledger = list(self.ledger)
        if base != self._chain_prefix_base:
            self._chain_prefix = [base_chain]
            self._chain_prefix_base = base
        k = upto - base
        while len(self._chain_prefix) <= k:
            d = ledger[len(self._chain_prefix) - 1]
            self._chain_prefix.append(chain_update(
                self._chain_prefix[-1], d.proposal.payload,
                d.proposal.metadata))
        return self._chain_prefix[k].hex()

    def barrier_seq(self, epoch: int) -> int:
        """Ledger position (1-based) of epoch ``epoch``'s committed
        reshard barrier command, 0 while it has not committed here.  The
        cluster manager polls this on every replica after a control-plane
        ``reshard`` trigger: once non-zero everywhere, the resize decision
        is ordered — it rode the stream, not a side channel.  Memoized
        (the position never changes once committed) and incrementally
        scanned, so the manager's poll loop costs O(new entries) per call
        instead of re-decoding the whole ledger on every tick."""
        from ..shard.epoch import barrier_marker

        found = self._barrier_seqs.get(epoch)
        if found:
            return found
        marker = barrier_marker(epoch)
        with self.lock:
            base = self._base_height
            ledger = list(self.ledger)
        start = max(0, self._barrier_scan.get(epoch, 0) - base)
        for idx in range(start, len(ledger)):
            infos = self.requests_from_proposal(ledger[idx].proposal)
            if any(str(i) == marker for i in infos):
                self._barrier_seqs[epoch] = base + idx + 1
                return base + idx + 1
        self._barrier_scan[epoch] = base + len(ledger)
        return 0


def _config_from_spec(spec: dict) -> Configuration:
    import dataclasses

    cfg = proc_config(int(spec["node_id"]))
    overrides = spec.get("config") or {}
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg


# --------------------------------------------------------------------------
# control channel (line JSON; parent-facing, never part of consensus)
# --------------------------------------------------------------------------


def _reply_dict(reply: ReadResponse) -> dict:
    """A read reply's JSON shape on the control channel — the full stamp
    always, the shed contract only when the gate fired."""
    d = {
        "found": reply.found,
        "value": reply.value.hex(),
        "height": reply.height,
        "state_digest": reply.state_digest.hex(),
        "anchor_height": reply.anchor_height,
        "at_base": reply.at_base,
    }
    if reply.shed:
        d.update(shed=True, shed_kind=reply.shed_kind,
                 retry_after_ms=reply.retry_after_ms,
                 occupancy=reply.occupancy, high_water=reply.high_water)
    return d


class ControlServer:
    def __init__(self, replica: ReplicaApp, addr: str, stop_evt: asyncio.Event):
        self.replica = replica
        self.addr = addr
        self.stop_evt = stop_evt
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        scheme, hostpath, port = parse_addr(self.addr)
        if scheme == "tcp":
            self._server = await asyncio.start_server(
                self._serve, host=hostpath, port=port
            )
        else:
            self._server = await asyncio.start_unix_server(
                self._serve, path=hostpath
            )

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
            scheme, hostpath, _ = parse_addr(self.addr)
            if scheme == "uds":
                import contextlib

                with contextlib.suppress(OSError):
                    os.unlink(hostpath)

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    resp = await self._handle(req)
                except Exception as e:  # noqa: BLE001 — control must answer
                    resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                writer.write((json.dumps(resp) + "\n").encode())
                await writer.drain()
        except (ConnectionError, OSError):
            pass
        finally:
            writer.close()

    async def _handle(self, req: dict) -> dict:
        r = self.replica
        cmd = req.get("cmd")
        if cmd == "ping":
            import time

            running = r.consensus is not None and r.consensus._running
            # "now" is this process's monotonic clock — the parent's
            # request/response midpoint against it estimates the clock
            # offset that aligns per-replica trace timestamps onto ONE
            # cluster timeline (SocketCluster.estimate_clock_offsets)
            return {"ok": True, "running": running, "node_id": r.id,
                    "now": time.monotonic()}
        if cmd == "leader":
            lead = r.consensus.get_leader_id() if r.consensus else 0
            return {"ok": True, "leader": lead}
        if cmd == "submit":
            from ..core.pool import AdmissionRejected, SubmitTimeoutError
            from ..testing.app import TestRequest

            raw = encode(TestRequest(
                client_id=req["client"],
                request_id=req["rid"],
                payload=bytes.fromhex(req.get("payload", "")),
            ))
            try:
                await r.consensus.submit_request(raw)
            except AdmissionRejected as e:
                # the PR 8 admission contract, now visible to SOCKET
                # clients: structured reject + drain-rate retry-after
                # hint instead of an opaque error string
                return {
                    "ok": False,
                    "rejected": "admission",
                    "retry_after_ms": int((e.retry_after or 0.0) * 1000),
                    "occupancy": e.occupancy,
                    "error": f"AdmissionRejected: {e}",
                }
            except SubmitTimeoutError as e:
                return {
                    "ok": False,
                    "rejected": "timeout",
                    "retry_after_ms": 0,
                    "occupancy": r.consensus.pool_occupancy(),
                    "error": f"SubmitTimeoutError: {e}",
                }
            # Read-your-write session token (ISSUE 20 satellite): the ack
            # carries a height the client can hand to cmd=read
            # mode=follower as min_height.  The pooled height is only a
            # lower bound (the request is admitted, not yet ordered);
            # wait_committed_s > 0 parks until THIS request is committed
            # locally and returns the height that provably covers it.
            wait_s = float(req.get("wait_committed_s", 0.0))
            committed = False
            if wait_s > 0:
                rid = f"{req['client']}:{req['rid']}"
                deadline = asyncio.get_event_loop().time() + wait_s
                while asyncio.get_event_loop().time() < deadline:
                    if rid in r.committed_ids():
                        committed = True
                        break
                    await asyncio.sleep(0.01)
            return {"ok": True, "height": r.height(), "committed": committed}
        if cmd == "height":
            pool = r.consensus.pool_occupancy() if r.consensus else {}
            return {"ok": True, "height": r.height(),
                    "pool": pool.get("size", 0)}
        if cmd == "occupancy":
            # the autoscaler's saturation signal, per replica — a manager
            # of S socket groups sums these into the ShardSet.occupancy
            # shape and feeds shard.autoscale.OccupancyAutoscaler
            occ = r.consensus.pool_occupancy() if r.consensus else {}
            return {"ok": True, "occupancy": occ}
        if cmd == "reshard":
            # control-plane reshard trigger: order epoch `epoch`'s barrier
            # command through THIS replica's consensus stream (Vertical
            # Paxos rule — the resize decision must ride the ordered
            # stream).  Idempotent: the pool's client dedup absorbs
            # re-triggers after a manager crash.  Construction shared with
            # the in-process harness (testing.app.submit_barrier_request)
            # so the barrier marker can never drift between the two.
            from ..testing.app import submit_barrier_request

            epoch = int(req["epoch"])
            await submit_barrier_request(
                r.consensus, epoch, int(req.get("old", 1)), int(req["new"])
            )
            return {"ok": True, "epoch": epoch,
                    "barrier_seq": r.barrier_seq(epoch)}
        if cmd == "barrier":
            epoch = int(req["epoch"])
            return {"ok": True, "epoch": epoch,
                    "barrier_seq": r.barrier_seq(epoch)}
        if cmd == "committed":
            return {"ok": True, "committed": r.committed_requests(),
                    "height": r.height()}
        if cmd == "committed_ids":
            return {"ok": True, "ids": r.committed_ids()}
        if cmd == "ledger_digest":
            upto = int(req.get("upto", 0))
            with r.lock:
                base = r._base_height
            return {"ok": True, "digest": r.ledger_digest(upto),
                    "height": r.height(), "base": base,
                    "ids_digest": r.ids_digest()}
        if cmd == "snapshot":
            # ISSUE 17: disk-bound observables + snapshot staleness —
            # what the kill-rejoin scenarios and the truncating soak's
            # bounded-disk oracle read off every replica
            return {"ok": True, "node": f"n{r.id}", **r.disk_snapshot()}
        if cmd == "stats":
            frontier = (r.consensus.delivery_frontier()
                        if r.consensus is not None else {})
            return {"ok": True, "transport": r.transport.transport_snapshot(),
                    "height": r.height(),
                    "committed": r.committed_requests(),
                    "disk": r.disk_snapshot(),
                    "read": r.read_stats.snapshot(),
                    "frontier": frontier}
        if cmd == "read":
            return await self._read(req)
        if cmd == "watch":
            # committed-stream subscription on a key prefix: bounded
            # buffer per watch, drained by cmd=watch_poll
            wid = r.add_watch(str(req.get("prefix", "")))
            if wid is None:
                return {"ok": False, "error": "watch cap reached",
                        "max_watches": r.config.read_max_watches}
            return {"ok": True, "watch_id": wid}
        if cmd == "watch_poll":
            polled = r.poll_watch(int(req["watch_id"]))
            if polled is None:
                return {"ok": False, "error": "unknown watch"}
            events, dropped = polled
            return {"ok": True, "events": events, "dropped": dropped}
        if cmd == "unwatch":
            return {"ok": r.remove_watch(int(req["watch_id"]))}
        if cmd == "health":
            # live SLO verdict (ISSUE 14): tick once on demand so the
            # answer reflects NOW even between periodic samples, then
            # serve the verdict + recent transitions
            r.health.tick()
            return {
                "ok": True,
                "node": f"n{r.id}",
                "health": r.health.verdict(),
                "transitions": r.health.transition_log()[-16:],
            }
        if cmd == "metrics":
            # Prometheus text exposition over the control channel: the
            # per-replica counters finally have a reader in multi-process
            # deployments (mount behind an HTTP handler in production)
            return {"ok": True, "text": r.metrics_provider.expose()}
        if cmd == "trace":
            # per-replica flight-recorder pull: summary block + events.
            # "since" (an event-sequence cursor from a previous pull's
            # "next_since") ships only NEW events — repeated pulls are
            # O(new), never a re-send of the whole ring; "last" keeps the
            # newest-N semantics.  since wins when both are present.
            last = req.get("last")
            since = req.get("since")
            if since is not None:
                events, cursor = r.recorder.snapshot_since(int(since))
            else:
                # the full/newest-N pull rides the same exact-seqno path
                # (events_since) so next_since can never cover an event
                # the snapshot raced past (recorders are fed from
                # executor threads too — the torn-pair hazard)
                evs, cursor = r.recorder.events_since(0)
                if last is not None:
                    evs = evs[-int(last):] if int(last) else []
                events = [e.as_dict() for e in evs]
            return {
                "ok": True,
                "node": f"n{r.id}",
                "trace": r.recorder.trace_block(),
                "dropped": r.recorder.dropped,
                "events": events,
                "next_since": cursor,
            }
        if cmd == "fault":
            return self._fault(req)
        if cmd == "stop":
            self.stop_evt.set()
            return {"ok": True}
        return {"ok": False, "error": f"unknown cmd {cmd!r}"}

    async def _read(self, req: dict) -> dict:
        """cmd=read — the serving plane's client edge, three modes:

        * ``local``: this replica's committed state as-is (optionally
          ``at_base``: anchored to the latest persisted snapshot);
        * ``follower``: local serve plus the client-side staleness
          judgement — ``accepted`` is the :func:`follower_read_accept`
          verdict against ``frontier`` (default: this replica's own
          height) and ``max_lag`` decisions;
        * ``quorum``: fan the read to every peer and apply the ``f+1``
          match rule — the reply is committed-proof without touching
          consensus."""
        r = self.replica
        key = str(req.get("key", ""))
        mode = req.get("mode", "local")
        max_lag = int(req.get("max_lag", 0))
        if mode == "quorum":
            return await self._quorum_read(key, max_lag)
        at_base = bool(req.get("at_base", False))
        min_height = int(req.get("min_height", 0))
        if mode == "follower" and min_height > 0:
            # Read-your-write session frontier (ISSUE 20 satellite): the
            # client hands back the height token its write ack carried.
            # A replica still behind it PARKS briefly (park_s, bounded)
            # for the commit to arrive; if it is still behind on wake it
            # answers a structured "stale" with a commit-gap-derived
            # retry-after hint — never a silently stale value.
            park_s = min(float(req.get("park_s", 0.25)), 5.0)
            deadline = asyncio.get_event_loop().time() + park_s
            while (r.height() + max_lag < min_height
                   and asyncio.get_event_loop().time() < deadline):
                await asyncio.sleep(0.01)
            height = r.height()
            if height + max_lag < min_height:
                frontier = (r.consensus.delivery_frontier()
                            if r.consensus is not None else {})
                return {
                    "ok": True, "accepted": False, "stale": True,
                    "height": height, "min_height": min_height,
                    "max_lag": max_lag,
                    "retry_after_ms": session_retry_after_ms(
                        height, min_height, frontier.get("commit_gap_s")
                    ),
                }
        reply = r._serve_read(ReadRequest(nonce=0, key=key, at_base=at_base))
        out = _reply_dict(reply)
        out["ok"] = True
        if mode == "follower":
            frontier = int(req.get("frontier", min_height or r.height()))
            out["accepted"] = follower_read_accept(reply, frontier, max_lag)
            out["frontier"] = frontier
            out["max_lag"] = max_lag
        return out

    async def _quorum_read(self, key: str, max_lag: int) -> dict:
        """Fan a keyed read to every peer (plus our own answer) and
        accept on ``f+1`` bit-identical stamps.  Contradicting donors
        are attributed to the MisbehaviorTable as OBSERVED-only
        ``stale_read`` evidence — read replies are unsigned, so they
        count for the operator but never feed the shun score."""
        r = self.replica
        members = [r.id, *r.peers]
        _quorum, f = compute_quorum(len(members))
        need = f + 1
        local = r._serve_read(ReadRequest(nonce=0, key=key, at_base=False))
        peer_ids = list(r.peers)
        results = await asyncio.gather(*[
            r.transport.request_read(p, key, timeout=1.0)
            for p in peer_ids
        ])
        replies = [(r.id, local), *zip(peer_ids, results)]
        decision = quorum_read_decide(replies, need,
                                      max_lag_decisions=max_lag)
        if r.consensus is not None:
            for sender, _reason in decision.outliers:
                r.consensus.misbehavior.note(sender, "stale_read")
        out = {"ok": True, "need": need, "matches": decision.matches,
               "outliers": [[s, why] for s, why in decision.outliers],
               "quorum": decision.winner is not None}
        if decision.winner is not None:
            out.update(_reply_dict(decision.winner))
        return out

    def _fault(self, req: dict) -> dict:
        """Socket-level chaos: the same fault vocabulary the in-process
        network exposes, applied at the transport."""
        t = self.replica.transport
        action = req.get("action")
        peer = int(req.get("peer", 0))
        peers = [peer] if peer else list(t._peers)
        if action == "mute":
            t.mute()
        elif action == "unmute":
            t.unmute()
        elif action == "drop_link":
            for p in peers:
                t.drop_link(p)
        elif action == "restore_link":
            for p in peers:
                t.restore_link(p)
        elif action == "heal_links":
            for p in list(t._dropped_links):
                t.restore_link(p)
            for p in list(t._slow_links):
                t.slow_link(p, 0.0)
            t.unmute()
        elif action == "slow_link":
            delay = float(req.get("delay", 0.0))
            for p in peers:
                t.slow_link(p, delay)
        else:
            return {"ok": False, "error": f"unknown fault {action!r}"}
        return {"ok": True}


async def run_replica(spec: dict) -> None:
    replica = ReplicaApp(spec)
    stop_evt = asyncio.Event()
    control = ControlServer(replica, spec["control"], stop_evt)
    await control.start()  # control first: the parent polls it for readiness
    await replica.start()
    try:
        await stop_evt.wait()
    finally:
        await replica.stop()
        await control.close()


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="SmartBFT socket replica process")
    ap.add_argument("--spec-file", required=True,
                    help="path to the JSON ReplicaSpec")
    args = ap.parse_args(argv)
    with open(args.spec_file) as fh:
        spec = json.load(fh)
    asyncio.run(run_replica(spec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
