"""One-replica process entry point: ``python -m smartbft_tpu.net.launch``.

A replica process is a :class:`ReplicaApp` (every SPI interface,
implemented for a process that shares NOTHING in memory with its peers)
wired to a :class:`~smartbft_tpu.net.transport.SocketComm` and a
Consensus facade running on its own wall-clock driver.  Processes share
only key material and the peer address map — exactly the deployment
contract of the paper's embedder.

What replaces the in-process harness's shared state:

* **Ledger** — each committed decision is appended (length-prefixed
  frame, ``framing.WireDecision``) to a per-replica ledger file.  On
  restart the file is replayed with torn-tail tolerance (a SIGKILL
  mid-append loses at most the partial tail record; the replica then
  catches up over the wire like any lagging peer).
* **Synchronizer** — ``sync()`` asks every peer for its ledger tail over
  the transport's SYNC_REQ/SYNC_RESP frames (nonce-correlated, batched
  at ``MAX_SYNC_DECISIONS`` per round trip) and applies the longest
  consistent extension.  This is what makes SIGKILL-and-rejoin a real
  scenario instead of a shared-memory illusion.
* **Control channel** — a tiny line-JSON server (its own UDS/TCP
  listener, NOT the consensus transport) the parent cluster manager
  uses to submit requests, read heights/digests/transport stats, inject
  socket-level faults, and request graceful shutdown.

Crypto is trivial (signature = node id), matching the in-process
harness's default: this subsystem proves the TRANSPORT, the crypto
planes are proven elsewhere and plug in through the same SPI.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
import threading
from typing import Optional

from .. import wal as walmod
from ..api import (
    Application,
    Assembler,
    Comm,
    MembershipNotifier,
    RequestInspector,
    Signer,
    Synchronizer,
    Verifier,
)
from ..codec import decode, encode
from ..config import Configuration
from ..consensus import Consensus
from ..messages import Proposal, Signature, ViewMetadata
from ..types import Decision, Reconfig, RequestInfo, SyncResponse
from ..utils.logging import StdLogger
from ..utils.memo import BoundedMemo
from .framing import FrameDecoder, FrameError, WireDecision, encode_frame, parse_addr
from .transport import SocketComm

#: ledger-file frame type (framing reserves 1..5 for the socket protocol;
#: the ledger file is a private on-disk format, any tag works as long as
#: the reader and writer agree — but reusing FrameDecoder keeps torn-tail
#: handling in one place, so the tag must be a known one)
from .framing import FT_SYNC_RESP as _FT_LEDGER  # noqa: E402


def proc_config(self_id: int) -> Configuration:
    """Wall-clock configuration for a localhost multi-process cluster:
    the socket twin of ``testing.app.fast_config`` — timeouts sized for
    real time on one machine (RTT ~50 us), snappy enough that the smoke
    gate's kill/rejoin cycles finish inside the tier-1 budget."""
    return Configuration(
        self_id=self_id,
        request_batch_max_count=10,
        request_batch_max_bytes=10 * 1024 * 1024,
        request_batch_max_interval=0.02,
        incoming_message_buffer_size=400,
        request_pool_size=800,
        request_forward_timeout=1.0,
        # round-16 fix: derive the EFFECTIVE forward timeout from the
        # transport's measured RTT (localhost: µs → clamped to the 10 ms
        # floor) instead of waiting out the full constant above — which
        # the cluster timeline measured as 97.6% of follower-submitted
        # request latency.  The constant stays the ceiling/fallback.
        request_forward_rtt_multiplier=20.0,
        request_complain_timeout=4.0,
        request_auto_remove_timeout=60.0,
        view_change_resend_interval=1.0,
        view_change_timeout=6.0,
        leader_heartbeat_timeout=3.0,
        leader_heartbeat_count=10,
        num_of_ticks_behind_before_syncing=10,
        collect_timeout=0.5,
        # off, like the in-process fast_config: a fresh replica starts at
        # its recovered height and catches up through the behind-by-
        # heartbeat sync path; sync_on_start=True measurably destabilizes
        # the first seconds of a wall-clock cluster (start-time syncs
        # contend with the first commit waves for the sync lock)
        sync_on_start=False,
        speed_up_view_change=False,
        leader_rotation=False,
        decisions_per_leader=0,
        transport_outbox_cap=4096,
        transport_reconnect_backoff_base=0.02,
        transport_reconnect_backoff_max=0.5,
    )


class LedgerFile:
    """Append-only committed-decision log with torn-tail-tolerant replay.

    Frames are ``framing`` frames; a truncated/corrupt tail record (the
    SIGKILL case) ends the replay instead of raising — the replica simply
    restarts a few decisions behind and syncs the rest from its peers."""

    def __init__(self, path: str):
        self.path = path
        self._fh = None

    def read_all(self) -> list[Decision]:
        decisions: list[Decision] = []
        if not os.path.exists(self.path):
            return decisions
        decoder = FrameDecoder()
        with open(self.path, "rb") as fh:
            data = fh.read()
        try:
            frames = decoder.feed(data)
        except FrameError:
            frames = []  # poisoned mid-file: at worst we resync everything
        for _ftype, payload in frames:
            try:
                wd = decode(WireDecision, payload)
            except Exception:
                break  # torn tail
            decisions.append(
                Decision(proposal=wd.proposal, signatures=tuple(wd.signatures))
            )
        return decisions

    def open_append(self) -> None:
        self._fh = open(self.path, "ab")

    def append(self, decision: Decision) -> None:
        wd = WireDecision(
            proposal=decision.proposal, signatures=list(decision.signatures)
        )
        self._fh.write(encode_frame(_FT_LEDGER, encode(wd)))
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class ReplicaApp(Application, Assembler, Comm, Signer, Verifier,
                 RequestInspector, Synchronizer, MembershipNotifier):
    """The multi-process embedder: one OS process, no shared memory."""

    #: ledger appends are a buffered write + flush — cheap enough to run
    #: inline on the event loop instead of paying an executor round-trip
    blocking_deliver = False

    def __init__(self, spec: dict):
        self.spec = spec
        self.id = int(spec["node_id"])
        self.logger = StdLogger(f"replica-{self.id}")
        self.config = _config_from_spec(spec)
        self.peers = {int(k): v for k, v in spec["peers"].items()}
        self.transport = SocketComm.from_config(
            self.config,
            self.peers,
            listen=spec["listen"],
            cluster_key=bytes.fromhex(spec.get("cluster_key", "")),
            logger=self.logger,
        )
        self.transport.sync_server = self._serve_sync
        # per-replica pull-based observability (ISSUE 12): a Prometheus
        # text-exposition provider ALWAYS (counters are cheap and the
        # control channel's cmd=metrics needs something to read), the
        # flight recorder only when the spec asks (cmd=trace then serves
        # the per-replica timeline to SocketCluster / operators)
        from ..metrics import MetricsBundle, PrometheusProvider
        from ..obs import NOP_RECORDER, TraceRecorder

        self.metrics_provider = PrometheusProvider()
        self.metrics = MetricsBundle(self.metrics_provider)
        if spec.get("trace"):
            self.recorder = TraceRecorder(
                node=f"n{self.id}",
                capacity=int(spec.get("trace_capacity", 2048)),
            )
        else:
            self.recorder = NOP_RECORDER
        self.transport.recorder = self.recorder
        # cluster health plane (ISSUE 14): every replica judges itself
        # against the declarative SLO spec on a periodic tick; cmd=health
        # serves the verdict, SocketCluster.cluster_health aggregates n
        # of them.  Breach/clear transitions land in the flight recorder
        # (when armed) so SLO violations show on the merged timeline.
        from ..obs.health import HealthMonitor

        self.health = HealthMonitor(recorder=self.recorder,
                                    node=f"n{self.id}")
        self.health_interval = float(spec.get("health_interval", 0.25))
        self._health_task = None
        # FT_TRACE sidecars carry the SAME "client:rid" correlator the
        # recorder stamps on req.submit/req.deliver (request_id memoizes,
        # so the per-forward cost is a dict hit once warm)
        self.transport.request_key_fn = \
            lambda raw: str(self.request_id(raw))
        self.ledger_file = LedgerFile(spec["ledger_path"])
        self.lock = threading.Lock()
        self.ledger: list[Decision] = []
        self.verification_seq = 0
        self.membership_changed = False
        self.consensus: Optional[Consensus] = None
        self._wal = None
        self._request_id_cache: BoundedMemo[bytes, RequestInfo] = BoundedMemo()
        #: epoch -> committed barrier ledger seq (immutable once found) and
        #: epoch -> ledger index already scanned without finding it — the
        #: reshard manager polls barrier_seq every ~100 ms, so each poll
        #: must cost O(new entries), not O(ledger)
        self._barrier_seqs: dict[int, int] = {}
        self._barrier_scan: dict[int, int] = {}

    # ------------------------------------------------------------ app SPI

    def deliver(self, proposal: Proposal, signatures) -> Reconfig:
        decision = Decision(proposal=proposal, signatures=tuple(signatures))
        with self.lock:
            self.ledger.append(decision)
            self.ledger_file.append(decision)
        return self._reconfig_in(proposal)

    def _reconfig_in(self, proposal: Proposal) -> Reconfig:
        from ..testing.app import BatchPayload, TestRequest
        from ..testing.reconfig import RECONFIG_MAGIC, detect_reconfig

        found = Reconfig(in_latest_decision=False)
        if not proposal.payload or RECONFIG_MAGIC not in proposal.payload:
            return found
        try:
            batch = decode(BatchPayload, proposal.payload)
        except Exception:
            return found
        for raw in batch.requests:
            try:
                req = decode(TestRequest, raw)
            except Exception:
                continue
            reconfig = detect_reconfig(req.payload)
            if reconfig is not None:
                found = reconfig
        return found

    def assemble_proposal(self, metadata: bytes, requests) -> Proposal:
        from ..testing.app import BatchPayload

        return Proposal(
            header=b"",
            payload=encode(BatchPayload(requests=list(requests))),
            metadata=metadata,
            verification_sequence=self.verification_seq,
        )

    # ------------------------------------------------------------ Comm

    def send_consensus(self, target_id: int, msg) -> None:
        self.transport.send_consensus(target_id, msg)

    def broadcast_consensus(self, msg, targets=None) -> None:
        self.transport.broadcast_consensus(msg, targets)

    def send_transaction(self, target_id: int, request: bytes) -> None:
        self.transport.send_transaction(target_id, request)

    def nodes(self) -> list[int]:
        return self.transport.nodes()

    def rtt_seconds(self):
        """Expose the transport's measured RTT through the Comm seam —
        the forward-timeout derivation reads it off whatever object
        Consensus holds as ``comm`` (this embedder)."""
        return self.transport.rtt_seconds()

    # ------------------------------------------------------------ crypto (trivial)

    def sign(self, data: bytes) -> bytes:
        return b"sig-%d" % self.id

    def sign_proposal(self, proposal: Proposal, auxiliary_input: bytes) -> Signature:
        return Signature(signer=self.id, value=b"sig-%d" % self.id,
                         msg=auxiliary_input)

    def verify_proposal(self, proposal: Proposal) -> list[RequestInfo]:
        return self.requests_from_proposal(proposal)

    def verify_request(self, raw_request: bytes) -> RequestInfo:
        return self.request_id(raw_request)

    def verify_consenter_sig(self, signature: Signature, proposal: Proposal) -> bytes:
        return signature.msg

    def verify_signature(self, signature: Signature) -> None:
        return None

    def verification_sequence(self) -> int:
        return self.verification_seq

    def requests_from_proposal(self, proposal: Proposal) -> list[RequestInfo]:
        from ..testing.app import BatchPayload

        if not proposal.payload:
            return []
        batch = decode(BatchPayload, proposal.payload)
        return [self.request_id(r) for r in batch.requests]

    def auxiliary_data(self, msg: bytes) -> bytes:
        return msg

    def request_id(self, raw_request: bytes) -> RequestInfo:
        from ..testing.app import TestRequest

        def compute() -> RequestInfo:
            req = decode(TestRequest, raw_request)
            return RequestInfo(client_id=req.client_id, request_id=req.request_id)

        return self._request_id_cache.get_or(raw_request, compute)

    def membership_change(self) -> bool:
        return self.membership_changed

    # ------------------------------------------------------------ sync (over the wire)

    def _serve_sync(self, from_height: int) -> tuple[list, int]:
        """Transport sync-server hook (runs on the event loop)."""
        with self.lock:
            tail = self.ledger[from_height:]
            total = len(self.ledger)
        return (
            [WireDecision(proposal=d.proposal, signatures=list(d.signatures))
             for d in tail],
            total,
        )

    def sync(self) -> SyncResponse:
        """Synchronizer SPI — called on an executor thread; the socket
        round trips run on the event loop via run_coroutine_threadsafe."""
        try:
            fut = asyncio.run_coroutine_threadsafe(self._sync_over_wire(),
                                                   self._loop)
            fut.result(timeout=30.0)
        except Exception as e:  # noqa: BLE001 — sync must not kill the caller
            self.logger.warnf("wire sync failed: %r", e)
        with self.lock:
            mine = list(self.ledger)
        latest = mine[-1] if mine else Decision(proposal=Proposal())
        reconfig = (
            self._reconfig_in(latest.proposal) if mine
            else Reconfig(in_latest_decision=False)
        )
        return SyncResponse(latest=latest, reconfig=reconfig)

    async def _sync_over_wire(self) -> None:
        """Pull our peers' ledger tails until no peer is ahead of us."""
        for _round in range(64):  # bound: 64 * MAX_SYNC_DECISIONS decisions
            with self.lock:
                my_height = len(self.ledger)
            results = await asyncio.gather(*[
                self.transport.request_sync(p, my_height, timeout=1.0)
                for p in self.peers
            ])
            batches = [r for r in results if r is not None and r.decisions]
            if not batches:
                return
            best = max(batches, key=lambda b: len(b.decisions))
            applied = 0
            for wd in best.decisions:
                md = (decode(ViewMetadata, wd.proposal.metadata)
                      if wd.proposal.metadata else ViewMetadata())
                with self.lock:
                    expect = len(self.ledger) + 1
                if md.latest_sequence != expect:
                    break  # stale/overlapping batch: re-request from new height
                self.deliver(wd.proposal, list(wd.signatures))
                self._drop_synced_from_pool(wd.proposal)
                applied += 1
            if applied == 0:
                return

    def _drop_synced_from_pool(self, proposal: Proposal) -> None:
        """Remove a wire-synced decision's requests from the local pool.

        Wire sync delivers around consensus (the decisions never pass
        through Controller._decide), so without this a request that sat in
        OUR pool while the cluster committed it stays pooled forever: the
        pool keeps forwarding it, the leader keeps rejecting it as already
        processed, the forward-timeout keeps complaining — observed as the
        restarted kill-rejoin replica complaining about a healthy leader
        until request_auto_remove_timeout (60 s) finally fired."""
        if self.consensus is None or self.consensus.pool is None:
            return
        from ..core.pool import remove_delivered_requests

        try:
            infos = self.requests_from_proposal(proposal)
        except Exception:  # noqa: BLE001 — foreign payload: nothing pooled
            return
        remove_delivered_requests(self.consensus.pool, infos, self.logger)

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        kw = {}
        if self.spec.get("wal_file_size_bytes"):
            kw["file_size_bytes"] = int(self.spec["wal_file_size_bytes"])
        self._wal, entries = walmod.initialize_and_read_all(
            self.spec["wal_dir"], self.logger, **kw
        )
        self.ledger = self.ledger_file.read_all()
        self.ledger_file.open_append()
        if self.ledger:
            last = self.ledger[-1]
            md = decode(ViewMetadata, last.proposal.metadata)
            last_proposal, last_sigs = last.proposal, list(last.signatures)
        else:
            md, last_proposal, last_sigs = ViewMetadata(), Proposal(), []
        self.consensus = Consensus(
            config=self.config,
            application=self,
            assembler=self,
            wal=self._wal,
            wal_initial_content=entries,
            comm=self,
            signer=self,
            verifier=self,
            membership_notifier=self,
            request_inspector=self,
            synchronizer=self,
            logger=self.logger,
            metadata=md,
            last_proposal=last_proposal,
            last_signatures=last_sigs,
            scheduler=None,  # own wall-clock driver: this is production mode
            metrics=self.metrics,
            viewchanger_tick_interval=0.1,
            heartbeat_tick_interval=0.1,
            recorder=self.recorder,
        )
        self.transport.attach(self.consensus)
        await self.transport.start()
        await self.consensus.start()
        # health sources wire AFTER start: the pool and WAL exist now
        self.health.watch_consensus(self.consensus)
        from ..obs.health import wal_signal_source

        self.health.add_source(wal_signal_source(self._wal))
        from ..utils.tasks import create_logged_task

        self._health_task = create_logged_task(
            self._health_loop(), name=f"health-{self.id}",
            logger=self.logger,
        )

    async def _health_loop(self) -> None:
        """Periodic SLO tick — the burn windows need a steady sample
        cadence, not just whenever an operator polls cmd=health."""
        while True:
            try:
                self.health.tick()
            except Exception as e:  # noqa: BLE001 — judged, never judging
                self.logger.warnf("health tick failed: %r", e)
            await asyncio.sleep(self.health_interval)

    async def stop(self) -> None:
        if self._health_task is not None:
            self._health_task.cancel()
            import contextlib

            with contextlib.suppress(asyncio.CancelledError):
                await self._health_task
            self._health_task = None
        if self.consensus is not None:
            await self.consensus.stop()
        await self.transport.close()
        if self._wal is not None and hasattr(self._wal, "close"):
            self._wal.close()
        self.ledger_file.close()

    # ------------------------------------------------------------ control queries

    def height(self) -> int:
        with self.lock:
            return len(self.ledger)

    def committed_requests(self) -> int:
        with self.lock:
            ledger = list(self.ledger)
        return sum(len(self.requests_from_proposal(d.proposal)) for d in ledger)

    def committed_ids(self) -> list[str]:
        """Every committed request as "client:rid", in ledger order — the
        chaos runner's exactly-once oracle and the client-resubmission
        check (a request in NO live ledger after quiescence died with a
        killed replica's pool and must be resubmitted, like any BFT
        client would)."""
        with self.lock:
            ledger = list(self.ledger)
        return [
            str(info)
            for d in ledger
            for info in self.requests_from_proposal(d.proposal)
        ]

    def ledger_digest(self, upto: int) -> str:
        """Fork detector: hash of the (payload, metadata) prefix."""
        with self.lock:
            prefix = self.ledger[:upto] if upto else list(self.ledger)
        h = hashlib.sha256()
        for d in prefix:
            h.update(d.proposal.payload)
            h.update(d.proposal.metadata)
        return h.hexdigest()

    def barrier_seq(self, epoch: int) -> int:
        """Ledger position (1-based) of epoch ``epoch``'s committed
        reshard barrier command, 0 while it has not committed here.  The
        cluster manager polls this on every replica after a control-plane
        ``reshard`` trigger: once non-zero everywhere, the resize decision
        is ordered — it rode the stream, not a side channel.  Memoized
        (the position never changes once committed) and incrementally
        scanned, so the manager's poll loop costs O(new entries) per call
        instead of re-decoding the whole ledger on every tick."""
        from ..shard.epoch import barrier_marker

        found = self._barrier_seqs.get(epoch)
        if found:
            return found
        marker = barrier_marker(epoch)
        with self.lock:
            ledger = list(self.ledger)
        for idx in range(self._barrier_scan.get(epoch, 0), len(ledger)):
            infos = self.requests_from_proposal(ledger[idx].proposal)
            if any(str(i) == marker for i in infos):
                self._barrier_seqs[epoch] = idx + 1
                return idx + 1
        self._barrier_scan[epoch] = len(ledger)
        return 0


def _config_from_spec(spec: dict) -> Configuration:
    import dataclasses

    cfg = proc_config(int(spec["node_id"]))
    overrides = spec.get("config") or {}
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg


# --------------------------------------------------------------------------
# control channel (line JSON; parent-facing, never part of consensus)
# --------------------------------------------------------------------------


class ControlServer:
    def __init__(self, replica: ReplicaApp, addr: str, stop_evt: asyncio.Event):
        self.replica = replica
        self.addr = addr
        self.stop_evt = stop_evt
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        scheme, hostpath, port = parse_addr(self.addr)
        if scheme == "tcp":
            self._server = await asyncio.start_server(
                self._serve, host=hostpath, port=port
            )
        else:
            self._server = await asyncio.start_unix_server(
                self._serve, path=hostpath
            )

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
            scheme, hostpath, _ = parse_addr(self.addr)
            if scheme == "uds":
                import contextlib

                with contextlib.suppress(OSError):
                    os.unlink(hostpath)

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    resp = await self._handle(req)
                except Exception as e:  # noqa: BLE001 — control must answer
                    resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                writer.write((json.dumps(resp) + "\n").encode())
                await writer.drain()
        except (ConnectionError, OSError):
            pass
        finally:
            writer.close()

    async def _handle(self, req: dict) -> dict:
        r = self.replica
        cmd = req.get("cmd")
        if cmd == "ping":
            import time

            running = r.consensus is not None and r.consensus._running
            # "now" is this process's monotonic clock — the parent's
            # request/response midpoint against it estimates the clock
            # offset that aligns per-replica trace timestamps onto ONE
            # cluster timeline (SocketCluster.estimate_clock_offsets)
            return {"ok": True, "running": running, "node_id": r.id,
                    "now": time.monotonic()}
        if cmd == "leader":
            lead = r.consensus.get_leader_id() if r.consensus else 0
            return {"ok": True, "leader": lead}
        if cmd == "submit":
            from ..core.pool import AdmissionRejected, SubmitTimeoutError
            from ..testing.app import TestRequest

            raw = encode(TestRequest(
                client_id=req["client"],
                request_id=req["rid"],
                payload=bytes.fromhex(req.get("payload", "")),
            ))
            try:
                await r.consensus.submit_request(raw)
            except AdmissionRejected as e:
                # the PR 8 admission contract, now visible to SOCKET
                # clients: structured reject + drain-rate retry-after
                # hint instead of an opaque error string
                return {
                    "ok": False,
                    "rejected": "admission",
                    "retry_after_ms": int((e.retry_after or 0.0) * 1000),
                    "occupancy": e.occupancy,
                    "error": f"AdmissionRejected: {e}",
                }
            except SubmitTimeoutError as e:
                return {
                    "ok": False,
                    "rejected": "timeout",
                    "retry_after_ms": 0,
                    "occupancy": r.consensus.pool_occupancy(),
                    "error": f"SubmitTimeoutError: {e}",
                }
            return {"ok": True}
        if cmd == "height":
            pool = r.consensus.pool_occupancy() if r.consensus else {}
            return {"ok": True, "height": r.height(),
                    "pool": pool.get("size", 0)}
        if cmd == "occupancy":
            # the autoscaler's saturation signal, per replica — a manager
            # of S socket groups sums these into the ShardSet.occupancy
            # shape and feeds shard.autoscale.OccupancyAutoscaler
            occ = r.consensus.pool_occupancy() if r.consensus else {}
            return {"ok": True, "occupancy": occ}
        if cmd == "reshard":
            # control-plane reshard trigger: order epoch `epoch`'s barrier
            # command through THIS replica's consensus stream (Vertical
            # Paxos rule — the resize decision must ride the ordered
            # stream).  Idempotent: the pool's client dedup absorbs
            # re-triggers after a manager crash.  Construction shared with
            # the in-process harness (testing.app.submit_barrier_request)
            # so the barrier marker can never drift between the two.
            from ..testing.app import submit_barrier_request

            epoch = int(req["epoch"])
            await submit_barrier_request(
                r.consensus, epoch, int(req.get("old", 1)), int(req["new"])
            )
            return {"ok": True, "epoch": epoch,
                    "barrier_seq": r.barrier_seq(epoch)}
        if cmd == "barrier":
            epoch = int(req["epoch"])
            return {"ok": True, "epoch": epoch,
                    "barrier_seq": r.barrier_seq(epoch)}
        if cmd == "committed":
            return {"ok": True, "committed": r.committed_requests(),
                    "height": r.height()}
        if cmd == "committed_ids":
            return {"ok": True, "ids": r.committed_ids()}
        if cmd == "ledger_digest":
            upto = int(req.get("upto", 0))
            return {"ok": True, "digest": r.ledger_digest(upto),
                    "height": r.height()}
        if cmd == "stats":
            return {"ok": True, "transport": r.transport.transport_snapshot(),
                    "height": r.height(),
                    "committed": r.committed_requests()}
        if cmd == "health":
            # live SLO verdict (ISSUE 14): tick once on demand so the
            # answer reflects NOW even between periodic samples, then
            # serve the verdict + recent transitions
            r.health.tick()
            return {
                "ok": True,
                "node": f"n{r.id}",
                "health": r.health.verdict(),
                "transitions": r.health.transition_log()[-16:],
            }
        if cmd == "metrics":
            # Prometheus text exposition over the control channel: the
            # per-replica counters finally have a reader in multi-process
            # deployments (mount behind an HTTP handler in production)
            return {"ok": True, "text": r.metrics_provider.expose()}
        if cmd == "trace":
            # per-replica flight-recorder pull: summary block + events.
            # "since" (an event-sequence cursor from a previous pull's
            # "next_since") ships only NEW events — repeated pulls are
            # O(new), never a re-send of the whole ring; "last" keeps the
            # newest-N semantics.  since wins when both are present.
            last = req.get("last")
            since = req.get("since")
            if since is not None:
                events, cursor = r.recorder.snapshot_since(int(since))
            else:
                # the full/newest-N pull rides the same exact-seqno path
                # (events_since) so next_since can never cover an event
                # the snapshot raced past (recorders are fed from
                # executor threads too — the torn-pair hazard)
                evs, cursor = r.recorder.events_since(0)
                if last is not None:
                    evs = evs[-int(last):] if int(last) else []
                events = [e.as_dict() for e in evs]
            return {
                "ok": True,
                "node": f"n{r.id}",
                "trace": r.recorder.trace_block(),
                "dropped": r.recorder.dropped,
                "events": events,
                "next_since": cursor,
            }
        if cmd == "fault":
            return self._fault(req)
        if cmd == "stop":
            self.stop_evt.set()
            return {"ok": True}
        return {"ok": False, "error": f"unknown cmd {cmd!r}"}

    def _fault(self, req: dict) -> dict:
        """Socket-level chaos: the same fault vocabulary the in-process
        network exposes, applied at the transport."""
        t = self.replica.transport
        action = req.get("action")
        peer = int(req.get("peer", 0))
        peers = [peer] if peer else list(t._peers)
        if action == "mute":
            t.mute()
        elif action == "unmute":
            t.unmute()
        elif action == "drop_link":
            for p in peers:
                t.drop_link(p)
        elif action == "restore_link":
            for p in peers:
                t.restore_link(p)
        elif action == "heal_links":
            for p in list(t._dropped_links):
                t.restore_link(p)
            for p in list(t._slow_links):
                t.slow_link(p, 0.0)
            t.unmute()
        elif action == "slow_link":
            delay = float(req.get("delay", 0.0))
            for p in peers:
                t.slow_link(p, delay)
        else:
            return {"ok": False, "error": f"unknown fault {action!r}"}
        return {"ok": True}


async def run_replica(spec: dict) -> None:
    replica = ReplicaApp(spec)
    stop_evt = asyncio.Event()
    control = ControlServer(replica, spec["control"], stop_evt)
    await control.start()  # control first: the parent polls it for readiness
    await replica.start()
    try:
        await stop_evt.wait()
    finally:
        await replica.stop()
        await control.close()


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="SmartBFT socket replica process")
    ap.add_argument("--spec-file", required=True,
                    help="path to the JSON ReplicaSpec")
    args = ap.parse_args(argv)
    with open(args.spec_file) as fh:
        spec = json.load(fh)
    asyncio.run(run_replica(spec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
