"""SocketComm: the asyncio TCP/UDS implementation of the Comm SPI.

The in-process ``testing.network.Network`` and this transport sit behind
the SAME seam (``api.Comm`` + the optional ``broadcast_consensus``
vectorization hook), so every protocol component is transport-blind.
PR 4 made the message plane carry canonical wire BYTES with encode-once
broadcast — the serialization work a real network needs was already
paid; this module adds the sockets:

* **Encode-once broadcast** — ``broadcast_consensus`` computes the
  canonical encoding once (``messages.wire_of``, memoized on the frozen
  instance), frames it once, and enqueues the SAME bytes object on every
  peer's outbox;
* **Per-wave write coalescing** — each peer has one sender task that
  drains the WHOLE outbox per wakeup and writes it as one
  ``writev``-style batch (one ``write`` + one ``drain`` per wave),
  mirroring PR 4's wave-batched ingest on the send side.  A depth-k
  window's k pre-prepares leave in one flush instead of k;
* **Wave-batched ingest** — one ``reader.read()`` returns whatever the
  peer's last flush carried; every complete frame in it is decoded
  (``messages.unmarshal_interned``) and handed to
  ``Consensus.handle_message_batch`` in ONE call, so a quorum wave
  registers in one scheduler tick — identical to the in-process plane;
* **Reconnect with exponential backoff + jitter** — the same retry
  idiom as the PR 3 verify plane: base doubles to a cap, each sleep is
  multiplied by ``1 ± jitter`` so n replicas redialing a restarted peer
  do not thundering-herd it;
* **Loud-but-bounded peer death** — outboxes are capped deques: when a
  peer is down past its cap the OLDEST frame is dropped and counted
  (protocol recovery — re-sends, view changes, sync — is built for loss;
  unbounded queues are how one dead peer OOMs a live replica);
* **Malformed frames drop the connection, loudly** — a bad length
  prefix, unknown frame type, or undecodable consensus payload counts
  in metrics and closes THAT connection; the replica and the intern LRU
  (which only caches successful decodes) are untouched;
* **Wire tracing sidecar (ISSUE 13)** — while this node's flight
  recorder is armed, each coalesced flush appends at most ONE untagged
  ``FT_TRACE`` frame batching the flush's correlation contexts (request
  key / (view, seq), origin, hop counter) plus the sender's monotonic
  flush stamp; the receive side records one ``net.recv`` event per
  context and remembers request hop chains so re-forwards continue
  them.  Data-frame counts and the canonical consensus encoding are
  untouched; sidecar loss costs timeline coverage, never correctness.

Connections are DIRECTED: each node dials every peer and uses that
connection only for its own sends; inbound connections only receive.
Two simplex links per pair cost one extra fd but remove all tie-break
complexity (simultaneous dial, connection reuse races), and a link
fault maps 1:1 onto a socket: dropping my outbound link to you is
exactly "my sends stop reaching you".
"""

from __future__ import annotations

import asyncio
import contextlib
import random
import time
from collections import OrderedDict, deque
from time import perf_counter
from typing import Callable, Optional

from ..api import Comm
from ..codec import CodecError, decode, encode
from ..messages import Message, unmarshal_interned, wire_of
from ..metrics import PROTOCOL_PLANE, install_plane, reset_plane
from ..utils.logging import StdLogger
from ..utils.tasks import create_logged_task
from .framing import (
    DEFAULT_MAX_FRAME_BYTES,
    FT_CONSENSUS,
    FT_HELLO,
    FT_READ_REQ,
    FT_READ_RESP,
    FT_REJECT,
    FT_REQUEST,
    FT_SNAP_REQ,
    FT_SNAP_RESP,
    FT_SYNC_REQ,
    FT_SYNC_RESP,
    FT_TRACE,
    FrameDecoder,
    FrameError,
    Hello,
    ReadRequest,
    ReadResponse,
    RejectFrame,
    SnapshotChunk,
    SnapshotFetchRequest,
    SyncBatch,
    SyncRequest,
    TraceCtx,
    TraceFrame,
    encode_frame,
    parse_addr,
    reject_digest,
)

#: read-buffer size per reader.read() call; one sender flush usually fits
READ_CHUNK = 256 * 1024

#: per-connection-attempt timeout (a dead TCP peer can otherwise park the
#: dial in SYN-retry for minutes; UDS fails instantly either way)
CONNECT_TIMEOUT = 3.0

#: a connection whose first frame is not a valid HELLO within this window
#: is rejected (handshake_rejected) — garbage dialers cannot hold fds open
HANDSHAKE_TIMEOUT = 5.0

#: SyncBatch responses are capped at this many decisions per round trip;
#: the requester loops until caught up.  A BYTE budget additionally caps
#: each batch under the frame cap (see ``_serve_sync``) — a deep tail of
#: fat decisions pages across continuation requests instead of emitting
#: one over-cap frame that would poison the connection it rides on.
MAX_SYNC_DECISIONS = 256

#: frame-envelope headroom reserved out of max_frame_bytes when budgeting
#: a SyncBatch / SnapshotChunk (codec framing + the non-payload fields)
FRAME_ENVELOPE_BYTES = 65536

#: resume attempts for one snapshot transfer before giving up (each
#: retry re-requests from the current offset — the reconnect-resume path)
SNAP_FETCH_RETRIES = 8

#: bounded memory of inbound request trace contexts (key -> (origin, hop))
#: used to continue the hop chain when this node re-forwards a request;
#: beyond the cap the OLDEST entry is evicted (telemetry, never state)
REQ_HOP_CAP = 1024


class TransportMetrics:
    """Per-transport counters, exported as the ``transport`` block in
    bench rows and readable over the replica control channel.  Separate
    from ProtocolPlaneTimers: the plane accounts protocol-core cost
    (codec/ingest/route/vote-reg), this accounts the SOCKET layer —
    bytes, frames, flushes, reconnects, drops."""

    __slots__ = (
        "bytes_sent", "bytes_received", "frames_sent", "frames_received",
        "flush_batches", "ingest_batches", "connects", "reconnects",
        "connect_failures", "outbox_dropped", "link_dropped",
        "malformed_frames", "connections_dropped", "handshake_rejected",
        "sync_requests", "sync_responses", "rejects_sent", "rejects_received",
        "trace_frames_sent", "trace_frames_received", "trace_ctxs_sent",
        # ISSUE 17: sync paging + snapshot state transfer.  sync_batches /
        # sync_bytes count SERVED SyncBatch replies and their decision
        # payload bytes (the paging satellite's accounting); the snap_*
        # counters meter the chunked snapshot RPC on both sides; and
        # sync_poisoned counts inbound batches/snapshots REJECTED by the
        # embedder's certificate verification (bumped by the app layer —
        # the transport is payload-agnostic, the counter lives here so it
        # rides the same transport_snapshot()/bench surface).
        "sync_batches", "sync_bytes", "snap_requests", "snap_chunks_sent",
        "snap_chunks_received", "snap_bytes_sent", "snap_bytes_received",
        "sync_poisoned",
        # ISSUE 19: the read/serving plane.  read_requests counts inbound
        # FT_READ_REQ served by this node, read_responses the replies that
        # resolved a local waiter; read_sheds_sent counts reads the LOCAL
        # token-bucket gate refused (the serving side of "a read storm
        # degrades reads, never writes"), read_sheds_received the shed
        # replies this node's clients got back; read_base_refused counts
        # read-at-base requests refused because the snapshot base was
        # torn/tampered/absent (the loud-refusal satellite).
        "read_requests", "read_responses", "read_sheds_sent",
        "read_sheds_received", "read_base_refused",
    )

    def __init__(self) -> None:
        for name in self.__slots__:
            setattr(self, name, 0)

    def snapshot(self) -> dict:
        snap = {name: getattr(self, name) for name in self.__slots__}
        snap["frames_per_flush"] = (
            round(self.frames_sent / self.flush_batches, 2)
            if self.flush_batches else 0.0
        )
        return snap


class _Peer:
    """Sender-side state for one outbound (directed) link."""

    __slots__ = ("id", "addr", "outbox", "wake", "task", "connected",
                 "trace_pending")

    def __init__(self, peer_id: int, addr: str):
        self.id = peer_id
        self.addr = addr
        self.outbox: deque = deque()
        self.wake: Optional[asyncio.Event] = None  # created on start()
        self.task: Optional[asyncio.Task] = None
        self.connected = False
        #: correlation contexts for data frames awaiting the next flush's
        #: FT_TRACE sidecar (only populated while wire tracing is armed)
        self.trace_pending: deque = deque()


class SocketComm(Comm):
    """Asyncio TCP/UDS node-to-node transport (see module docstring).

    ``peers`` maps node id -> address string for every OTHER replica;
    ``listen`` is this node's own address (``tcp://host:port`` with port
    0 for ephemeral, or ``uds:///path``).  ``consensus`` must be
    attached (:meth:`attach`) before traffic flows; frames arriving
    before that are dropped and counted.
    """

    def __init__(
        self,
        self_id: int,
        listen: str,
        peers: dict[int, str],
        *,
        cluster_key: bytes = b"",
        group: int = 0,
        outbox_cap: int = 4096,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        backoff_jitter: float = 0.25,
        max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
        logger=None,
        plane=None,
        rng: Optional[random.Random] = None,
    ):
        if self_id in peers:
            raise ValueError(f"peers must not contain self_id {self_id}")
        self.self_id = self_id
        self.listen = listen
        self.group = group
        self.cluster_key = bytes(cluster_key)
        self.outbox_cap = outbox_cap
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.backoff_jitter = backoff_jitter
        self.max_frame_bytes = max_frame_bytes
        self.logger = logger or StdLogger(f"smartbft.net.{self_id}")
        self.plane = PROTOCOL_PLANE if plane is None else plane
        self.metrics = TransportMetrics()
        # flight recorder for control-plane transitions (reconnects);
        # the embedder swaps in a real obs.TraceRecorder when tracing
        from ..obs.recorder import NOP_RECORDER

        self.recorder = NOP_RECORDER
        #: optional embedder hook mapping raw request bytes -> the request
        #: key ("client:rid") so FT_TRACE sidecars carry the SAME
        #: correlator the flight recorder stamps on req.submit/req.deliver
        #: (the transport itself is payload-agnostic); failures fall back
        #: to an empty key — the context still carries origin + hop
        self.request_key_fn: Optional[Callable[[bytes], object]] = None
        #: inbound request contexts (key -> (origin, hop)) so a re-forward
        #: of the same request continues its hop chain; bounded LRU
        self._req_hops: "OrderedDict[str, tuple[int, int]]" = OrderedDict()
        self.consensus = None
        #: multi-process sync server hook: (from_height) -> (decisions,
        #: total_height) with decisions a list[framing.WireDecision]; the
        #: embedder should materialize at most MAX_SYNC_DECISIONS — the
        #: transport additionally byte-budgets the reply under the frame
        #: cap and pages the rest via continuation requests
        self.sync_server: Optional[Callable[[int], tuple[list, int]]] = None
        #: snapshot state-transfer hook (ISSUE 17), duck-typed:
        #:   describe() -> Optional[(height, total_bytes, digest)] — the
        #:     snapshot currently on offer (None = no snapshot);
        #:   read_chunk(height, offset, max_bytes) ->
        #:     (total_bytes, data, last) — one bounded slice of the
        #:     snapshot file at `height`; total_bytes == 0 means that
        #:     snapshot is gone (superseded mid-transfer) and the
        #:     requester must restart against the current offer.
        self.snapshot_server = None
        #: read-plane server hook (ISSUE 19), duck-typed like sync_server:
        #: (framing.ReadRequest) -> framing.ReadResponse, answered from
        #: COMMITTED state only.  The embedder owns the token-bucket gate
        #: and returns a shed-shaped response when it refuses; the
        #: transport just counts and carries.  None = reads unserved
        #: (requester times out, same as a down peer).
        self.read_server: Optional[Callable[[ReadRequest], ReadResponse]] = None
        #: optional embedder hook: (sender_id, framing.RejectFrame) called
        #: on every received FT_REJECT (the peer shed a request this node
        #: forwarded); the last few frames are kept in `rejects` either way
        self.on_reject: Optional[Callable[[int, RejectFrame], None]] = None
        #: bounded record of received reject frames (newest last) — the
        #: client-visible admission contract over the wire, readable via
        #: the control channel / tests without installing a hook
        self.rejects: deque = deque(maxlen=64)
        self._rng = rng or random.Random(self_id * 7919 + 17)
        self._peers: dict[int, _Peer] = {
            pid: _Peer(pid, addr) for pid, addr in peers.items()
        }
        self._server: Optional[asyncio.AbstractServer] = None
        self._bound_addr: Optional[str] = None
        self._reader_tasks: set[asyncio.Task] = set()
        self._inbound_writers: set[asyncio.StreamWriter] = set()
        self._sync_waiters: dict[int, asyncio.Future] = {}
        self._snap_waiters: dict[int, asyncio.Future] = {}
        self._read_waiters: dict[int, asyncio.Future] = {}
        self._sync_nonce = 0
        self._started = False
        self._closing = False
        self._closed_evt: Optional[asyncio.Event] = None
        # fault injection (socket-level chaos)
        self.muted = False
        self._dropped_links: set[int] = set()
        self._slow_links: dict[int, float] = {}
        #: per-peer RTT estimate (seconds), EWMA over measured round
        #: trips: the TCP dial (connect = one SYN/SYN-ACK round trip;
        #: UDS connects in ~µs, which is the true loopback answer) and
        #: every sync RPC.  Consumed by Pool via Consensus's
        #: forward-timeout derivation (request_forward_rtt_multiplier):
        #: round 16 measured follower-submitted requests spending 97.6%
        #: of their latency waiting out the FIXED forward constant.
        self._rtt: dict[int, float] = {}

    @classmethod
    def from_config(cls, config, peers: dict[int, str], *,
                    listen: Optional[str] = None, **kw) -> "SocketComm":
        """Build from the Configuration transport knobs (the same fields
        ConfigMirror round-trips through a reconfiguration)."""
        return cls(
            config.self_id,
            listen if listen is not None else config.transport_listen,
            peers,
            outbox_cap=config.transport_outbox_cap,
            backoff_base=config.transport_reconnect_backoff_base,
            backoff_max=config.transport_reconnect_backoff_max,
            max_frame_bytes=config.transport_max_frame_bytes,
            **kw,
        )

    # ------------------------------------------------------------ lifecycle

    def attach(self, consensus) -> None:
        """Point ingest at the consensus intake (any object exposing the
        handle_message_batch / handle_request surface)."""
        self.consensus = consensus

    @property
    def bound_addr(self) -> str:
        """The address actually bound (resolves tcp port 0); valid after
        :meth:`start`."""
        return self._bound_addr or self.listen

    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._closing = False
        self._closed_evt = asyncio.Event()
        scheme, hostpath, port = parse_addr(self.listen)
        if scheme == "tcp":
            self._server = await asyncio.start_server(
                self._on_connection, host=hostpath, port=port
            )
            bound = self._server.sockets[0].getsockname()
            self._bound_addr = f"tcp://{bound[0]}:{bound[1]}"
        else:
            self._server = await asyncio.start_unix_server(
                self._on_connection, path=hostpath
            )
            self._bound_addr = self.listen
        for peer in self._peers.values():
            peer.wake = asyncio.Event()
            if peer.outbox:
                peer.wake.set()
            peer.task = create_logged_task(
                self._peer_sender(peer),
                name=f"net-send-{self.self_id}->{peer.id}",
                logger=self.logger,
            )

    async def close(self) -> None:
        """Graceful shutdown contract: stop accepting, drain + close every
        sender, cancel every reader, close every inbound connection — the
        transport leaves ZERO background tasks and zero open sockets."""
        if not self._started or self._closing:
            return
        self._closing = True
        self._closed_evt.set()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        # senders: wake them so each drains its outbox once and exits
        for peer in self._peers.values():
            if peer.wake is not None:
                peer.wake.set()
        sender_tasks = [p.task for p in self._peers.values() if p.task]
        if sender_tasks:
            await asyncio.gather(*sender_tasks, return_exceptions=True)
        for peer in self._peers.values():
            peer.task = None
        # readers: nothing to drain on the receive side — cancel
        for task in list(self._reader_tasks):
            task.cancel()
        if self._reader_tasks:
            await asyncio.gather(*self._reader_tasks, return_exceptions=True)
        self._reader_tasks.clear()
        for writer in list(self._inbound_writers):
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()
        self._inbound_writers.clear()
        for fut in self._sync_waiters.values():
            if not fut.done():
                fut.cancel()
        self._sync_waiters.clear()
        for fut in self._snap_waiters.values():
            if not fut.done():
                fut.cancel()
        self._snap_waiters.clear()
        for fut in self._read_waiters.values():
            if not fut.done():
                fut.cancel()
        self._read_waiters.clear()
        scheme, hostpath, _ = parse_addr(self.listen)
        if scheme == "uds":
            import os

            with contextlib.suppress(OSError):
                os.unlink(hostpath)
        self._started = False

    # ------------------------------------------------------------ Comm SPI

    def nodes(self) -> list[int]:
        return sorted([self.self_id, *self._peers.keys()])

    def send_consensus(self, target_id: int, msg: Message) -> None:
        if self.muted:
            return
        self.plane.sends += 1
        wire = wire_of(msg, self.plane)
        self._enqueue(target_id, encode_frame(FT_CONSENSUS, wire))
        if self.recorder.enabled:
            self._trace_ctx(target_id, self._consensus_ctx(msg))

    def broadcast_consensus(self, msg: Message,
                            targets: Optional[list[int]] = None) -> None:
        """Encode-once fan-out: ONE canonical encoding, ONE frame object,
        shared by reference across every peer outbox."""
        self.plane.broadcasts += 1
        if self.muted:
            return  # outbound silence: nothing leaves, nothing encodes
        t0 = perf_counter()
        codec0 = self.plane.codec_us
        frame = encode_frame(FT_CONSENSUS, wire_of(msg, self.plane))
        ctx = self._consensus_ctx(msg) if self.recorder.enabled else None
        for target in (targets if targets is not None else self._peers):
            if target == self.self_id:
                continue
            self._enqueue(target, frame)
            if ctx is not None:
                # ONE frozen context object shared across every sidecar,
                # mirroring the encode-once data frame
                self._trace_ctx(target, ctx)
        # disjoint accounting: encode time is already in codec_us
        self.plane.route_us += (
            (perf_counter() - t0) * 1e6 - (self.plane.codec_us - codec0)
        )

    def send_transaction(self, target_id: int, request: bytes) -> None:
        if self.muted:
            return
        self._enqueue(target_id, encode_frame(FT_REQUEST, request))
        if self.recorder.enabled:
            key = self._request_key(request)
            # continue the hop chain of a remembered inbound context (a
            # forward of a forward); otherwise this node originates it
            origin, hop = self._req_hops.get(key, (self.self_id, 0)) \
                if key else (self.self_id, 0)
            self._trace_ctx(target_id, TraceCtx(
                kind="request", key=key, origin=origin, hop=hop + 1,
            ))

    # ------------------------------------------------------------ tracing

    def _consensus_ctx(self, msg: Message) -> TraceCtx:
        """Correlation context for one consensus message: class name +
        (view, seq) when the message carries them (pre-prepare / prepare /
        commit / heartbeat do; view-change messages carry other fields and
        correlate by kind + origin alone)."""
        view = getattr(msg, "view", 0)
        seq = getattr(msg, "seq", 0)
        return TraceCtx(
            kind=type(msg).__name__,
            view=view if isinstance(view, int) and view >= 0 else 0,
            seq=seq if isinstance(seq, int) and seq >= 0 else 0,
            origin=self.self_id,
            hop=1,
        )

    def _request_key(self, request: bytes) -> str:
        if self.request_key_fn is None:
            return ""
        try:
            return str(self.request_key_fn(request))
        except Exception:  # noqa: BLE001 — telemetry must never shed traffic
            return ""

    def _trace_ctx(self, target: int, ctx: TraceCtx) -> None:
        """Stage one sidecar context for ``target``'s next flush.  Mirrors
        the outbox's fault surface (dropped links stage nothing) and its
        bound (oldest context dropped past the cap) — contexts are
        advisory, so a mismatch after drops costs coverage, not
        correctness."""
        peer = self._peers.get(target)
        if peer is None or target in self._dropped_links:
            return
        if len(peer.trace_pending) >= self.outbox_cap:
            peer.trace_pending.popleft()
        peer.trace_pending.append(ctx)

    def _on_trace_frame(self, sender: int, payload: bytes,
                        recv_t: Optional[float] = None) -> None:
        """Ingest one FT_TRACE sidecar: remember request hop chains and —
        when this node's recorder is armed — stamp one ``net.recv`` event
        per context (receiver-ingest side of the per-hop network time;
        the sender's ``sent_us`` rides in ``extra`` for the clock-aligned
        merge to subtract).  ``recv_t`` is the socket READ time of the
        batch the sidecar arrived in (time.monotonic, the recorder's
        clock domain): the dispatch loop awaits consensus handling of
        the wave BEFORE reaching this frame, and stamping at record time
        would book that compute as wire time."""
        frame = decode(TraceFrame, payload)  # CodecError -> drop conn
        self.metrics.trace_frames_received += 1
        rec = self.recorder
        for e in frame.entries:
            if e.kind == "request" and e.key:
                self._req_hops[e.key] = (e.origin, e.hop)
                self._req_hops.move_to_end(e.key)
                if len(self._req_hops) > REQ_HOP_CAP:
                    self._req_hops.popitem(last=False)
            if rec.enabled:
                consensus_kind = e.kind != "request"
                rec.record(
                    "net.recv",
                    key=e.key,
                    view=e.view if consensus_kind else -1,
                    seq=e.seq if consensus_kind else -1,
                    extra={"from": sender, "origin": e.origin, "hop": e.hop,
                           "sent_us": frame.sent_us, "wire": e.kind},
                    t=recv_t,
                )

    # ------------------------------------------------------------ send path

    def _enqueue(self, target: int, frame: bytes) -> None:
        peer = self._peers.get(target)
        if peer is None:
            return
        if target in self._dropped_links:
            self.metrics.link_dropped += 1
            return
        if len(peer.outbox) >= self.outbox_cap:
            # loud-but-bounded: drop the OLDEST frame (the protocol's
            # recovery paths — re-sends, view change, sync — are built for
            # loss; what it cannot survive is unbounded memory growth).
            # Its staged trace context drops with it (oldest-for-oldest —
            # approximate, since untraced frame kinds hold no context,
            # but it keeps the sidecar from advertising frames that never
            # went out; phantom net.recv events would fabricate coverage
            # exactly under the overload the recorder exists to diagnose)
            peer.outbox.popleft()
            if peer.trace_pending:
                peer.trace_pending.popleft()
            self.metrics.outbox_dropped += 1
            if self.metrics.outbox_dropped % 1000 == 1:
                self.logger.warnf(
                    "outbox to peer %d full (cap %d): dropping oldest "
                    "(%d dropped so far)",
                    target, self.outbox_cap, self.metrics.outbox_dropped,
                )
        peer.outbox.append(frame)
        if peer.wake is not None:
            peer.wake.set()

    async def _peer_sender(self, peer: _Peer) -> None:
        """Connect loop + per-wave flush loop for one directed link."""
        backoff = self.backoff_base
        first = True
        while not self._closing:
            try:
                t_dial = perf_counter()
                reader, writer = await asyncio.wait_for(
                    self._dial(peer.addr), timeout=CONNECT_TIMEOUT
                )
                self._note_rtt(peer.id, perf_counter() - t_dial)
            except (OSError, asyncio.TimeoutError):
                self.metrics.connect_failures += 1
                if self._closing:
                    return
                await self._backoff_sleep(backoff)
                backoff = min(backoff * 2, self.backoff_max)
                continue
            self.metrics.connects += 1
            if not first:
                self.metrics.reconnects += 1
                if self.recorder.enabled:
                    self.recorder.record("ctl.reconnect",
                                         extra={"peer": peer.id})
            first = False
            backoff = self.backoff_base
            peer.connected = True
            try:
                hello = Hello(node_id=self.self_id, group=self.group,
                              key=self.cluster_key)
                writer.write(encode_frame(FT_HELLO, encode(hello)))
                await writer.drain()
                await self._flush_loop(peer, writer)
                return  # clean close() exit
            except (OSError, ConnectionError, asyncio.TimeoutError) as e:
                self.logger.warnf(
                    "link %d->%d broke (%r); reconnecting",
                    self.self_id, peer.id, e,
                )
            finally:
                peer.connected = False
                writer.close()
                with contextlib.suppress(Exception):
                    await writer.wait_closed()

    async def _dial(self, addr: str):
        scheme, hostpath, port = parse_addr(addr)
        if scheme == "tcp":
            return await asyncio.open_connection(host=hostpath, port=port)
        return await asyncio.open_unix_connection(path=hostpath)

    async def _flush_loop(self, peer: _Peer, writer: asyncio.StreamWriter) -> None:
        """Drain the whole outbox per wakeup and write it as ONE batch —
        the send-side mirror of wave-batched ingest.  On close(), performs
        one final drain so frames already accepted are not stranded."""
        while True:
            while not peer.outbox and not self._closing:
                peer.wake.clear()
                await peer.wake.wait()
            delay = self._slow_links.get(peer.id)
            if delay:
                await asyncio.sleep(delay)
            batch_len = len(peer.outbox)
            if batch_len:
                pending = [peer.outbox.popleft() for _ in range(batch_len)]
                ctxs = None
                if peer.trace_pending and self.recorder.enabled:
                    # ONE sidecar frame per flush describing the whole
                    # batch (the write-coalescing contract).  The sidecar
                    # stays OUT of `pending`: a mid-flush failure hands
                    # the contexts back to trace_pending so the retry
                    # flush re-encodes them with a FRESH sent_us stamp
                    # (a re-queued stale stamp would book the whole
                    # reconnect outage as per-link network time) and the
                    # data-frame accounting below never counts it
                    ctxs = list(peer.trace_pending)
                    peer.trace_pending.clear()
                elif peer.trace_pending:
                    # tracing disarmed between enqueue and flush: drop the
                    # stale contexts instead of letting them accumulate
                    peer.trace_pending.clear()
                try:
                    blob = b"".join(pending)
                    if ctxs:
                        blob += encode_frame(FT_TRACE, encode(TraceFrame(
                            origin=self.self_id,
                            sent_us=int(time.monotonic() * 1e6),
                            entries=ctxs,
                        )))
                    writer.write(blob)
                    await writer.drain()
                except BaseException:
                    # the link died mid-flush: re-queue the batch at the
                    # front (new frames may have arrived behind it) so the
                    # reconnect delivers it instead of silently losing it
                    peer.outbox.extendleft(reversed(pending))
                    if ctxs:
                        peer.trace_pending.extendleft(reversed(ctxs))
                    raise
                self.metrics.flush_batches += 1
                self.metrics.frames_sent += batch_len
                self.metrics.bytes_sent += len(blob)
                if ctxs:
                    self.metrics.trace_frames_sent += 1
                    self.metrics.trace_ctxs_sent += len(ctxs)
            if self._closing and not peer.outbox:
                return

    async def _backoff_sleep(self, delay: float) -> None:
        jitter = 1.0 + self.backoff_jitter * (2 * self._rng.random() - 1.0)
        with contextlib.suppress(asyncio.TimeoutError):
            # close() sets the event, so a parked reconnect wakes instantly
            await asyncio.wait_for(self._closed_evt.wait(), delay * jitter)

    # ------------------------------------------------------------ recv path

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        # runs AS the server's connection task; register for cancellation
        task = asyncio.current_task()
        self._reader_tasks.add(task)
        self._inbound_writers.add(writer)
        try:
            await self._serve_connection(reader, writer)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — one bad conn never kills the node
            self.logger.errorf("inbound connection handler died: %r", e)
        finally:
            self._reader_tasks.discard(task)
            self._inbound_writers.discard(writer)
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()

    async def _serve_connection(self, reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter) -> None:
        decoder = FrameDecoder(self.max_frame_bytes)
        # -- handshake: first frame must be a valid HELLO with our key
        sender: Optional[int] = None
        try:
            # ONE deadline for the whole handshake (not per read: a
            # trickling dialer must not hold the fd open by sending one
            # byte per read-timeout window)
            deadline = asyncio.get_running_loop().time() + HANDSHAKE_TIMEOUT
            frames: list = []
            while not frames:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    raise asyncio.TimeoutError("handshake deadline expired")
                data = await asyncio.wait_for(reader.read(READ_CHUNK), remaining)
                if not data:
                    return  # dialer went away before the hello
                frames = decoder.feed(data)
            ftype, payload = frames[0]
            if ftype != FT_HELLO:
                raise FrameError(f"first frame is type {ftype}, not HELLO")
            hello = decode(Hello, payload)
            if hello.key != self.cluster_key:
                raise FrameError("cluster key mismatch")
            if hello.node_id == self.self_id or (
                hello.node_id not in self._peers
            ):
                raise FrameError(f"unknown peer id {hello.node_id}")
            sender = hello.node_id
            frames = frames[1:]
        except (FrameError, CodecError, asyncio.TimeoutError) as e:
            self.metrics.handshake_rejected += 1
            self.logger.warnf("rejecting inbound connection: %r", e)
            return
        # -- steady state: read -> decode frames -> batch-dispatch
        try:
            recv_t = time.monotonic()  # covers handshake-leftover frames
            while True:
                if frames:
                    await self._dispatch(sender, frames, recv_t)
                data = await reader.read(READ_CHUNK)
                if not data:
                    return  # peer closed cleanly (its reconnect, our EOF)
                # the batch's arrival instant, captured BEFORE dispatch
                # awaits consensus handling (net.recv timestamps use it)
                recv_t = time.monotonic()
                frames = decoder.feed(data)
        except (FrameError, CodecError) as e:
            # poisoned stream: drop THIS connection loudly; the peer's
            # sender will redial and resume from a clean framing state
            self.metrics.malformed_frames += 1
            self.metrics.connections_dropped += 1
            self.plane.malformed_dropped += 1
            self.logger.warnf(
                "dropping connection from peer %s: malformed frame (%r)",
                sender, e,
            )

    async def _dispatch(self, sender: int, frames: list,
                        recv_t: Optional[float] = None) -> None:
        """Decode (interned) and route one read's frames, preserving
        arrival order across kinds — the socket twin of testing.network.
        Node._dispatch, with the same disjoint plane accounting.
        ``recv_t`` is the batch's socket read time (see
        :meth:`_on_trace_frame`)."""
        if sender in self._dropped_links:
            self.metrics.link_dropped += len(frames)
            return
        plane = self.plane
        t0 = perf_counter()
        codec0 = plane.codec_us
        vote0 = plane.vote_reg_us
        token = install_plane(plane)
        poisoned: Optional[CodecError] = None
        try:
            run: list = []  # consecutive (sender, msg) consensus pairs
            for ftype, payload in frames:
                if ftype == FT_CONSENSUS:
                    try:
                        msg = unmarshal_interned(payload, plane)
                    except CodecError as e:
                        # flush what already decoded, then poison the conn
                        poisoned = e
                        break
                    run.append((sender, msg))
                elif ftype == FT_REQUEST:
                    await self._flush_consensus(run)
                    if self.consensus is not None:
                        shed = await self.consensus.handle_request(
                            sender, payload
                        )
                        if shed is not None:
                            self._send_reject(sender, payload, shed)
                elif ftype == FT_REJECT:
                    await self._flush_consensus(run)
                    self._on_reject_frame(sender, payload)
                elif ftype == FT_TRACE:
                    await self._flush_consensus(run)
                    self._on_trace_frame(sender, payload, recv_t)
                elif ftype == FT_SYNC_REQ:
                    await self._flush_consensus(run)
                    self._serve_sync(sender, payload)
                elif ftype == FT_SYNC_RESP:
                    await self._flush_consensus(run)
                    self._resolve_sync(payload)
                elif ftype == FT_SNAP_REQ:
                    await self._flush_consensus(run)
                    self._serve_snapshot(sender, payload)
                elif ftype == FT_SNAP_RESP:
                    await self._flush_consensus(run)
                    self._resolve_snapshot(payload)
                elif ftype == FT_READ_REQ:
                    await self._flush_consensus(run)
                    self._serve_read(sender, payload)
                elif ftype == FT_READ_RESP:
                    await self._flush_consensus(run)
                    self._resolve_read(payload)
                else:  # FT_HELLO after handshake: tolerated no-op
                    continue
            await self._flush_consensus(run)
        finally:
            reset_plane(token)
        plane.ingest_us += (
            (perf_counter() - t0) * 1e6
            - (plane.codec_us - codec0)
            - (plane.vote_reg_us - vote0)
        )
        plane.batch_ingests += 1
        plane.msgs_ingested += len(frames)
        self.metrics.ingest_batches += 1
        self.metrics.frames_received += len(frames)
        self.metrics.bytes_received += sum(len(p) + 5 for _, p in frames)
        if poisoned is not None:
            raise poisoned

    async def _flush_consensus(self, run: list) -> None:
        if not run:
            return
        c = self.consensus
        if c is None:
            run.clear()
            return
        batch_async = getattr(c, "handle_message_batch_async", None)
        if batch_async is not None:
            await batch_async(list(run))
        else:
            batch_sync = getattr(c, "handle_message_batch", None)
            if batch_sync is not None:
                batch_sync(list(run))
            else:
                for sender, msg in run:
                    c.handle_message(sender, msg)
        run.clear()

    # ------------------------------------------------------------ rejects

    def _send_reject(self, sender: int, payload: bytes, shed) -> None:
        """Turn a pool shed of a forwarded request into a structured
        REJECT frame back to the forwarder (the PR 8 admission contract,
        now visible across the wire instead of dying inside this
        process).  Advisory: the forwarder's pool timers keep running."""
        from ..core.pool import AdmissionRejected

        retry_after = float(getattr(shed, "retry_after", 0.0) or 0.0)
        occ = getattr(shed, "occupancy", None) or {}
        kind = "admission" if isinstance(shed, AdmissionRejected) \
            else "timeout"
        frame = RejectFrame(
            kind=kind,
            reason=str(shed)[:512],
            retry_after_ms=int(retry_after * 1000),
            occupancy=int(occ.get("size", 0) or 0),
            high_water=int(occ.get("high_water", 0) or 0),
            request_digest=reject_digest(payload),
        )
        self._enqueue(sender, encode_frame(FT_REJECT, encode(frame)))
        self.metrics.rejects_sent += 1

    def _on_reject_frame(self, sender: int, payload: bytes) -> None:
        frame = decode(RejectFrame, payload)  # CodecError -> drop conn
        self.metrics.rejects_received += 1
        self.rejects.append((sender, frame))
        self.logger.warnf(
            "peer %d shed a forwarded request (%s, retry-after %d ms)",
            sender, frame.kind, frame.retry_after_ms,
        )
        if self.on_reject is not None:
            try:
                self.on_reject(sender, frame)
            except Exception as e:  # noqa: BLE001 — embedder hook
                self.logger.warnf("on_reject hook failed: %r", e)

    # ------------------------------------------------------------ sync RPC

    def _serve_sync(self, sender: int, payload: bytes) -> None:
        req = decode(SyncRequest, payload)  # CodecError -> drop conn (caller)
        self.metrics.sync_requests += 1
        if self.sync_server is None:
            return
        decisions, total = self.sync_server(req.from_height)
        # double cap: decision count AND encoded bytes under the frame
        # cap.  At least one decision always ships (the loop's progress
        # guarantee); an over-budget single decision still fits the frame
        # because transport_max_frame_bytes exceeds any legal proposal by
        # the validated envelope headroom.
        budget = self.max_frame_bytes - FRAME_ENVELOPE_BYTES
        picked: list = []
        used = 0
        for wd in decisions[:MAX_SYNC_DECISIONS]:
            size = len(encode(wd))
            if picked and used + size > budget:
                break
            picked.append(wd)
            used += size
        offer_height = offer_bytes = 0
        offer_digest = b""
        snap = self.snapshot_server
        if snap is not None:
            desc = snap.describe()
            if desc is not None and desc[0] > req.from_height:
                offer_height, offer_bytes, offer_digest = desc
        resp = SyncBatch(
            nonce=req.nonce,
            from_height=req.from_height,
            total_height=total,
            decisions=picked,
            snapshot_height=offer_height,
            snapshot_bytes=offer_bytes,
            snapshot_digest=offer_digest,
        )
        self._enqueue(sender, encode_frame(FT_SYNC_RESP, encode(resp)))
        self.metrics.sync_batches += 1
        self.metrics.sync_bytes += used

    def _resolve_sync(self, payload: bytes) -> None:
        resp = decode(SyncBatch, payload)  # CodecError -> drop conn (caller)
        self.metrics.sync_responses += 1
        fut = self._sync_waiters.pop(resp.nonce, None)
        if fut is not None and not fut.done():
            fut.set_result(resp)

    async def request_sync(self, target: int, from_height: int,
                           timeout: float = 2.0) -> Optional[SyncBatch]:
        """One sync round trip to ``target``; None on timeout / peer down."""
        self._sync_nonce += 1
        nonce = self._sync_nonce
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._sync_waiters[nonce] = fut
        req = SyncRequest(nonce=nonce, from_height=from_height)
        t0 = perf_counter()
        self._enqueue(target, encode_frame(FT_SYNC_REQ, encode(req)))
        try:
            resp = await asyncio.wait_for(fut, timeout)
            # a completed sync RPC is a measured round trip (enqueue ->
            # response dispatch): opportunistically refresh the RTT
            self._note_rtt(target, perf_counter() - t0)
            return resp
        except (asyncio.TimeoutError, asyncio.CancelledError):
            return None
        finally:
            self._sync_waiters.pop(nonce, None)

    # ------------------------------------------------------------ snapshot RPC

    def _serve_snapshot(self, sender: int, payload: bytes) -> None:
        req = decode(SnapshotFetchRequest, payload)  # CodecError -> drop conn
        self.metrics.snap_requests += 1
        snap = self.snapshot_server
        if snap is None:
            return
        max_bytes = min(
            req.max_bytes or self.max_frame_bytes,
            self.max_frame_bytes - FRAME_ENVELOPE_BYTES,
        )
        total, data, last = snap.read_chunk(req.height, req.offset, max_bytes)
        chunk = SnapshotChunk(
            nonce=req.nonce,
            height=req.height,
            total_bytes=total,
            offset=req.offset,
            data=data,
            last=last,
        )
        self._enqueue(sender, encode_frame(FT_SNAP_RESP, encode(chunk)))
        self.metrics.snap_chunks_sent += 1
        self.metrics.snap_bytes_sent += len(data)

    def _resolve_snapshot(self, payload: bytes) -> None:
        chunk = decode(SnapshotChunk, payload)  # CodecError -> drop conn
        self.metrics.snap_chunks_received += 1
        self.metrics.snap_bytes_received += len(chunk.data)
        fut = self._snap_waiters.pop(chunk.nonce, None)
        if fut is not None and not fut.done():
            fut.set_result(chunk)

    async def request_snapshot_chunk(
        self, target: int, height: int, offset: int, max_bytes: int,
        timeout: float = 2.0,
    ) -> Optional[SnapshotChunk]:
        """One chunk round trip; None on timeout / peer down."""
        self._sync_nonce += 1
        nonce = self._sync_nonce
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._snap_waiters[nonce] = fut
        req = SnapshotFetchRequest(nonce=nonce, height=height,
                                   offset=offset, max_bytes=max_bytes)
        t0 = perf_counter()
        self._enqueue(target, encode_frame(FT_SNAP_REQ, encode(req)))
        try:
            chunk = await asyncio.wait_for(fut, timeout)
            self._note_rtt(target, perf_counter() - t0)
            return chunk
        except (asyncio.TimeoutError, asyncio.CancelledError):
            return None
        finally:
            self._snap_waiters.pop(nonce, None)

    async def fetch_snapshot(
        self, target: int, height: int, *, chunk_bytes: int = 1024 * 1024,
        timeout: float = 2.0,
    ) -> Optional[bytes]:
        """Fetch the peer's whole snapshot file at ``height``, chunk by
        chunk under the frame cap.  A lost chunk (reconnect, timeout)
        re-requests from the CURRENT offset — partial progress is kept in
        memory only, so resume is just re-asking; ``SNAP_FETCH_RETRIES``
        consecutive losses abandon the transfer.  None when the peer no
        longer serves ``height`` (superseded mid-transfer: the caller
        restarts against the peer's current offer) or on abandonment."""
        buf = bytearray()
        retries = 0
        while True:
            chunk = await self.request_snapshot_chunk(
                target, height, len(buf), chunk_bytes, timeout
            )
            if chunk is None:
                retries += 1
                if retries > SNAP_FETCH_RETRIES:
                    return None
                continue  # resume: re-request the same offset
            if chunk.total_bytes == 0:
                return None  # snapshot gone on the responder
            if chunk.offset != len(buf) or (not chunk.data and not chunk.last):
                retries += 1  # stale chunk / empty non-final slice
                if retries > SNAP_FETCH_RETRIES:
                    return None
                continue  # re-request the current offset
            retries = 0
            buf += chunk.data
            if chunk.last or len(buf) >= chunk.total_bytes:
                return bytes(buf)

    # ------------------------------------------------------------ read RPC

    def _serve_read(self, sender: int, payload: bytes) -> None:
        req = decode(ReadRequest, payload)  # CodecError -> drop conn
        self.metrics.read_requests += 1
        server = self.read_server
        if server is None:
            return  # unserved: the requester times out, same as a down peer
        resp = server(req)
        if resp is None:
            return
        if resp.shed:
            self.metrics.read_sheds_sent += 1
        self._enqueue(sender, encode_frame(FT_READ_RESP, encode(resp)))

    def _resolve_read(self, payload: bytes) -> None:
        resp = decode(ReadResponse, payload)  # CodecError -> drop conn
        self.metrics.read_responses += 1
        if resp.shed:
            self.metrics.read_sheds_received += 1
        fut = self._read_waiters.pop(resp.nonce, None)
        if fut is not None and not fut.done():
            fut.set_result(resp)

    async def request_read(self, target: int, key: str, *,
                           at_base: bool = False,
                           timeout: float = 2.0) -> Optional[ReadResponse]:
        """One keyed read round trip to ``target``; None on timeout / peer
        down.  A shed reply IS returned (``resp.shed``) — the caller owns
        retry-after handling, exactly like the FT_REJECT contract."""
        self._sync_nonce += 1
        nonce = self._sync_nonce
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._read_waiters[nonce] = fut
        req = ReadRequest(nonce=nonce, key=key, at_base=at_base)
        t0 = perf_counter()
        self._enqueue(target, encode_frame(FT_READ_REQ, encode(req)))
        try:
            resp = await asyncio.wait_for(fut, timeout)
            self._note_rtt(target, perf_counter() - t0)
            return resp
        except (asyncio.TimeoutError, asyncio.CancelledError):
            return None
        finally:
            self._read_waiters.pop(nonce, None)

    # ------------------------------------------------------------ RTT

    def _note_rtt(self, peer_id: int, sample: float) -> None:
        """Fold one measured round trip into the per-peer EWMA."""
        if sample <= 0:
            return
        prev = self._rtt.get(peer_id)
        self._rtt[peer_id] = sample if prev is None \
            else 0.7 * prev + 0.3 * sample

    def rtt_seconds(self) -> Optional[float]:
        """The transport's measured RTT envelope: the WORST (largest)
        per-peer estimate, because a forwarded request must reach
        whichever peer currently leads — deriving the forward timer from
        the slowest link is the conservative choice.  None before any
        round trip was measured (the consumer falls back to the
        configured constant)."""
        if not self._rtt:
            return None
        return max(self._rtt.values())

    # ------------------------------------------------------------ faults

    def mute(self) -> None:
        """Outbound-only silence (the chaos mute-leader fault)."""
        self.muted = True

    def unmute(self) -> None:
        self.muted = False

    def drop_link(self, peer_id: int) -> None:
        """Blackhole the link with ``peer_id`` in BOTH directions at this
        node: outbound frames stop enqueuing, inbound frames from it stop
        dispatching.  Applied on both endpoints by the chaos runner, it is
        a full partition cut; applied on one, an asymmetric drop."""
        self._dropped_links.add(peer_id)

    def restore_link(self, peer_id: int) -> None:
        self._dropped_links.discard(peer_id)

    def slow_link(self, peer_id: int, delay: float) -> None:
        """Add ``delay`` seconds before every flush to ``peer_id`` (the
        throttled-WAN-link fault); 0 clears."""
        if delay > 0:
            self._slow_links[peer_id] = delay
        else:
            self._slow_links.pop(peer_id, None)

    # ------------------------------------------------------------ queries

    def transport_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        snap["peers_connected"] = sum(
            1 for p in self._peers.values() if p.connected
        )
        snap["outbox_backlog"] = sum(len(p.outbox) for p in self._peers.values())
        snap["rtt_ms"] = {
            str(p): round(r * 1e3, 3) for p, r in sorted(self._rtt.items())
        }
        return snap
