"""Flight recorder: request-scoped protocol tracing + VC decomposition.

The observability plane ISSUE 12 builds: a bounded-memory
:class:`~smartbft_tpu.obs.recorder.TraceRecorder` of structured span
events (injectable clock, nop when disabled — the ``DisabledProvider``
pattern, so the hot path pays one attribute check when tracing is off),
a :class:`~smartbft_tpu.obs.vcphases.ViewChangePhaseTracker` that
decomposes the complain → depose → ViewData → new-view → first-commit
pipeline into measured sub-phases, and the pure ``assemble_*`` helpers
that fold either into bench-row JSON blocks.  ``python -m
smartbft_tpu.obs.report`` renders a recorder dump as a text timeline +
per-span-type percentile summary.
"""

from .critpath import (  # noqa: F401
    SEGMENTS,
    assemble_critical_path_block,
)
from .health import (  # noqa: F401
    HealthMonitor,
    aggregate_cluster_verdict,
)
from .recorder import (  # noqa: F401
    NOP_RECORDER,
    NopRecorder,
    SpanEvent,
    TraceRecorder,
    assemble_trace_block,
)
from .slo import (  # noqa: F401
    SLOEvaluator,
    SLORule,
    SLOSpec,
    default_slo_spec,
)
from .vcphases import (  # noqa: F401
    ViewChangePhaseTracker,
    assemble_viewchange_block,
)

__all__ = [
    "NOP_RECORDER",
    "NopRecorder",
    "SEGMENTS",
    "SpanEvent",
    "TraceRecorder",
    "assemble_critical_path_block",
    "assemble_trace_block",
    "ViewChangePhaseTracker",
    "assemble_viewchange_block",
    "HealthMonitor",
    "aggregate_cluster_verdict",
    "SLOEvaluator",
    "SLORule",
    "SLOSpec",
    "default_slo_spec",
]
