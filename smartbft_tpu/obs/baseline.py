"""Longitudinal bench-regression guard: pin a baseline, diff every run.

The BENCH_*.json trajectory never accumulated because rows from
different rounds were not canonically comparable: reps varied, host
weather varied, and nothing stored "what good looked like".  This module
closes the loop:

* :func:`canonicalize_rows` folds any bench row family (identified by
  its ``metric`` field and validated against
  :mod:`~smartbft_tpu.obs.benchschema`) into ONE canonical entry per
  metric: best-of-reps value (min for lower-is-better units, max for
  higher-is-better), the rep spread, the host-weather fields carried
  verbatim (launch probe, core count) so a future reader can judge
  comparability, and a noise-aware threshold — the allowed regression
  percentage, widened to 1.5x the observed rep spread when the reps
  disagreed more than the family default.

* :func:`pin` writes the canonical entries + ``schema_version`` into a
  baseline file; :func:`check_rows` diffs a fresh run against it and
  reports regressions (worse than baseline by more than the pinned
  threshold), improvements, and schema drift.

* ``python -m smartbft_tpu.obs.baseline pin|check`` is the CLI, and
  ``bench.py --check-baseline`` runs the same check over the rows it
  just emitted, exiting non-zero on regression — the longitudinal gate.

* :func:`tiny_logical_row` produces a deterministic LOGICAL-CLOCK row (a
  4-node in-process cluster commits a fixed workload on the tick-driven
  scheduler; latencies are logical seconds, independent of host speed)
  — the row family the tier-1 gate pins against the committed
  ``BASELINE_OBS.json`` so the guard itself is exercised every run.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Optional

from .benchschema import SCHEMA_VERSION, identify_row, validate_rows

__all__ = [
    "canonicalize_rows",
    "pin",
    "load_baseline",
    "check_rows",
    "tiny_logical_row",
    "main",
]

#: units where a SMALLER value is better (latency-shaped, plus critpath
#: segment shares — a segment REGAINING commit-path share is the round-18
#: regression the commit-path guard rows exist to catch)
#: ("x" is the ratio unit of the rejoin flatness guard — deep-history
#: rejoin wall over shallow, where growing IS the regression)
#: ("actions/fault" and "count" are the self-driving controller's guard
#: units — more remediations per fault, or any oscillation reversal,
#: means the control plane got twitchier)
LOWER_IS_BETTER_UNITS = {"ms", "us", "us/sig", "logical_ms", "s", "share",
                         "x", "actions/fault", "count"}

#: host-weather fields carried into the baseline verbatim — the context a
#: future reader needs to judge whether two rounds are comparable at all
WEATHER_FIELDS = ("launch_probe_ms", "baseline_launch_probe_ms", "cores",
                  "devices", "shards", "nodes", "pipeline",
                  "burst_decisions", "offered_per_sec")

#: default allowed-regression percentage per family; wall-clock rows get
#: a wide default (this rig's measured run-to-run weather is 2-3x under
#: contention), the logical row a tight one (the clock is deterministic)
DEFAULT_THRESHOLD_PCT = 35.0
FAMILY_THRESHOLD_PCT = {
    "tiny_logical_commit_ms": 100.0,
    # pinned at the ideal 1.0: fail only when deep-history snapshot
    # rejoin exceeds 2x the shallow one (the ISSUE 17 acceptance bound)
    "rejoin_flatness_vs_depth": 100.0,
    # single-digit wall ms over connect-per-call sockets: run-to-run
    # weather on the contended 1-core rig dwarfs the 35% default
    "read_p99_ms": 100.0,
    # the ISSUE 19 acceptance is scaling strictly above 1.0; pinned at
    # the measured ~2.17x for n=8/n=4, 45% still fails below ~1.2x
    "read_scaling_vs_n": 45.0,
    # ISSUE 20: pinned at the measured 1.0 action/fault; 100% allowance
    # means the guard trips only past 2 actions per injected fault (the
    # anti-thrash acceptance bound)
    "selfdrive_*": 100.0,
    # baseline 0 makes ANY reversal a flat 100% delta; the threshold
    # must sit strictly BELOW 100 (check is delta > threshold) so one
    # flip-flop fails.  Exact family, wins over the wildcard.
    "selfdrive_oscillation_reversals": 50.0,
}


def _direction(row: dict) -> str:
    unit = str(row.get("unit", ""))
    return "lower" if unit in LOWER_IS_BETTER_UNITS else "higher"


def canonicalize_rows(rows: list) -> dict:
    """Fold bench rows (one or more reps per metric) into canonical
    baseline entries keyed by metric name.  Rows without a ``metric`` +
    numeric ``value`` are skipped (sweep-point rows ride inside their
    assembled parent)."""
    groups: dict[str, list[dict]] = {}
    for row in rows:
        metric = row.get("metric")
        value = row.get("value")
        if not isinstance(metric, str) or not isinstance(value, (int, float)) \
                or isinstance(value, bool):
            continue
        groups.setdefault(metric, []).append(row)
    out: dict = {}
    for metric, reps in groups.items():
        direction = _direction(reps[0])
        values = [float(r["value"]) for r in reps]
        best = min(values) if direction == "lower" else max(values)
        worst = max(values) if direction == "lower" else min(values)
        spread_pct = (abs(worst - best) / abs(best) * 100.0) if best else 0.0
        family = identify_row(reps[0]) or metric
        default_pct = FAMILY_THRESHOLD_PCT.get(
            family, FAMILY_THRESHOLD_PCT.get(metric, DEFAULT_THRESHOLD_PCT)
        )
        threshold_pct = round(max(default_pct, spread_pct * 1.5), 1)
        weather = {}
        for r in reps:
            for k in WEATHER_FIELDS:
                if r.get(k) is not None and k not in weather:
                    weather[k] = r[k]
        out[metric] = {
            "value": best,
            "unit": reps[0].get("unit", ""),
            "direction": direction,
            "reps": len(reps),
            "spread_pct": round(spread_pct, 1),
            "threshold_pct": threshold_pct,
            "weather": weather,
        }
    return out


def pin(rows: list, path: str, *, note: str = "") -> dict:
    """Canonicalize ``rows`` and write the pinned baseline file."""
    entries = canonicalize_rows(rows)
    baseline = {
        "schema_version": SCHEMA_VERSION,
        "pinned_at": time.strftime("%Y-%m-%d", time.gmtime()),
        "note": note,
        "rows": entries,
    }
    with open(path, "w") as fh:
        json.dump(baseline, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return baseline


def load_baseline(path: str) -> dict:
    with open(path) as fh:
        baseline = json.load(fh)
    if "rows" not in baseline:
        raise ValueError(f"{path}: not a baseline file (no 'rows')")
    return baseline


def check_rows(rows: list, baseline: dict) -> dict:
    """Diff fresh bench rows against a pinned baseline.

    Returns ``{"checked", "regressions", "improvements", "missing",
    "schema_errors", "ok"}``.  A metric regresses when its fresh value
    is worse than the pinned one by more than the pinned threshold; a
    fresh run missing a pinned metric is reported (``missing``) but not
    fatal — benches are modal, one run rarely produces every family.
    Schema drift in the fresh rows IS fatal: a row that no longer parses
    the way it did when pinned cannot be compared at all."""
    schema_errors = validate_rows(rows)
    pinned_version = baseline.get("schema_version")
    if pinned_version != SCHEMA_VERSION:
        schema_errors.insert(0, (
            f"baseline schema_version {pinned_version} != checker "
            f"{SCHEMA_VERSION}: re-pin before comparing"
        ))
    fresh = canonicalize_rows(rows)
    pinned = baseline.get("rows", {})
    regressions, improvements, checked = [], [], []
    for metric, entry in sorted(pinned.items()):
        got = fresh.get(metric)
        if got is None:
            continue
        checked.append(metric)
        base_v = float(entry["value"])
        new_v = float(got["value"])
        threshold = float(entry.get("threshold_pct", DEFAULT_THRESHOLD_PCT))
        if base_v == 0.0:
            delta_pct = 0.0 if new_v == 0.0 else 100.0
        elif entry.get("direction") == "lower":
            delta_pct = (new_v - base_v) / abs(base_v) * 100.0
        else:
            delta_pct = (base_v - new_v) / abs(base_v) * 100.0
        row = {
            "metric": metric,
            "baseline": base_v,
            "value": new_v,
            "unit": entry.get("unit", ""),
            "direction": entry.get("direction", "higher"),
            "delta_pct": round(delta_pct, 1),   # positive = worse
            "threshold_pct": threshold,
            "weather": {"pinned": entry.get("weather", {}),
                        "fresh": got.get("weather", {})},
        }
        if delta_pct > threshold:
            regressions.append(row)
        elif delta_pct < -threshold:
            improvements.append(row)
    missing = sorted(set(pinned) - set(fresh))
    return {
        "checked": checked,
        "regressions": regressions,
        "improvements": improvements,
        "missing": missing,
        "schema_errors": schema_errors,
        "ok": not regressions and not schema_errors,
    }


def render_check(result: dict) -> str:
    out = [f"baseline check: {len(result['checked'])} metric(s) compared"]
    for r in result["regressions"]:
        out.append(
            f"  REGRESSION {r['metric']}: {r['value']:g} {r['unit']} vs "
            f"baseline {r['baseline']:g} ({r['delta_pct']:+.1f}% worse, "
            f"threshold {r['threshold_pct']:g}%)"
        )
    for r in result["improvements"]:
        out.append(
            f"  improvement {r['metric']}: {r['value']:g} {r['unit']} vs "
            f"baseline {r['baseline']:g} ({-r['delta_pct']:.1f}% better)"
        )
    for e in result["schema_errors"]:
        out.append(f"  SCHEMA DRIFT: {e}")
    if result["missing"]:
        out.append(f"  not produced this run: {', '.join(result['missing'])}")
    out.append("  OK" if result["ok"] else "  FAILED")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# the tier-1 gate row: a deterministic logical-clock micro workload
# ---------------------------------------------------------------------------


async def _tiny_logical_run(*, requests: int, n: int, seed: int) -> dict:
    import dataclasses
    import tempfile

    from ..metrics import CommitLatencyTracker
    from ..testing.app import App, SharedLedgers, fast_config, wait_for
    from ..testing.network import Network
    from ..utils.clock import Scheduler

    scheduler = Scheduler()
    network = Network(seed=seed)
    shared = SharedLedgers()
    tracker = CommitLatencyTracker(clock=scheduler.now)
    with tempfile.TemporaryDirectory(prefix="sbft-baseline-tiny-") as root:
        cfg = lambda i: dataclasses.replace(
            fast_config(i),
            request_batch_max_count=2,
            request_batch_max_interval=0.05,
            leader_rotation=False,
            decisions_per_leader=0,
        )
        apps = [
            App(i, network, shared, scheduler, wal_dir=f"{root}/wal-{i}",
                config=cfg(i))
            for i in range(1, n + 1)
        ]
        for a in apps:
            await a.start()
        probe = apps[0]
        scanned = 0

        def scan() -> int:
            nonlocal scanned
            ledger = probe.ledger()
            for d in ledger[scanned:]:
                for info in probe.requests_from_proposal(d.proposal):
                    tracker.on_committed(str(info), 0)
            scanned = len(ledger)
            return scanned

        try:
            committed = 0
            for k in range(requests):
                key = f"tiny:t-{k}"
                tracker.on_submitted(key)
                await apps[0].submit("tiny", f"t-{k}")
                committed += 1
                # commit-paced submission: each request's logical latency
                # is the protocol's own commit time, not queueing skew
                await wait_for(
                    lambda: (scan(), tracker.pending() == 0)[-1],
                    scheduler, 30.0,
                )
            decisions = len(probe.ledger())
        finally:
            for a in apps:
                await a.stop()
    snap = tracker.aggregate.snapshot()
    return {
        # the VALUE is the mean: on the stepped logical clock a p99 is
        # one 0.05 s tick of asyncio interleaving away from flapping a
        # whole bucket, while the mean moves only when the commit path
        # itself changes; the full percentile block rides along
        "metric": "tiny_logical_commit_ms",
        "value": snap["mean_ms"],
        "unit": "logical_ms",
        "requests": requests,
        "decisions": decisions,
        "nodes": n,
        "seed": seed,
        "p50_ms": snap["p50_ms"],
        "latency": snap,
    }


def tiny_logical_row(*, requests: int = 10, n: int = 4, seed: int = 7) -> dict:
    """One deterministic logical-clock bench row: a 4-node in-process
    cluster commits ``requests`` commit-paced requests on the tick-driven
    scheduler; the row's value is the MEAN submit->commit latency in
    LOGICAL milliseconds (percentiles ride in the ``latency`` block —
    the mean is the pinned value because a logical-clock p99 flaps a
    whole scheduler tick on asyncio interleaving).  Host-speed-
    independent, so the committed baseline holds on any rig, and a
    protocol regression that stretches the commit path (a timer bug, a
    lost wave needing a retransmit round) moves it."""
    import asyncio

    return asyncio.run(_tiny_logical_run(requests=requests, n=n, seed=seed))


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _read_rows(path: str) -> list:
    """Rows from a JSON-lines file, a JSON array, or a dict with rows."""
    with open(path) as fh:
        text = fh.read()
    try:
        data = json.loads(text)
        if isinstance(data, list):
            return data
        if isinstance(data, dict):
            return [data]
    except json.JSONDecodeError:
        pass
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if line:
            rows.append(json.loads(line))
    return rows


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Pin and check longitudinal bench baselines"
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_pin = sub.add_parser("pin", help="canonicalize rows into a baseline")
    p_pin.add_argument("--rows", action="append", required=False, default=[],
                       help="JSON/JSON-lines file(s) of bench rows")
    p_pin.add_argument("--out", required=True, help="baseline file to write")
    p_pin.add_argument("--note", default="")
    p_pin.add_argument("--tiny-logical", action="store_true",
                       help="also run the deterministic logical-clock row "
                            "and pin it")
    p_chk = sub.add_parser("check", help="diff fresh rows against a baseline")
    p_chk.add_argument("--rows", action="append", required=False, default=[],
                       help="JSON/JSON-lines file(s) of fresh bench rows")
    p_chk.add_argument("--baseline", required=True)
    p_chk.add_argument("--tiny-logical", action="store_true",
                       help="also run the deterministic logical-clock row "
                            "and include it in the check")
    args = ap.parse_args(argv)

    rows: list = []
    for path in args.rows:
        rows.extend(_read_rows(path))
    if args.tiny_logical:
        rows.append(tiny_logical_row())

    if args.cmd == "pin":
        baseline = pin(rows, args.out, note=args.note)
        print(f"pinned {len(baseline['rows'])} metric(s) -> {args.out}")
        return 0

    result = check_rows(rows, load_baseline(args.baseline))
    print(render_check(result))
    if not result["checked"]:
        # zero metrics compared = the guard verified nothing; exiting 0
        # here would read as green precisely when every producer broke
        print("  VACUOUS: no pinned metric was produced this run")
        return 1
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
