"""Versioned bench-row schema: the cross-round comparability contract.

Every bench row family this repo emits (the ``assemble_*_row`` pure
functions in ``bench.py`` plus the kernel/throughput headline rows) is
pinned here as a small JSON-schema-style description: required keys with
types, optional keys typed when present, nested blocks described
recursively.  Two consumers rely on it:

* the tier-1 drift gate (tests) validates synthetic rows built through
  the SAME pure assemble functions the real benches call, so a row-shape
  change that would break downstream tooling fails in CI, not three
  rounds later when someone diffs BENCH_*.json files;
* the longitudinal baseline guard (:mod:`smartbft_tpu.obs.baseline`)
  validates fresh rows before comparing them against a pinned baseline —
  rows from different rounds are only comparable because this schema
  says they still mean the same thing.

Unknown top-level keys are ALLOWED (additive evolution is the norm);
missing required keys and type changes are the drift this gate exists to
catch.  ``SCHEMA_VERSION`` is stamped into every baseline file; a pinned
baseline whose schema version disagrees with the checker's is reported
instead of silently compared.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["SCHEMA_VERSION", "ROW_SCHEMAS", "assemble_rejoin_row",
           "assemble_read_row", "assemble_read_scaling_row",
           "assemble_selfdrive_rows",
           "identify_row", "validate_row", "validate_rows"]

#: bump when a row family's required shape changes incompatibly
SCHEMA_VERSION = 1

_NUM = (int, float)
_STR = (str,)
_DICT = (dict,)
_LIST = (list,)


def _check(obj, schema: dict, path: str, errors: list[str]) -> None:
    if not isinstance(obj, dict):
        errors.append(f"{path or '<row>'}: expected object, got "
                      f"{type(obj).__name__}")
        return
    for key, want in schema.get("required", {}).items():
        if key not in obj or obj[key] is None:
            errors.append(f"{path}{key}: required key missing")
            continue
        _check_value(obj[key], want, f"{path}{key}", errors)
    for key, want in schema.get("optional", {}).items():
        if key in obj and obj[key] is not None:
            _check_value(obj[key], want, f"{path}{key}", errors)


def _check_value(value, want, path: str, errors: list[str]) -> None:
    if isinstance(want, dict):
        _check(value, want, path + ".", errors)
    elif isinstance(want, tuple):
        # bool is an int subclass; a numeric field turning bool is drift
        if isinstance(value, bool) and bool not in want:
            errors.append(f"{path}: expected "
                          f"{'/'.join(t.__name__ for t in want)}, got bool")
        elif not isinstance(value, want):
            errors.append(
                f"{path}: expected {'/'.join(t.__name__ for t in want)}, "
                f"got {type(value).__name__}"
            )
    elif callable(want):
        err = want(value)
        if err:
            errors.append(f"{path}: {err}")


def _list_of(item_schema) -> "callable":
    def check(value):
        if not isinstance(value, list):
            return f"expected list, got {type(value).__name__}"
        errs: list[str] = []
        for i, item in enumerate(value):
            _check_value(item, item_schema, f"[{i}]", errs)
        return "; ".join(errs) if errs else None

    return check


#: the percentile sub-block LogScaleHistogram.snapshot() emits
_PCTS = {"required": {"count": _NUM, "p50_ms": _NUM, "p95_ms": _NUM,
                      "p99_ms": _NUM, "max_ms": _NUM},
         "optional": {"mean_ms": _NUM}}

_LATENCY_BLOCK = {
    "required": {"count": _NUM, "p50_ms": _NUM, "p95_ms": _NUM,
                 "p99_ms": _NUM, "shed": _DICT, "histogram": _DICT},
    "optional": {"mean_ms": _NUM, "max_ms": _NUM, "pending_stamps": _NUM,
                 "dropped_stamps": _NUM, "per_shard": _DICT,
                 "phases": _DICT, "knee": _DICT},
}

_PROTOCOL_PLANE = {
    "required": {"ingest_us": _NUM, "route_us": _NUM, "vote_reg_us": _NUM,
                 "codec_us": _NUM},
    "optional": {"broadcasts": _NUM, "sends": _NUM, "encodes": _NUM,
                 "decodes": _NUM, "batch_ingests": _NUM,
                 "msgs_ingested": _NUM},
}

#: shared shape of the ISSUE 20 self-driving controller guard rows
_SELFDRIVE_ROW = {
    "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                 "faults": _NUM, "actions": _NUM},
    "optional": {"actions_ok": _NUM, "scale_out": _NUM,
                 "scale_in": _NUM, "retune": _NUM, "vetoes": _DICT,
                 "final_status": _STR, "fill_at_scale_out": _NUM,
                 "peak_fill": _NUM, "ctl_spans": _NUM,
                 "clear_spans": _NUM, "seed": _NUM,
                 "verdict_samples": _NUM},
}

ROW_SCHEMAS: dict = {
    # bench.py e2e_bench / assemble_e2e_row — the north-star row
    "committed_tx_per_sec_n*": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "vs_baseline": _NUM, "baseline_tx_per_sec": _NUM,
                     "pipeline": _NUM, "burst_decisions": _NUM},
        "optional": {"launches": _NUM, "decisions": _NUM,
                     "launches_per_decision": _NUM, "window_launches": _LIST,
                     "batch_fill_pct": _NUM, "launch_probe_ms": _NUM,
                     "baseline_launch_probe_ms": _NUM, "breaker": _DICT,
                     "mesh": _DICT, "protocol_plane": _PROTOCOL_PLANE,
                     "baseline_protocol_plane": _DICT,
                     "tx_per_sec_probe_normalized": _NUM,
                     "vs_baseline_probe_normalized": _NUM},
    },
    # bench.py kernel_bench — the kernel micro headline
    "p256_sig_verify_p50_us": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "vs_baseline": _NUM},
        "optional": {"vs_all_cores": _NUM, "cores": _NUM,
                     "protocol_plane": _PROTOCOL_PLANE},
    },
    # bench.py assemble_open_loop_row
    "open_loop_p99_ms": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "offered_per_sec": _NUM, "goodput_per_sec": _NUM,
                     "latency": _LATENCY_BLOCK, "sweep": _list_of(_DICT)},
        "optional": {"shards": _NUM, "zipf_skew": _NUM,
                     "admission_high_water": _NUM, "viewchange": _DICT,
                     "trace": _DICT, "critical_path": _DICT,
                     "health": _DICT, "degraded_notes": _DICT},
    },
    # bench.py assemble_transport_row
    "transport_committed_tx_per_sec": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "vs_baseline": _NUM, "flavor": _STR, "nodes": _NUM,
                     "requests": _NUM, "transport": _DICT},
        "optional": {"inproc_tx_per_sec": _NUM,
                     "protocol_plane": _PROTOCOL_PLANE,
                     "inproc_protocol_plane": _DICT,
                     "critical_path": _DICT, "cluster_trace": _DICT},
    },
    # bench.py assemble_sharded_row
    "sharded_committed_tx_per_sec": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "vs_baseline": _NUM,
                     "shard": {"required": {"sweep": _list_of(_DICT)},
                               "optional": {"scaling": _DICT,
                                            "top": _DICT}}},
        "optional": {"reshard": _DICT},
    },
    # bench.py assemble_mesh_row
    "mesh_committed_tx_per_sec": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "vs_baseline": _NUM, "devices": _NUM,
                     "mesh": {"required": {"sweep": _list_of(_DICT)},
                              "optional": {"gating": _DICT,
                                           "verdict_parity": _DICT,
                                           "verdict_parity_2d": _DICT,
                                           "capacity_scaling": _NUM,
                                           "topology": _STR,
                                           "downgrades": _NUM,
                                           "top": _DICT}}},
        "optional": {},
    },
    # bench.py viewchange_guard_rows (ISSUE 15) — the forced-VC phase's
    # request p99 in the round-12 degraded harness, the longitudinal
    # failover-regression pin
    "viewchange_phase_p99_ms": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR},
        "optional": {"offered_per_sec": _NUM, "shards": _NUM,
                     "healthy_p99_ms": _NUM, "vs_healthy": _NUM},
    },
    # bench.py viewchange_guard_rows (ISSUE 15) — complain-timer
    # arm-to-fire p99 under the degraded run's muted leader
    "viewchange_detection_p99_ms": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR},
        "optional": {"count": _NUM, "offered_per_sec": _NUM,
                     "shards": _NUM, "timer": _DICT},
    },
    # bench.py commitpath_guard_rows (ISSUE 16) — the open-loop
    # saturation knee (highest swept offered load meeting the goodput +
    # shed SLO), the longitudinal raw-speed pin
    "open_loop_knee_tx_per_sec": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR},
        "optional": {"goodput_per_sec": _NUM, "p99_ms": _NUM,
                     "beyond_sweep": (bool,)},
    },
    # bench.py commitpath_guard_rows (ISSUE 16) — HEALTHY-phase critical
    # path segment shares (unit "share", lower is better): the two
    # segments the round-18 commit-path work cut
    "critpath_*": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR},
        "optional": {"phase": _STR, "requests": _NUM,
                     "dominant_segment": _STR, "sums_consistent": (bool,),
                     "offered_per_sec": _NUM},
    },
    # bench.py commitpath_guard_rows (ISSUE 16) — per-S knee of the
    # process-per-shard affinity sweep
    "open_loop_affinity_s*": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "shards": _NUM},
        "optional": {"loop_affinity": _STR, "goodput_per_sec": _NUM,
                     "p99_ms": _NUM, "beyond_sweep": (bool,)},
    },
    # assemble_rejoin_row (ISSUE 17) — rejoin wall-clock + bytes at a
    # given history depth, snapshot-install vs chain-replay control.
    # The flat-vs-depth guard pins the deep-history snapshot row within
    # 2x the shallow one (vs O(depth) for the replay control).
    "rejoin_*": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "history_decisions": _NUM, "mode": _STR,
                     "bytes_transferred": _NUM},
        "optional": {"decisions_replayed": _NUM, "snapshot_bytes": _NUM,
                     "snap_chunks": _NUM, "requests": _NUM,
                     "vs_small_history": _NUM, "interval": _NUM},
    },
    # bench.py rejoin_guard_rows (ISSUE 17) — deep-over-shallow snapshot
    # rejoin wall ratio (unit "x", lower is better); the committed
    # baseline pins the ideal 1.0 with a 100% allowance, encoding the
    # acceptance bound "deep rejoin within 2x shallow" directly.  Listed
    # as an EXACT family so it wins over the rejoin_* wildcard.
    "rejoin_flatness_vs_depth": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "history_small": _NUM, "history_deep": _NUM},
        "optional": {"snapshot_small_s": _NUM, "snapshot_deep_s": _NUM,
                     "replay_ratio": _NUM, "interval": _NUM},
    },
    # bench.py assemble_byzantine_row (ISSUE 18) — honest-path request
    # p99 WITH an f=1 actor flooding forged votes at the shared verify
    # plane (per-sender accounting shuns + sheds it), next to the same
    # cluster's no-actor control; the baseline bounds the forger's
    # latency tax on honest clients
    "byzantine_forge_p99_ms": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "healthy_p99_ms": _NUM},
        "optional": {"vs_healthy": _NUM, "forged": _NUM,
                     "shun_events": _NUM, "shed_votes": _NUM,
                     "spike_acked": _NUM, "healthy_spike_acked": _NUM,
                     "latency": _LATENCY_BLOCK, "healthy_latency": _DICT},
    },
    # assemble_read_row (ISSUE 19) — mixed 95/5 read/write sweep against
    # the socket cluster: wall-clock quorum-read p99 next to the SAME
    # run's write (submit->committed) p99.  The read plane never touches
    # consensus, so the pinned contrast is reads staying far under the
    # write path; the storm block records that an over-gate read flood
    # shed READS while the concurrent writes kept committing.
    "read_p99_ms": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "write_p99_ms": _NUM, "nodes": _NUM, "reads": _NUM},
        "optional": {"writes": _NUM, "vs_write": _NUM, "mode": _STR,
                     "local_p99_ms": _NUM, "follower_p99_ms": _NUM,
                     "read_sheds": _NUM, "storm": _DICT, "read": _DICT},
    },
    # assemble_read_scaling_row (ISSUE 19) — aggregate read capacity at
    # n=8 over n=4 at fixed S.  Local reads touch ONLY their serving
    # replica (no fan-out, no consensus work), so cluster read capacity
    # is n x the measured per-replica service rate; the row carries both
    # per-replica rates so a flat-with-n service rate (the isolation
    # invariant) is what the guard actually pins.  On a multi-core host
    # the aggregate is realized parallelism; on a 1-core rig it is
    # capacity aggregation under that measured invariant.
    "read_scaling_vs_n": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "nodes_small": _NUM, "nodes_large": _NUM},
        "optional": {"reads_per_sec_small": _NUM,
                     "reads_per_sec_large": _NUM,
                     "per_replica_rate_small": _NUM,
                     "per_replica_rate_large": _NUM,
                     "rate_flatness": _NUM, "ideal": _NUM},
    },
    # assemble_selfdrive_rows (ISSUE 20) — the controller's behavior
    # under the remediation_storm chaos round: actions taken per injected
    # fault (unit "actions/fault", lower is better — a thrashing
    # controller fails this long before it breaks safety) and A→B→A
    # oscillation reversals inside one hysteresis window (unit "count",
    # pinned at 0 so ANY flip-flop regresses the baseline).  The
    # oscillation row is listed as an EXACT family so it wins over the
    # wildcard and can carry its own (tighter) baseline threshold.
    "selfdrive_*": _SELFDRIVE_ROW,
    "selfdrive_oscillation_reversals": _SELFDRIVE_ROW,
    # obs.baseline.tiny_logical_row — the tier-1 regression-gate row
    # (value = mean logical commit latency; percentiles ride in "latency")
    "tiny_logical_commit_ms": {
        "required": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "requests": _NUM, "decisions": _NUM,
                     "latency": _PCTS},
        "optional": {"nodes": _NUM, "seed": _NUM, "p50_ms": _NUM},
    },
}


def assemble_rejoin_row(*, history: int, mode: str, rejoin_s: float,
                        bytes_transferred: int,
                        decisions_replayed: Optional[int] = None,
                        snapshot_bytes: Optional[int] = None,
                        snap_chunks: Optional[int] = None,
                        interval: Optional[int] = None,
                        vs_small_history: Optional[float] = None) -> dict:
    """The ``rejoin_*`` bench row (ISSUE 17), as a PURE function so the
    tier-1 schema gate can validate synthetic rows without running the
    bench.  ``mode`` is ``"snapshot"`` (offer + install + tail) or
    ``"replay"`` (the full chain-replay control); ``vs_small_history``
    is this row's wall-clock over the smallest swept history's — the
    flat-vs-depth guard the baseline pins (snapshot mode must stay ~1.0
    while the replay control grows with depth)."""
    if mode not in ("snapshot", "replay"):
        raise ValueError(f"mode must be 'snapshot' or 'replay', got {mode!r}")
    row = {
        "metric": f"rejoin_wall_s_h{int(history)}_{mode}",
        "value": round(float(rejoin_s), 4),
        "unit": "s",
        "history_decisions": int(history),
        "mode": mode,
        "bytes_transferred": int(bytes_transferred),
    }
    if decisions_replayed is not None:
        row["decisions_replayed"] = int(decisions_replayed)
    if snapshot_bytes is not None:
        row["snapshot_bytes"] = int(snapshot_bytes)
    if snap_chunks is not None:
        row["snap_chunks"] = int(snap_chunks)
    if interval is not None:
        row["interval"] = int(interval)
    if vs_small_history is not None:
        row["vs_small_history"] = round(float(vs_small_history), 4)
    return row


def assemble_read_row(*, read_p99_ms: float, write_p99_ms: float,
                      nodes: int, reads: int, writes: Optional[int] = None,
                      mode: str = "quorum",
                      local_p99_ms: Optional[float] = None,
                      follower_p99_ms: Optional[float] = None,
                      read_sheds: Optional[int] = None,
                      storm: Optional[dict] = None,
                      read_stats: Optional[dict] = None) -> dict:
    """The ``read_p99_ms`` bench row (ISSUE 19), as a PURE function so
    the tier-1 schema gate can validate synthetic rows without running
    the bench.  ``read_p99_ms`` is the wall-clock p99 of ``mode`` reads
    during the mixed 95/5 phase; ``write_p99_ms`` the SAME phase's
    submit->committed p99 — the pinned contrast is the read plane never
    paying consensus latency."""
    if mode not in ("local", "follower", "quorum"):
        raise ValueError(f"mode must be local/follower/quorum, got {mode!r}")
    row = {
        "metric": "read_p99_ms",
        "value": round(float(read_p99_ms), 3),
        "unit": "ms",
        "write_p99_ms": round(float(write_p99_ms), 3),
        "nodes": int(nodes),
        "reads": int(reads),
        "mode": mode,
    }
    if write_p99_ms:
        row["vs_write"] = round(float(read_p99_ms) / float(write_p99_ms), 4)
    if writes is not None:
        row["writes"] = int(writes)
    if local_p99_ms is not None:
        row["local_p99_ms"] = round(float(local_p99_ms), 3)
    if follower_p99_ms is not None:
        row["follower_p99_ms"] = round(float(follower_p99_ms), 3)
    if read_sheds is not None:
        row["read_sheds"] = int(read_sheds)
    if storm is not None:
        row["storm"] = dict(storm)
    if read_stats is not None:
        row["read"] = dict(read_stats)
    return row


def assemble_read_scaling_row(*, per_replica_rate_small: float,
                              per_replica_rate_large: float,
                              nodes_small: int, nodes_large: int) -> dict:
    """The ``read_scaling_vs_n`` bench row (ISSUE 19): aggregate read
    capacity (n x measured per-replica local-read service rate) at
    ``nodes_large`` over ``nodes_small``.  ``rate_flatness`` is the
    per-replica rate ratio large/small — the isolation invariant (a
    local read costs the same no matter the cluster size) that makes
    the aggregate claim honest on any core count."""
    if nodes_small <= 0 or nodes_large <= nodes_small:
        raise ValueError(
            f"need 0 < nodes_small < nodes_large, got "
            f"{nodes_small}/{nodes_large}"
        )
    if per_replica_rate_small <= 0 or per_replica_rate_large <= 0:
        raise ValueError("per-replica rates must be positive")
    agg_small = per_replica_rate_small * nodes_small
    agg_large = per_replica_rate_large * nodes_large
    return {
        "metric": "read_scaling_vs_n",
        "value": round(agg_large / agg_small, 4),
        "unit": "ratio",
        "nodes_small": int(nodes_small),
        "nodes_large": int(nodes_large),
        "reads_per_sec_small": round(agg_small, 1),
        "reads_per_sec_large": round(agg_large, 1),
        "per_replica_rate_small": round(float(per_replica_rate_small), 1),
        "per_replica_rate_large": round(float(per_replica_rate_large), 1),
        "rate_flatness": round(
            per_replica_rate_large / per_replica_rate_small, 4),
        "ideal": round(nodes_large / nodes_small, 4),
    }


def assemble_selfdrive_rows(stats: dict) -> list:
    """The ``selfdrive_*`` bench rows (ISSUE 20), as a PURE function over
    the stats dict :func:`remediation_storm_round` returns, so the tier-1
    schema gate can validate synthetic rows without running the ~20s
    chaos round.  Two rows: ``selfdrive_actions_per_fault`` (how many
    remediations the controller spent per injected fault — the
    anti-thrash pin) and ``selfdrive_oscillation_reversals`` (A→B→A
    flips inside one hysteresis window — pinned at zero)."""
    faults = int(stats.get("faults", 0))
    actions = int(stats.get("actions", 0))
    if faults <= 0:
        raise ValueError(f"faults must be positive, got {faults}")
    if actions < 0:
        raise ValueError(f"actions must be >= 0, got {actions}")
    reversals = int(stats.get("reversals", 0))
    common = {
        "faults": faults,
        "actions": actions,
        "actions_ok": int(stats.get("actions_ok", actions)),
        "scale_out": int(stats.get("scale_out", 0)),
        "scale_in": int(stats.get("scale_in", 0)),
        "retune": int(stats.get("retune", 0)),
    }
    apf_row = {
        "metric": "selfdrive_actions_per_fault",
        "value": round(actions / faults, 4),
        "unit": "actions/fault",
        **common,
    }
    rev_row = {
        "metric": "selfdrive_oscillation_reversals",
        "value": float(reversals),
        "unit": "count",
        **common,
    }
    for key in ("vetoes", "final_status", "fill_at_scale_out", "peak_fill",
                "ctl_spans", "clear_spans", "seed", "verdict_samples"):
        val = stats.get(key)
        if val is None:
            continue
        if key == "vetoes":
            apf_row[key] = dict(val)
        elif key == "final_status":
            apf_row[key] = str(val)
        elif key in ("fill_at_scale_out", "peak_fill"):
            apf_row[key] = round(float(val), 4)
        else:
            apf_row[key] = int(val)
    return [apf_row, rev_row]


def identify_row(row: dict) -> Optional[str]:
    """The schema family a row belongs to, or None for unpinned rows."""
    metric = row.get("metric")
    if not isinstance(metric, str):
        return None
    if metric in ROW_SCHEMAS:
        return metric
    for family in ROW_SCHEMAS:
        if family.endswith("*") and metric.startswith(family[:-1]):
            return family
    return None


def validate_row(row: dict) -> list[str]:
    """Schema errors for one row ([] when clean or the family is
    unpinned — an unknown family is not drift, it is a new row)."""
    family = identify_row(row)
    if family is None:
        return []
    errors: list[str] = []
    _check(row, ROW_SCHEMAS[family], "", errors)
    return [f"{family}: {e}" for e in errors]


def validate_rows(rows: list) -> list[str]:
    errors: list[str] = []
    for i, row in enumerate(rows):
        for e in validate_row(row):
            errors.append(f"row[{i}] {e}")
    return errors
