"""Per-request critical-path decomposition: where did THIS request's
latency go, across the whole cluster?

PR 12's recorders answer "what happened on node i"; this module joins
their events into one answer per REQUEST: submit → leader pool →
propose broadcast → prepare quorum (the voter who completed it named) →
commit-record WAL persist → commit quorum → deliver.  The decomposition
follows the vcphases sums-consistent idiom — each segment is the delta
between consecutive PRESENT marks on one timeline, a missing mark's
interval is absorbed by the next present mark — so segment sums equal
the measured end-to-end commit latency by construction, with the worst
residual (clamped negative deltas from cross-process clock skew)
reported instead of hidden.

One deliberate divergence from the ISSUE sketch's segment order: this
implementation persists the commit record BEFORE broadcasting its
commit vote (the WAL-first rule every view obeys), so the
``wal_persist`` segment sits between the prepare quorum and the commit
quorum — the true pipeline, not the idealized one.

Mark vocabulary (flight-recorder event kinds):

==================  =====================================================
``req.submit``      front-door entry (pool.submit, pre-admission)
``req.pool``        pooled (admission/park wait ended)
``batch.propose``   the leader assembled the batch containing it
``quorum.prepare``  prepare quorum completed (extra.slowest_voter = the
                    node whose vote completed it)
``wal.persist``     the commit record's durability wave resolved
``quorum.commit``   commit quorum completed (slowest voter named)
``req.deliver``     delivered (per request, carries (view, seq))
==================  =====================================================

Everything here is a PURE function over event dicts (the PR 8
``assemble_*`` idiom): benches feed it merged recorder snapshots, tests
feed it synthetic events, and the block schema is pinned through the
same function both use.
"""

from __future__ import annotations

from typing import Optional, Sequence

from .recorder import pct as _pct

__all__ = ["SEGMENTS", "assemble_critical_path_block"]

#: canonical mark order along the request pipeline
_MARKS = ("submit", "pool", "propose", "prepare_quorum", "wal_persist",
          "commit_quorum", "deliver")

#: mark -> the segment ENDING at it (the interval since the previous
#: present mark), in pipeline order
_SEGMENT_OF = (
    ("pool", "pool_wait"),
    ("propose", "propose_wait"),
    ("prepare_quorum", "prepare_wave"),
    ("wal_persist", "wal_persist"),
    ("commit_quorum", "commit_wave"),
    ("deliver", "deliver"),
)

SEGMENTS = tuple(seg for _, seg in _SEGMENT_OF)

#: event kind -> (view,seq)-scoped mark name
_VS_MARK_OF_KIND = {
    "batch.propose": "propose",
    "quorum.prepare": "prepare_quorum",
    "wal.persist": "wal_persist",
    "quorum.commit": "commit_quorum",
}


def _shard_of(node: str) -> str:
    """The shard scope of a recorder label: ``"s0n1"`` -> ``"s0"``,
    ``"s2g1n3"`` -> ``"s2g1"`` (a reborn shard id's NEW generation is a
    distinct scope — two generations never share a (view, seq) space),
    ``"n4"`` -> ``""`` (single-group socket replicas)."""
    cut = node.rfind("n")
    return node[:cut] if cut > 0 else ""


def _vs_key(node: str, view: int, seq: int) -> tuple:
    return (_shard_of(node), view, seq)


def _decompose(marks: dict) -> Optional[dict]:
    """One request's segments from its mark timestamps (absolute
    seconds).  Consecutive deltas over PRESENT marks, clamped at zero;
    the clamp total is the residual vs the end-to-end span."""
    t_submit = marks.get("submit")
    t_deliver = marks.get("deliver")
    if t_submit is None or t_deliver is None:
        return None
    total_ms = max(t_deliver - t_submit, 0.0) * 1e3
    segments: dict[str, float] = {}
    prev = t_submit
    for mark, seg in _SEGMENT_OF:
        t = marks.get(mark)
        if t is None:
            continue
        segments[seg] = max(t - prev, 0.0) * 1e3
        prev = t
    residual = abs(sum(segments.values()) - total_ms)
    return {"total_ms": total_ms, "segments": segments,
            "residual_ms": residual}


def _stats(vals: list, total_pool: float) -> dict:
    vals = sorted(vals)
    s = sum(vals)
    return {
        "count": len(vals),
        "p50_ms": round(_pct(vals, 0.50), 3),
        "p95_ms": round(_pct(vals, 0.95), 3),
        "p99_ms": round(_pct(vals, 0.99), 3),
        "max_ms": round(vals[-1], 3) if vals else 0.0,
        "mean_ms": round(s / len(vals), 3) if vals else 0.0,
        # fraction of ALL measured request time spent in this segment —
        # the decomposition column; shares sum to ~1 across segments
        "share": round(s / total_pool, 3) if total_pool else 0.0,
    }


def _fold(rows: list[dict], *, residual_tolerance_ms: float,
          sample: int) -> dict:
    per_seg: dict[str, list] = {seg: [] for seg in SEGMENTS}
    totals: list[float] = []
    worst_residual = 0.0
    for r in rows:
        totals.append(r["total_ms"])
        worst_residual = max(worst_residual, r["residual_ms"])
        for seg, ms in r["segments"].items():
            per_seg.setdefault(seg, []).append(ms)
    totals.sort()
    total_pool = sum(totals)
    segments = {seg: _stats(vals, total_pool)
                for seg, vals in per_seg.items() if vals}
    dominant = max(segments, key=lambda s: segments[s]["share"],
                   default=None) if segments else None
    return {
        "requests": len(rows),
        "end_to_end": {
            "count": len(totals),
            "p50_ms": round(_pct(totals, 0.50), 3),
            "p95_ms": round(_pct(totals, 0.95), 3),
            "p99_ms": round(_pct(totals, 0.99), 3),
            "max_ms": round(totals[-1], 3) if totals else 0.0,
            "mean_ms": round(total_pool / len(totals), 3) if totals else 0.0,
        },
        "segments": segments,
        "dominant_segment": dominant,
        # the instrument's core promise, stated per block: every request's
        # segment sums equal its end-to-end latency within the tolerance
        "sums_consistent": worst_residual <= residual_tolerance_ms,
        "worst_residual_ms": round(worst_residual, 4),
        "residual_tolerance_ms": residual_tolerance_ms,
        "sample": [
            {"key": r["key"],
             "total_ms": round(r["total_ms"], 3),
             "residual_ms": round(r["residual_ms"], 4),
             "segments": {s: round(ms, 3)
                          for s, ms in r["segments"].items()}}
            for r in rows[:max(0, sample)]
        ],
    }


def assemble_critical_path_block(
    events: Sequence[dict],
    *,
    phases: Optional[Sequence[str]] = None,
    sample: int = 8,
    residual_tolerance_ms: float = 1.0,
) -> dict:
    """Fold merged flight-recorder events into the ONE ``critical_path``
    block a bench row carries (pure function, PR 8 idiom; schema pinned
    by tests/test_critpath.py).

    ``events`` are event dicts (``SpanEvent.as_dict`` shape, ``node``
    filled), already on ONE timeline — the in-process harness's shared
    scheduler clock, or a socket cluster's skew-adjusted merge (then
    ``residual_tolerance_ms`` should be at least the offset error
    bound).  Per request: the submit/pool marks come from its first
    ``req.submit``/``req.pool`` events; the (view, seq) pipeline marks
    come from the node that recorded ``batch.propose`` for that slot
    (the leader — its pipeline IS the critical path), falling back to
    the earliest recording node; ``deliver`` prefers the leader's
    ``req.deliver``.  ``phases`` groups requests by request-id prefix
    (the open-loop harness's per-phase ``request_prefix``), yielding a
    per-phase sub-block each with its own dominant segment.

    ``slowest_prepare_voters`` counts, per completing voter, how often
    that node's vote was the one that completed a prepare quorum — the
    "slowest f+1-th voter named" column.  Granularity caveat: the views
    observe arrivals per INGEST WAVE, so votes landing in one coalesced
    wave are simultaneous to the instrument and ties within the
    completing wave resolve in signer-index order — a follower is only
    distinguishably slow when its vote misses its peers' wave."""
    # -- pass 1: (shard, view, seq)-scoped pipeline marks ------------------
    leader_of: dict[tuple, str] = {}
    vs_marks: dict[tuple, dict[str, dict[str, float]]] = {}
    # per-slot completing voter BY OBSERVING NODE (insertion order =
    # merge order, earliest first): resolved leader-first at join time,
    # like the timestamp marks — each replica's quorum can complete on a
    # different arrival order, and mixing perspectives would blame a
    # voter that was not last on the LEADER's (critical) path
    slowest_prepare: dict[tuple, dict[str, int]] = {}
    for ev in events:
        kind = ev.get("kind", "")
        mark = _VS_MARK_OF_KIND.get(kind)
        if mark is None:
            continue
        view, seq = ev.get("view"), ev.get("seq")
        if view is None or seq is None:
            continue
        node = ev.get("node", "")
        vs = _vs_key(node, view, seq)
        if kind == "batch.propose" and vs not in leader_of:
            leader_of[vs] = node
        per_node = vs_marks.setdefault(vs, {}).setdefault(mark, {})
        if node not in per_node:
            per_node[node] = ev.get("t", 0.0)
        if kind == "quorum.prepare":
            voter = (ev.get("extra") or {}).get("slowest_voter")
            if voter is not None and voter >= 0:
                slowest_prepare.setdefault(vs, {}).setdefault(node, voter)
    # -- pass 2: per-request submit/pool/deliver marks ---------------------
    submits: dict[str, float] = {}
    pools: dict[str, float] = {}
    delivers: dict[str, list] = {}  # key -> [(node, t, view, seq)]
    for ev in events:
        kind = ev.get("kind", "")
        key = ev.get("key", "")
        if not key:
            continue
        if kind == "req.submit":
            submits.setdefault(key, ev.get("t", 0.0))
        elif kind == "req.pool":
            pools.setdefault(key, ev.get("t", 0.0))
        elif kind == "req.deliver":
            delivers.setdefault(key, []).append(
                (ev.get("node", ""), ev.get("t", 0.0),
                 ev.get("view"), ev.get("seq"))
            )
    # -- join --------------------------------------------------------------
    rows: list[dict] = []
    voter_counts: dict[int, int] = {}
    counted_vs: set = set()  # one count per QUORUM, not per request —
    # a 100-request batch's quorum must not outvote a 1-request batch's
    for key, dels in delivers.items():
        t_submit = submits.get(key)
        if t_submit is None:
            continue  # ring overwrote the submit: skip, count below
        # the request's slot: from its deliver events (prefer the leader's)
        view, seq = dels[0][2], dels[0][3]
        if view is None or seq is None:
            continue
        vs = _vs_key(dels[0][0], view, seq)
        leader = leader_of.get(vs, "")
        deliver = next((d for d in dels if d[0] == leader),
                       min(dels, key=lambda d: d[1]))
        marks: dict[str, float] = {"submit": t_submit,
                                   "deliver": deliver[1]}
        t_pool = pools.get(key)
        if t_pool is not None:
            marks["pool"] = t_pool
        for mark, per_node in vs_marks.get(vs, {}).items():
            t = per_node.get(leader)
            if t is None and per_node:
                t = min(per_node.values())
            if t is not None:
                marks[mark] = t
        row = _decompose(marks)
        if row is None:
            continue
        row["key"] = key
        rows.append(row)
        by_node = slowest_prepare.get(vs)
        if by_node and vs not in counted_vs:
            counted_vs.add(vs)
            voter = by_node.get(leader, next(iter(by_node.values())))
            voter_counts[voter] = voter_counts.get(voter, 0) + 1
    rows.sort(key=lambda r: r["key"])
    block = _fold(rows, residual_tolerance_ms=residual_tolerance_ms,
                  sample=sample)
    block["requests_seen"] = len(delivers)
    block["requests_decomposed"] = len(rows)
    block["slowest_prepare_voters"] = {
        str(v): n for v, n in sorted(voter_counts.items())
    }
    block["slowest_prepare_voter"] = (
        max(voter_counts, key=voter_counts.get) if voter_counts else None
    )
    if phases:
        by_phase: dict[str, list] = {}
        for r in rows:
            rid = r["key"].split(":", 1)[-1]
            for p in phases:
                if rid.startswith(p):
                    by_phase.setdefault(p, []).append(r)
                    break
        block["phases"] = {
            p: _fold(prows, residual_tolerance_ms=residual_tolerance_ms,
                     sample=0)
            for p, prows in by_phase.items()
        }
    return block
