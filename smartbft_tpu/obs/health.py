"""Live health verdicts over the SLO spec: the judgment layer's top half.

:class:`HealthMonitor` ties the declarative :mod:`~smartbft_tpu.obs.slo`
rules to the signal surfaces that already exist — the request pool's
occupancy snapshot, the per-Consensus
:class:`~smartbft_tpu.obs.vcphases.ViewChangePhaseTracker`, the verify
coalescer's breaker/mesh state, the WAL's always-on fsync histograms,
and the sharded front door's latency tracker — and renders a
``healthy`` / ``degraded(reasons[])`` / ``critical`` verdict an operator
(or the chaos harness) can poll.

Event-shaped signals (a heartbeat detection, a shed, a backlog reading
at the view flip) are **latched**: the monitor holds the value live for
``latch_s`` seconds after the underlying counter moved, then releases it
to 0 — so a 20-second detection reads as a violation while it is recent
and ages out of the verdict as the fast burn window drains, instead of a
stale gauge pinning the cluster degraded forever.

Verdict **transitions** are first-class: every status change is appended
to ``transitions`` and recorded into the flight recorder as
``slo.breach`` / ``slo.clear`` span events carrying the breaching rule
names, so an SLO violation lands on the merged cluster timeline next to
the fault that caused it.

:func:`aggregate_cluster_verdict` folds n per-replica verdicts (plus the
unreachable set) into ONE cluster verdict — what
``SocketCluster.cluster_health()`` returns from a single control-channel
sweep.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

from .recorder import NOP_RECORDER
from .slo import (
    CRITICAL,
    DEGRADED,
    HEALTHY,
    SLOEvaluator,
    SLOSpec,
    default_slo_spec,
    worse,
)

__all__ = [
    "HealthMonitor",
    "aggregate_cluster_verdict",
    "vc_signal_source",
    "pool_signal_source",
    "coalescer_signal_source",
    "wal_signal_source",
    "snapshot_signal_source",
    "latency_signal_source",
    "EventLatch",
]


class EventLatch:
    """Hold an event value live for ``hold_s`` after its counter moved."""

    __slots__ = ("hold_s", "prev_count", "value", "since")

    def __init__(self, hold_s: float):
        self.hold_s = hold_s
        self.prev_count: Optional[float] = None
        self.value = 0.0
        self.since: Optional[float] = None

    def update(self, count: float, value: float, now: float) -> float:
        if self.prev_count is None:
            # first sight: pre-existing history is not a fresh event
            self.prev_count = count
        elif count > self.prev_count:
            self.prev_count = count
            self.value = value
            self.since = now
        elif count < self.prev_count:
            # the counter DROPPED (a restart reset it, or an aggregate
            # lost a member to a scale-in): that is not a fresh event —
            # latching here would report a violation nothing produced.
            # Re-anchor so the NEXT increase latches correctly.
            self.prev_count = count
        if self.since is not None and now - self.since <= self.hold_s:
            return self.value
        return 0.0


def vc_signal_source(tracker, *, clock, latch_s: float = 5.0) -> Callable:
    """Signals from one ViewChangePhaseTracker:

    - ``viewchange.active_seconds`` — time the current round has been
      open (0 when none is);
    - ``viewchange.detection_seconds`` — the latest heartbeat
      arm-to-fire sample, latched for ``latch_s`` after it fired;
    - ``viewchange.backlog_at_flip`` — the latest completed round's
      flip backlog, latched the same way."""
    det = EventLatch(latch_s)
    backlog = EventLatch(latch_s)

    def signals() -> dict:
        now = clock()
        out = {}
        # active = a view change actually IN PROGRESS: anchored at the
        # complaint-quorum mark ("joined"), not at the arm — a lone
        # complainer against a healthy leader keeps its armed round open
        # indefinitely by design (nobody joins), and that suspicion must
        # not pin the verdict degraded while commits flow; the detection
        # signal below already surfaces the suspicion itself.  The delta
        # is computed on the TRACKER's clock: its marks live in the
        # consensus scheduler's domain, which on a wall-driven replica is
        # NOT the monitor's time.monotonic (different epoch).
        joined = tracker._marks.get("joined") if tracker.open else None
        out["viewchange.active_seconds"] = \
            max(tracker._clock() - joined, 0.0) if joined is not None \
            else 0.0
        last_det = (tracker._detections[-1] / 1e3
                    if tracker._detections else 0.0)
        out["viewchange.detection_seconds"] = det.update(
            tracker.detections_total, last_det, now
        )
        recs = tracker.records()
        last_backlog = float(recs[-1].get("backlog_at_flip", 0)) \
            if recs else 0.0
        out["viewchange.backlog_at_flip"] = backlog.update(
            tracker.completed_total, last_backlog, now
        )
        return out

    return signals


def pool_signal_source(occupancy_fn: Callable[[], dict], *, clock,
                       latch_s: float = 5.0) -> Callable:
    """Signals from a pool/front-door occupancy snapshot:
    ``pool.fill`` (system size / capacity) and ``pool.shed_recent``
    (1.0 while sheds happened within ``latch_s``)."""
    sheds = EventLatch(latch_s)

    def signals() -> dict:
        occ = occupancy_fn() or {}
        cap = occ.get("capacity", 0) or 0
        size = (occ.get("size", 0) or 0) + (occ.get("waiters", 0) or 0)
        out = {}
        if cap:
            out["pool.fill"] = size / cap
        shed_total = (occ.get("shed_admission", 0) or 0) \
            + (occ.get("shed_timeout", 0) or 0)
        out["pool.shed_recent"] = 1.0 if sheds.update(
            shed_total, 1.0, clock()
        ) else 0.0
        return out

    return signals


def coalescer_signal_source(coalescer) -> Callable:
    """Signals from the shared verify coalescer: breaker state and the
    mesh's minimum per-device fill (when a mesh is installed)."""

    def signals() -> dict:
        out = {"verify.breaker_open":
               1.0 if getattr(coalescer, "breaker_open", False) else 0.0}
        snap_fn = getattr(coalescer, "mesh_snapshot", None)
        if snap_fn is not None:
            try:
                snap = snap_fn() or {}
            except Exception:  # noqa: BLE001 — telemetry only
                snap = {}
            if snap.get("enabled") and snap.get("launches"):
                fills = snap.get("device_fill_pct_last") or []
                if fills:
                    out["mesh.device_fill_pct"] = float(min(fills))
        return out

    return signals


def wal_signal_source(wal) -> Callable:
    """``wal.fsync_p99_ms`` from the WAL's always-on span histograms."""

    def signals() -> dict:
        span_fn = getattr(wal, "span_block", None)
        if span_fn is None:
            return {}
        try:
            block = span_fn() or {}
        except Exception:  # noqa: BLE001 — telemetry only
            return {}
        fsync = block.get("fsync") or {}
        if fsync.get("count"):
            return {"wal.fsync_p99_ms": float(fsync.get("p99_ms", 0.0))}
        return {}

    return signals


def snapshot_signal_source(disk_fn: Callable[[], dict]) -> Callable:
    """``snapshot.lag_intervals`` from an embedder's disk snapshot dict
    (``ReplicaApp.disk_snapshot`` / ``testing.app.App.disk_snapshot``):
    decisions committed since the last snapshot, normalized by the
    configured interval so the SLO bound is static across deployments.
    Emits nothing when snapshots are disabled (interval 0) — an absent
    signal never breaches, matching the spec's opt-in contract."""

    def signals() -> dict:
        try:
            disk = disk_fn() or {}
        except Exception:  # noqa: BLE001 — telemetry only
            return {}
        interval = disk.get("snapshot_interval", 0) or 0
        if interval <= 0:
            return {}
        age = disk.get("snapshot_age_decisions", 0) or 0
        return {"snapshot.lag_intervals": float(age) / float(interval)}

    return signals


def read_signal_source(stats_fn: Callable[[], dict], *, clock=None,
                       latch_s: float = 5.0) -> Callable:
    """Read-plane signals (ISSUE 19) from a ``ReadStats.snapshot`` dict:

    - ``read.shed_recent`` — 1.0 while the read gate shed within the
      latch window (a read storm being absorbed: degraded by design,
      and proof the storm is NOT reaching the write path);
    - ``read.base_refused_recent`` — 1.0 while a read-at-base was
      refused over a torn/tampered snapshot within the window (an
      integrity event, not load);
    - ``read.staleness_decisions`` — the worst anchor lag served,
      latched while snapshot-anchored reads are actively landing.

    An idle read plane emits nothing — absent signals never breach,
    matching the snapshot source's opt-in contract."""
    import time

    clk = clock if clock is not None else time.monotonic
    shed = EventLatch(latch_s)
    refused = EventLatch(latch_s)
    staleness = EventLatch(latch_s)

    def signals() -> dict:
        try:
            stats = stats_fn() or {}
        except Exception:  # noqa: BLE001 — telemetry only
            return {}
        now = clk()
        shed_live = shed.update(float(stats.get("sheds", 0)), 1.0, now)
        refused_live = refused.update(
            float(stats.get("base_refused", 0)), 1.0, now)
        stale_live = staleness.update(
            float(stats.get("served_base", 0)),
            float(stats.get("lag_max", 0)), now)
        if not (stats.get("served", 0) or stats.get("sheds", 0)
                or stats.get("base_refused", 0)):
            return {}
        out = {"read.shed_recent": shed_live,
               "read.base_refused_recent": refused_live}
        if stats.get("served_base", 0):
            out["read.staleness_decisions"] = stale_live
        return out

    return signals


def latency_signal_source(tracker) -> Callable:
    """``latency.commit_p99_ms`` from a CommitLatencyTracker — the p99 of
    commits landed SINCE THE LAST TICK (ISSUE 20).  The lifetime
    aggregate is the wrong verdict input: one bad spell dominates its
    p99 forever, so a breach could never clear and the control plane
    would remediate history.  Per-tick deltas give the SLO evaluator
    fresh samples; its own fast/slow windows provide the smoothing.  A
    tick with no new commits emits nothing (no signal ≠ zero latency)."""
    state = {"buckets": None}

    def signals() -> dict:
        hist = tracker.aggregate
        if not hist.count:
            return {}
        if state["buckets"] is None:
            # first sight: lifetime p99 seeds the window (no baseline yet)
            state["buckets"] = list(hist.buckets)
            return {"latency.commit_p99_ms": hist.quantile(0.99) * 1e3}
        p99 = hist.delta_quantile(0.99, state["buckets"])
        if p99 <= 0.0:
            return {}
        state["buckets"] = list(hist.buckets)
        return {"latency.commit_p99_ms": p99 * 1e3}

    return signals


class HealthMonitor:
    """One replica's (or one cluster's) live verdict machine.

    ``sources`` are zero-arg callables returning partial signal dicts;
    the monitor unions them per tick, feeds the
    :class:`~smartbft_tpu.obs.slo.SLOEvaluator`, and tracks verdict
    transitions.  A failing source is counted, never fatal — a health
    plane that can crash the thing it judges is worse than no health
    plane."""

    def __init__(self, spec: Optional[SLOSpec] = None, *, clock=None,
                 recorder=None, node: str = "", max_transitions: int = 256):
        self._clock = clock if clock is not None else time.monotonic
        self.spec = spec if spec is not None else default_slo_spec()
        self.node = node
        self.recorder = recorder if recorder is not None else NOP_RECORDER
        self.evaluator = SLOEvaluator(self.spec, clock=self._clock)
        self._sources: list[Callable[[], dict]] = []
        self.source_errors = 0
        self.status = HEALTHY
        self.reasons: list[dict] = []
        self._since = self._clock()
        #: bounded (t, status, [rule names]) history, oldest dropped
        self.transitions: list[tuple] = []
        self.max_transitions = max_transitions
        self.ticks = 0

    # -- wiring -------------------------------------------------------------

    def add_source(self, fn: Callable[[], dict]) -> "HealthMonitor":
        self._sources.append(fn)
        return self

    def watch_consensus(self, consensus, *, latch_s: float = 5.0
                        ) -> "HealthMonitor":
        """Wire the standard per-replica surfaces of one Consensus: the
        VC phase tracker and the request pool."""
        self.add_source(vc_signal_source(
            consensus.vc_phases, clock=self._clock, latch_s=latch_s
        ))
        self.add_source(pool_signal_source(
            consensus.pool_occupancy, clock=self._clock, latch_s=latch_s
        ))
        return self

    # -- ticking ------------------------------------------------------------

    def tick(self) -> dict:
        """Sample every source, evaluate, record any transition.
        Returns the current verdict dict."""
        now = self._clock()
        self.ticks += 1
        signals: dict = {}
        for fn in self._sources:
            try:
                signals.update(fn() or {})
            except Exception:  # noqa: BLE001 — judged, never judging
                self.source_errors += 1
        self.evaluator.observe(signals, t=now)
        verdict = self.evaluator.evaluate(t=now)
        if verdict.status != self.status:
            self._transition(verdict, now)
        self.status = verdict.status
        self.reasons = [b.as_dict() for b in verdict.breaches]
        return self.verdict()

    def _transition(self, verdict, now: float) -> None:
        names = verdict.reasons
        self.transitions.append((now, verdict.status, names))
        if len(self.transitions) > self.max_transitions:
            del self.transitions[0]
        self._since = now
        rec = self.recorder
        if rec.enabled:
            kind = "slo.clear" if verdict.status == HEALTHY else "slo.breach"
            rec.record(kind, node=self.node,
                       extra={"status": verdict.status,
                              "slos": names[:8]})

    # -- reading ------------------------------------------------------------

    def verdict(self) -> dict:
        """The JSON-able verdict a control channel serves."""
        return {
            "status": self.status,
            "reasons": self.reasons,
            "since": round(self._clock() - self._since, 3),
            "spec": self.spec.name,
            "ticks": self.ticks,
            "transitions": len(self.transitions),
            "source_errors": self.source_errors,
        }

    def transition_log(self) -> list[dict]:
        return [
            {"t": round(t, 4), "status": status, "slos": list(names)}
            for t, status, names in self.transitions
        ]


def aggregate_cluster_verdict(replica_verdicts: dict,
                              unreachable: Sequence[str] = ()) -> dict:
    """Fold per-replica verdicts into ONE cluster verdict.

    The cluster is as sick as its sickest replica; replicas that did not
    answer the sweep are a degradation in themselves (one unreachable)
    and critical when a majority is gone — an operator must never read
    "healthy" off a sweep that reached one node out of four."""
    status = HEALTHY
    reasons: list[dict] = []
    for node, v in sorted(replica_verdicts.items()):
        status = worse(status, v.get("status", HEALTHY))
        for r in v.get("reasons", []):
            reasons.append(dict(r, node=node))
    unreachable = list(unreachable)
    if unreachable:
        total = len(replica_verdicts) + len(unreachable)
        majority_gone = len(unreachable) * 2 > total
        status = worse(status, CRITICAL if majority_gone else DEGRADED)
        reasons.append({
            "slo": "replica.unreachable",
            "severity": CRITICAL if majority_gone else DEGRADED,
            "value": float(len(unreachable)),
            "bound": 0.0,
            "nodes": unreachable,
        })
    return {
        "status": status,
        "replicas": {n: v.get("status", HEALTHY)
                     for n, v in sorted(replica_verdicts.items())},
        "reasons": reasons,
        "unreachable": unreachable,
    }
