"""Bounded-memory flight recorder for request-scoped protocol tracing.

A :class:`TraceRecorder` is a fixed ring buffer of :class:`SpanEvent`
records — submit, park, pool, propose, ingest wave, verify launch,
deliver, view-change sub-phase marks, control-plane transitions —
correlated by request key (``"client:rid"``), (view, seq), reshard
epoch, and verify-launch id.  The memory contract is the whole point:

* the ring never exceeds ``capacity`` events (the oldest is overwritten
  and counted in ``dropped``);
* per-kind duration statistics live in fixed-array
  :class:`~smartbft_tpu.metrics.LogScaleHistogram` buckets, capped at
  ``span_kinds_cap`` distinct kinds (overflow folds into ``"_other"``);
* the clock is injectable (``Scheduler.now`` in logical tests, wall
  ``time.monotonic`` in benches) — the same idiom as
  ``CommitLatencyTracker``.

When tracing is off, components hold :data:`NOP_RECORDER` (the
``DisabledProvider`` pattern): every instrumentation site guards with
``if rec.enabled:`` so a disabled recorder costs one attribute read per
site and allocates nothing.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional, Sequence

from ..metrics import LogScaleHistogram

__all__ = [
    "SpanEvent",
    "TraceRecorder",
    "NopRecorder",
    "NOP_RECORDER",
    "assemble_trace_block",
]


class SpanEvent:
    """One structured trace event.  ``dur`` >= 0 marks a completed span
    (seconds); -1 marks a point event.  Unset correlators stay at their
    sentinel (-1 / "") and are omitted from the dict form.  ``seqno`` is
    the recorder-assigned all-time event sequence (1-based) — the
    incremental-pull cursor compares against it EXACTLY, so a snapshot
    racing a concurrent record (the WAL executor thread) can never skip
    or double-ship an event."""

    __slots__ = ("t", "kind", "node", "key", "view", "seq", "epoch",
                 "launch", "dur", "extra", "seqno")

    def __init__(self, t: float, kind: str, node: str = "", key: str = "",
                 view: int = -1, seq: int = -1, epoch: int = -1,
                 launch: int = -1, dur: float = -1.0,
                 extra: Optional[dict] = None):
        self.t = t
        self.kind = kind
        self.node = node
        self.key = key
        self.view = view
        self.seq = seq
        self.epoch = epoch
        self.launch = launch
        self.dur = dur
        self.extra = extra
        self.seqno = 0

    def as_dict(self) -> dict:
        out = {"t": round(self.t, 6), "kind": self.kind}
        if self.node:
            out["node"] = self.node
        if self.key:
            out["key"] = self.key
        if self.view >= 0:
            out["view"] = self.view
        if self.seq >= 0:
            out["seq"] = self.seq
        if self.epoch >= 0:
            out["epoch"] = self.epoch
        if self.launch >= 0:
            out["launch"] = self.launch
        if self.dur >= 0:
            out["dur_ms"] = round(self.dur * 1e3, 3)
        if self.extra:
            out["extra"] = self.extra
        return out


class TraceRecorder:
    """Ring buffer of :class:`SpanEvent` with bounded per-kind stats."""

    enabled = True

    def __init__(self, *, clock=None, node: str = "", capacity: int = 4096,
                 span_kinds_cap: int = 64):
        self._clock = clock if clock is not None else time.monotonic
        self.node = node
        self.capacity = max(int(capacity), 1)
        self.span_kinds_cap = max(int(span_kinds_cap), 1)
        self._buf: list = [None] * self.capacity
        self._idx = 0
        self.recorded = 0
        # recorders are fed from the event loop AND executor threads (the
        # WAL group-commit fsync spans): the ring/seqno update is a
        # read-modify-write, so it takes a lock — uncontended acquire is
        # ~100 ns next to the event construction it guards, and without
        # it two racing records share one slot + seqno, breaking the
        # events_since exactness contract and the dropped count
        self._write_lock = threading.Lock()
        #: all-time per-kind event counts (bounded like the span dict)
        self.kind_counts: dict[str, int] = {}
        #: per-kind duration histograms for events carrying ``dur``
        self.spans: dict[str, LogScaleHistogram] = {}

    @property
    def dropped(self) -> int:
        """Events overwritten by the ring bound (recorded beyond cap)."""
        return max(0, self.recorded - self.capacity)

    def _bounded_kind(self, store: dict, kind: str) -> str:
        if kind in store or len(store) < self.span_kinds_cap:
            return kind
        return "_other"

    def record(self, kind: str, *, node: str = "", key: str = "",
               view: int = -1, seq: int = -1, epoch: int = -1,
               launch: int = -1, dur: float = -1.0,
               extra: Optional[dict] = None,
               t: Optional[float] = None) -> SpanEvent:
        """``t`` overrides the event timestamp (SAME clock domain as the
        recorder's): for marks whose true instant precedes the record
        call — the transport stamps ``net.recv`` with the socket READ
        time so per-hop network time excludes the consensus processing
        awaited between read and record."""
        ev = SpanEvent(t if t is not None else self._clock(), kind,
                       node or self.node, key, view,
                       seq, epoch, launch, dur, extra)
        with self._write_lock:
            seqno = self.recorded + 1
            ev.seqno = seqno
            self._buf[self._idx] = ev
            self._idx = (self._idx + 1) % self.capacity
            self.recorded = seqno
            ck = self._bounded_kind(self.kind_counts, kind)
            self.kind_counts[ck] = self.kind_counts.get(ck, 0) + 1
            if dur >= 0.0:
                sk = self._bounded_kind(self.spans, kind)
                hist = self.spans.get(sk)
                if hist is None:
                    hist = self.spans[sk] = LogScaleHistogram()
                hist.observe(dur)
        return ev

    # -- reading -----------------------------------------------------------

    def events(self, last: Optional[int] = None) -> list:
        """The buffered events in chronological (record) order, optionally
        only the newest ``last``.  Takes the write lock: an unlocked read
        racing a wrapped-ring record() between its slot write and index
        advance would rotate the newest event to the FRONT of the list,
        breaking chronological order and the since-cursor exactness
        (cursor = out[-1].seqno would under-report an already-shipped
        event).  Reads are control-channel-rate, so the lock never
        contends the hot path."""
        with self._write_lock:
            if self.recorded >= self.capacity:
                ordered = self._buf[self._idx:] + self._buf[:self._idx]
            else:
                ordered = self._buf[:self._idx]
            out = [e for e in ordered if e is not None]
        if last is not None and last >= 0:
            out = out[-last:] if last else []
        return out

    def snapshot(self, last: Optional[int] = None) -> list[dict]:
        return [e.as_dict() for e in self.events(last)]

    def events_since(self, since: int) -> tuple[list, int]:
        """Incremental read for repeated pulls: the buffered events
        recorded AFTER cursor ``since``, plus the next cursor.

        The cursor is an event's all-time ``seqno`` (0 means "from the
        beginning"); the filter compares EXACTLY against each buffered
        event's own sequence number, so a snapshot racing a concurrent
        ``record`` (recorders are fed from executor threads too — the
        WAL fsync spans) can never skip or double-ship: an event that
        missed this snapshot keeps a seqno above the returned cursor and
        ships next pull.  Events the ring already overwrote are gone — a
        puller more than ``capacity`` events behind gets only the
        surviving tail (the gap is visible as ``dropped`` growth) — and
        a cursor from the future (stale after a recorder restart) stays
        at "nothing new".  This is what keeps ``cmd=trace`` pulls O(new
        events) instead of re-shipping the whole ring every poll."""
        since = max(0, int(since))
        out = [e for e in self.events() if e.seqno > since]
        return out, (out[-1].seqno if out else since)

    def snapshot_since(self, since: int) -> tuple[list[dict], int]:
        events, cursor = self.events_since(since)
        return [e.as_dict() for e in events], cursor

    def trace_block(self) -> dict:
        """The JSON-able ``trace`` summary block (bench rows, cmd=trace)."""
        return {
            "enabled": True,
            "node": self.node,
            "capacity": self.capacity,
            "recorded": self.recorded,
            "dropped": self.dropped,
            "kinds": dict(sorted(self.kind_counts.items())),
            "spans": {k: h.snapshot()
                      for k, h in sorted(self.spans.items())},
        }

    def dump(self) -> dict:
        """The full JSON-able dump (events + summary) the chaos runner
        writes per replica and ``python -m smartbft_tpu.obs.report``
        renders."""
        return {
            "node": self.node,
            "capacity": self.capacity,
            "recorded": self.recorded,
            "dropped": self.dropped,
            "events": self.snapshot(),
        }

    def dump_to(self, path: str) -> str:
        with open(path, "w") as fh:
            json.dump(self.dump(), fh)
        return path


class NopRecorder:
    """The disabled recorder: every site's ``if rec.enabled:`` guard is
    False, so tracing off costs one attribute read per instrumentation
    point and allocates nothing (the ``DisabledProvider`` pattern)."""

    enabled = False
    node = ""
    capacity = 0
    recorded = 0
    dropped = 0

    def record(self, kind: str, **_kw) -> None:
        return None

    def events(self, last: Optional[int] = None) -> list:
        return []

    def snapshot(self, last: Optional[int] = None) -> list:
        return []

    def events_since(self, since: int) -> tuple[list, int]:
        return [], 0

    def snapshot_since(self, since: int) -> tuple[list, int]:
        return [], 0

    def trace_block(self) -> dict:
        return {"enabled": False}

    def dump(self) -> dict:
        return {"node": "", "capacity": 0, "recorded": 0, "dropped": 0,
                "events": []}

    def dump_to(self, path: str) -> str:
        with open(path, "w") as fh:
            json.dump(self.dump(), fh)
        return path


#: the process-wide disabled singleton components default to
NOP_RECORDER = NopRecorder()


def pct(sorted_vals: Sequence[float], q: float) -> float:
    """The q-quantile (0..1) of an ALREADY-SORTED value list by index —
    the one exact-percentile helper the obs modules share (vcphases'
    pooled VC records, report's span summaries)."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


def assemble_trace_block(recorders: Sequence) -> dict:
    """Fold N recorders (one per replica + shared-plane recorders) into
    the ONE ``trace`` block a bench row carries.  Pure function — the
    PR 8 ``assemble_*`` idiom, schema-pinned by tests/test_obs.py.

    Per-kind duration percentiles are EXACT merges: the per-recorder
    LogScaleHistograms share one geometry, so bucket-wise summation is
    the true combined distribution (not a percentile-of-percentiles)."""
    live = [r for r in recorders if getattr(r, "enabled", False)]
    kinds: dict[str, int] = {}
    spans: dict[str, LogScaleHistogram] = {}
    for r in live:
        for k, n in r.kind_counts.items():
            kinds[k] = kinds.get(k, 0) + n
        for k, h in r.spans.items():
            agg = spans.get(k)
            if agg is None:
                agg = spans[k] = LogScaleHistogram()
            agg.merge_from(h)
    return {
        "enabled": bool(live),
        "recorders": len(live),
        "recorded": sum(r.recorded for r in live),
        "dropped": sum(r.dropped for r in live),
        "kinds": dict(sorted(kinds.items())),
        "spans": {k: h.snapshot() for k, h in sorted(spans.items())},
    }
