"""Render flight-recorder dumps: ``python -m smartbft_tpu.obs.report``.

Input: one or more JSON dump files (``TraceRecorder.dump_to``, the chaos
runner's per-replica ``flight-*.json`` artifacts, or a ``cmd=trace``
control-channel response saved to disk).  Output: a merged text timeline
(events from every replica interleaved by timestamp, offsets relative to
the earliest event) followed by a per-span-type percentile summary over
the events that carry durations, plus derived submit→deliver spans
joined by request key when both ends are present.

**Cluster timelines (ISSUE 13).**  Multi-PROCESS dumps live on different
monotonic clocks; a dump carrying ``clock_offset_s`` (written by
``SocketCluster.cluster_timeline`` from the control-channel ping
midpoint estimate) has every event timestamp shifted by ``-offset``
during the merge, so N replicas' rings interleave on ONE causally-
ordered timeline with a stated error bound (RTT/2 per replica).  When
offsets are known, ``net.recv`` sidecar events additionally yield a
per-directed-link network-time summary: receiver ingest (skew-adjusted)
minus the sender's flush stamp (``extra.sent_us``, mapped through the
SENDER's offset).

Usage::

    python -m smartbft_tpu.obs.report run/flight-*.json [--last N]
    python -m smartbft_tpu.obs.report dump.json --summary-only
    python -m smartbft_tpu.obs.report run/flight-*.json \
        --offsets run/offsets.json   # {"n1": {"offset_s": ...}, ...}
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

from .recorder import pct as _pct

__all__ = ["load_dump", "merged_events", "link_summary", "render", "main"]


def load_dump(path: str) -> dict:
    """Load one dump file; accepts both the recorder's native dump shape
    and a saved ``cmd=trace`` control response (events under "events")."""
    with open(path) as fh:
        data = json.load(fh)
    if "events" not in data:
        raise ValueError(f"{path}: not a flight-recorder dump (no 'events')")
    return data


def merged_events(dumps: list[dict]) -> list[dict]:
    """Fold N dumps into one chronologically-sorted event list.

    Each event gets its dump's ``node`` label (when the event lacks one)
    and — the clock-alignment step — its timestamp shifted by the dump's
    ``clock_offset_s`` so every replica's monotonic clock maps onto the
    estimator's (parent's) timeline: ``t_cluster = t_replica - offset``.
    Dumps without an offset merge unshifted (the single-process case,
    where all recorders already share one clock).  Pure function."""
    events: list[dict] = []
    for d in dumps:
        node = d.get("node", "")
        off = float(d.get("clock_offset_s", 0.0) or 0.0)
        for ev in d.get("events", []):
            if (node and "node" not in ev) or off:
                ev = dict(ev)
                if node and "node" not in ev:
                    ev["node"] = node
                if off:
                    ev["t"] = ev.get("t", 0.0) - off
            events.append(ev)
    events.sort(key=lambda e: e.get("t", 0.0))
    return events


def link_summary(events: list[dict], offsets: dict) -> list[dict]:
    """Per-directed-link network time from ``net.recv`` sidecar events.

    ``events`` must already be clock-aligned (:func:`merged_events`);
    ``offsets`` maps node label -> offset seconds (the SENDER's stamp
    ``extra.sent_us`` is in the sender's clock and needs its own
    offset).  Per hop: ``net_ms = (t_recv_aligned - (sent_us/1e6 -
    offset_sender)) * 1e3``.  In a multi-clock merge (``offsets``
    non-empty) a hop needs BOTH endpoints' offsets known — rows whose
    sender or receiver clock is unestimated are skipped rather than
    published with unbounded skew.  Returns one row per directed link
    with exact percentiles — the WAN-profile work (ROADMAP item 5)
    reads per-link time straight off this table.

    Offset-estimation error can exceed a loopback hop's real flight
    time: an apparently NEGATIVE network time is an artifact of that
    error bound, so it is CLAMPED to 0 and counted per link
    (``clamped``) instead of published as a physically impossible
    measurement."""
    links: dict[tuple, list] = {}
    clamped: dict[tuple, int] = {}
    for ev in events:
        if ev.get("kind") != "net.recv":
            continue
        extra = ev.get("extra") or {}
        sent_us = extra.get("sent_us")
        frm = extra.get("from")
        if sent_us is None or frm is None:
            continue
        sender = f"n{frm}"
        off = offsets.get(sender)
        if offsets and (off is None or ev.get("node", "?") not in offsets):
            continue  # an endpoint's clock was never aligned: skip
        if off is None:
            off = 0.0  # single-clock run: no shift needed anywhere
        net_ms = (ev.get("t", 0.0) - (sent_us / 1e6 - off)) * 1e3
        key = (sender, ev.get("node", "?"))
        if net_ms < 0.0:
            clamped[key] = clamped.get(key, 0) + 1
            net_ms = 0.0
        links.setdefault(key, []).append(net_ms)
    rows = []
    for (a, b), vals in sorted(links.items()):
        vals.sort()
        rows.append({
            "link": f"{a}->{b}",
            "count": len(vals),
            "p50_ms": round(_pct(vals, 0.50), 3),
            "p95_ms": round(_pct(vals, 0.95), 3),
            "p99_ms": round(_pct(vals, 0.99), 3),
            "max_ms": round(vals[-1], 3),
            # samples the skew error bound pushed below zero (published
            # as 0): err_bound exceeding the hop time is EXPECTED on
            # loopback, and hiding the clamp would overstate precision
            "clamped": clamped.get((a, b), 0),
        })
    return rows


def _fmt_event(ev: dict, t0: float) -> str:
    parts = [f"+{ev.get('t', 0.0) - t0:10.4f}s",
             f"[{ev.get('node', '?'):>6}]",
             f"{ev.get('kind', '?'):<22}"]
    for field, tag in (("key", ""), ("view", "v"), ("seq", "s"),
                       ("epoch", "e"), ("launch", "L")):
        if field in ev:
            parts.append(f"{tag}{ev[field]}")
    if "dur_ms" in ev:
        parts.append(f"{ev['dur_ms']:.3f}ms")
    if ev.get("extra"):
        parts.append(json.dumps(ev["extra"], sort_keys=True))
    return " ".join(parts)


def _summary_rows(events: list[dict]) -> list[tuple]:
    """(kind, count, p50, p95, p99, max) over events carrying dur_ms,
    plus derived ``req.submit->deliver`` spans joined by request key."""
    by_kind: dict[str, list] = {}
    for ev in events:
        if "dur_ms" in ev:
            by_kind.setdefault(ev["kind"], []).append(ev["dur_ms"])
    # derived submit→deliver per (node, key): first submit-ish stamp to
    # first deliver stamp — the request's protocol-pipeline span
    first_seen: dict[tuple, float] = {}
    derived: list = []
    for ev in events:
        key = ev.get("key")
        if not key:
            continue
        ident = (ev.get("node", ""), key)
        if ev["kind"] in ("req.submit", "req.pool") \
                and ident not in first_seen:
            first_seen[ident] = ev["t"]
        elif ev["kind"] == "req.deliver" and ident in first_seen:
            derived.append((ev["t"] - first_seen.pop(ident)) * 1e3)
    if derived:
        by_kind["req.submit->deliver"] = derived
    rows = []
    for kind in sorted(by_kind):
        vals = sorted(by_kind[kind])
        rows.append((kind, len(vals), _pct(vals, 0.50), _pct(vals, 0.95),
                     _pct(vals, 0.99), vals[-1]))
    return rows


def render(dumps: list[dict], *, last: Optional[int] = None,
           summary_only: bool = False) -> str:
    """Merged (clock-aligned when offsets present) text timeline +
    per-span-type percentile summary + per-link network times."""
    events = merged_events(dumps)
    aligned = any(d.get("clock_offset_s") for d in dumps)
    if last is not None and last >= 0:
        events = events[-last:] if last else []
    out: list[str] = []
    header = (f"flight recorder: {len(dumps)} dump(s), "
              f"{len(events)} event(s)"
              + (", clock-aligned" if aligned else "")
              + (f", dropped {sum(d.get('dropped', 0) for d in dumps)}"
                 if any(d.get("dropped") for d in dumps) else ""))
    out.append(header)
    unaligned = sorted(d.get("node", "?") for d in dumps
                       if not d.get("offset_known", True))
    if aligned and unaligned:
        # loud degradation: these nodes merge with an UNKNOWN clock —
        # their timestamps are unshifted and their per-link rows are
        # excluded, not silently published with assumed-zero skew
        out.append(
            f"WARNING: no clock offset for {', '.join(unaligned)} — "
            "their events merge UNALIGNED and their links are excluded"
        )
    if events and not summary_only:
        t0 = events[0].get("t", 0.0)
        out.append("")
        out.append("timeline:")
        out.extend("  " + _fmt_event(ev, t0) for ev in events)
    rows = _summary_rows(events)
    if rows:
        out.append("")
        out.append("span summary (ms):")
        out.append(f"  {'kind':<24} {'count':>6} {'p50':>10} {'p95':>10} "
                   f"{'p99':>10} {'max':>10}")
        for kind, n, p50, p95, p99, mx in rows:
            out.append(f"  {kind:<24} {n:>6} {p50:>10.3f} {p95:>10.3f} "
                       f"{p99:>10.3f} {mx:>10.3f}")
    offsets = {d.get("node", ""): d.get("clock_offset_s", 0.0)
               for d in dumps
               if d.get("node") and d.get("offset_known", True)}
    hops = link_summary(events, offsets if aligned else {})
    if hops:
        out.append("")
        out.append("per-link network time (ms"
                   + (", skew-adjusted" if aligned else "") + "):")
        out.append(f"  {'link':<12} {'count':>6} {'p50':>10} {'p95':>10} "
                   f"{'p99':>10} {'max':>10}")
        for h in hops:
            out.append(f"  {h['link']:<12} {h['count']:>6} "
                       f"{h['p50_ms']:>10.3f} {h['p95_ms']:>10.3f} "
                       f"{h['p99_ms']:>10.3f} {h['max_ms']:>10.3f}")
    return "\n".join(out) + "\n"


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Render SmartBFT flight-recorder dumps as a text "
                    "timeline + per-span-type percentile summary"
    )
    ap.add_argument("dumps", nargs="+", help="flight-recorder JSON dump(s)")
    ap.add_argument("--last", type=int, default=None,
                    help="only the newest N merged events")
    ap.add_argument("--summary-only", action="store_true",
                    help="skip the timeline, print only the span summary")
    ap.add_argument("--offsets", default=None,
                    help="JSON file of per-node clock offsets "
                         "({\"n1\": {\"offset_s\": ...}, ...} — "
                         "SocketCluster.cluster_timeline writes one); "
                         "applied to dumps lacking an embedded offset")
    args = ap.parse_args(argv)
    dumps = [load_dump(p) for p in args.dumps]
    if args.offsets:
        with open(args.offsets) as fh:
            offs = json.load(fh)
        for d in dumps:
            if "clock_offset_s" not in d:
                known = d.get("node", "") in offs
                entry = offs.get(d.get("node", ""), {})
                d["clock_offset_s"] = (
                    entry.get("offset_s", 0.0)
                    if isinstance(entry, dict) else float(entry)
                )
                # a node ABSENT from the offsets file merges with an
                # UNKNOWN clock — flag it so its per-link rows are
                # skipped, not published with assumed-zero skew
                d["offset_known"] = known
    print(render(dumps, last=args.last, summary_only=args.summary_only),
          end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
