"""Render flight-recorder dumps: ``python -m smartbft_tpu.obs.report``.

Input: one or more JSON dump files (``TraceRecorder.dump_to``, the chaos
runner's per-replica ``flight-*.json`` artifacts, or a ``cmd=trace``
control-channel response saved to disk).  Output: a merged text timeline
(events from every replica interleaved by timestamp, offsets relative to
the earliest event) followed by a per-span-type percentile summary over
the events that carry durations, plus derived submit→deliver spans
joined by request key when both ends are present.

Usage::

    python -m smartbft_tpu.obs.report run/flight-*.json [--last N]
    python -m smartbft_tpu.obs.report dump.json --summary-only
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

from .recorder import pct as _pct

__all__ = ["load_dump", "render", "main"]


def load_dump(path: str) -> dict:
    """Load one dump file; accepts both the recorder's native dump shape
    and a saved ``cmd=trace`` control response (events under "events")."""
    with open(path) as fh:
        data = json.load(fh)
    if "events" not in data:
        raise ValueError(f"{path}: not a flight-recorder dump (no 'events')")
    return data


def _fmt_event(ev: dict, t0: float) -> str:
    parts = [f"+{ev.get('t', 0.0) - t0:10.4f}s",
             f"[{ev.get('node', '?'):>6}]",
             f"{ev.get('kind', '?'):<22}"]
    for field, tag in (("key", ""), ("view", "v"), ("seq", "s"),
                       ("epoch", "e"), ("launch", "L")):
        if field in ev:
            parts.append(f"{tag}{ev[field]}")
    if "dur_ms" in ev:
        parts.append(f"{ev['dur_ms']:.3f}ms")
    if ev.get("extra"):
        parts.append(json.dumps(ev["extra"], sort_keys=True))
    return " ".join(parts)


def _summary_rows(events: list[dict]) -> list[tuple]:
    """(kind, count, p50, p95, p99, max) over events carrying dur_ms,
    plus derived ``req.submit->deliver`` spans joined by request key."""
    by_kind: dict[str, list] = {}
    for ev in events:
        if "dur_ms" in ev:
            by_kind.setdefault(ev["kind"], []).append(ev["dur_ms"])
    # derived submit→deliver per (node, key): first submit-ish stamp to
    # first deliver stamp — the request's protocol-pipeline span
    first_seen: dict[tuple, float] = {}
    derived: list = []
    for ev in events:
        key = ev.get("key")
        if not key:
            continue
        ident = (ev.get("node", ""), key)
        if ev["kind"] in ("req.submit", "req.pool") \
                and ident not in first_seen:
            first_seen[ident] = ev["t"]
        elif ev["kind"] == "req.deliver" and ident in first_seen:
            derived.append((ev["t"] - first_seen.pop(ident)) * 1e3)
    if derived:
        by_kind["req.submit->deliver"] = derived
    rows = []
    for kind in sorted(by_kind):
        vals = sorted(by_kind[kind])
        rows.append((kind, len(vals), _pct(vals, 0.50), _pct(vals, 0.95),
                     _pct(vals, 0.99), vals[-1]))
    return rows


def render(dumps: list[dict], *, last: Optional[int] = None,
           summary_only: bool = False) -> str:
    """Merged text timeline + per-span-type percentile summary."""
    events: list[dict] = []
    for d in dumps:
        node = d.get("node", "")
        for ev in d.get("events", []):
            if node and "node" not in ev:
                ev = dict(ev, node=node)
            events.append(ev)
    events.sort(key=lambda e: e.get("t", 0.0))
    if last is not None and last >= 0:
        events = events[-last:] if last else []
    out: list[str] = []
    header = (f"flight recorder: {len(dumps)} dump(s), "
              f"{len(events)} event(s)"
              + (f", dropped {sum(d.get('dropped', 0) for d in dumps)}"
                 if any(d.get("dropped") for d in dumps) else ""))
    out.append(header)
    if events and not summary_only:
        t0 = events[0].get("t", 0.0)
        out.append("")
        out.append("timeline:")
        out.extend("  " + _fmt_event(ev, t0) for ev in events)
    rows = _summary_rows(events)
    if rows:
        out.append("")
        out.append("span summary (ms):")
        out.append(f"  {'kind':<24} {'count':>6} {'p50':>10} {'p95':>10} "
                   f"{'p99':>10} {'max':>10}")
        for kind, n, p50, p95, p99, mx in rows:
            out.append(f"  {kind:<24} {n:>6} {p50:>10.3f} {p95:>10.3f} "
                       f"{p99:>10.3f} {mx:>10.3f}")
    return "\n".join(out) + "\n"


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Render SmartBFT flight-recorder dumps as a text "
                    "timeline + per-span-type percentile summary"
    )
    ap.add_argument("dumps", nargs="+", help="flight-recorder JSON dump(s)")
    ap.add_argument("--last", type=int, default=None,
                    help="only the newest N merged events")
    ap.add_argument("--summary-only", action="store_true",
                    help="skip the timeline, print only the span summary")
    args = ap.parse_args(argv)
    dumps = [load_dump(p) for p in args.dumps]
    print(render(dumps, last=args.last, summary_only=args.summary_only),
          end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
