"""Declarative SLOs with Google-SRE multi-window burn-rate evaluation.

The observability PRs gave the system eyes (flight recorder, merged
cluster timelines, per-request critical paths) but no *judgment*: nothing
machine-readable said whether what the instruments measure is acceptable.
This module is the judgment layer's bottom half:

* :class:`SLORule` — one declarative objective over a named **signal**
  (a float the health plane samples each tick: per-phase p99s, shed
  rates, mesh fill, view-change detection time, backlog at the view
  flip, WAL fsync latency).  A rule bounds the signal with a ceiling or
  a floor, carries a ``degraded`` bound and an optional ``critical``
  bound, and an **error budget**: the fraction of samples allowed to
  violate the bound before the objective is considered breached.

* :class:`SLOEvaluator` — evaluates the rules with the multi-window
  burn-rate method (Google SRE workbook, ch. 5): a rule only breaches
  when the budget burn rate is >= 1 in BOTH a fast window (catches the
  incident quickly, clears quickly on recovery) and a slow window
  (ignores one-sample blips), so transient noise cannot flap the
  verdict.  The clock is injectable — logical ``Scheduler.now`` in
  deterministic tests, ``time.monotonic`` in live replicas — the same
  idiom as :class:`~smartbft_tpu.metrics.CommitLatencyTracker` and the
  flight recorder.

Memory is bounded: each rule keeps only the samples inside its slow
window (older samples are dropped on observe), and a sample is two
floats.  Signals absent from an observation contribute no sample — a
rule over a surface the embedder did not wire simply never breaches.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

__all__ = [
    "SLORule",
    "SLOSpec",
    "SLOEvaluator",
    "default_slo_spec",
    "HEALTHY",
    "DEGRADED",
    "CRITICAL",
]

HEALTHY = "healthy"
DEGRADED = "degraded"
CRITICAL = "critical"

#: verdict severity order (index = badness)
STATUS_ORDER = (HEALTHY, DEGRADED, CRITICAL)


def worse(a: str, b: str) -> str:
    """The worse of two verdict statuses."""
    return a if STATUS_ORDER.index(a) >= STATUS_ORDER.index(b) else b


@dataclass(frozen=True)
class SLORule:
    """One declarative objective over a named signal.

    ``kind`` is ``"ceiling"`` (signal must stay at or below ``bound``)
    or ``"floor"`` (at or above — mesh fill, goodput).  ``critical_bound``
    (optional) is a second, worse bound whose breach escalates the
    verdict to ``critical``.  ``budget`` is the allowed violating-sample
    fraction per window (the error budget); ``fast_window_s`` /
    ``slow_window_s`` are the two burn-rate windows."""

    name: str
    signal: str
    bound: float
    kind: str = "ceiling"  # "ceiling" | "floor"
    critical_bound: Optional[float] = None
    budget: float = 0.01
    fast_window_s: float = 5.0
    slow_window_s: float = 60.0
    description: str = ""

    def violates(self, value: float, bound: Optional[float] = None) -> bool:
        b = self.bound if bound is None else bound
        return value > b if self.kind == "ceiling" else value < b

    def validate(self) -> None:
        if self.kind not in ("ceiling", "floor"):
            raise ValueError(f"SLO {self.name}: kind must be ceiling|floor")
        if not (0.0 < self.budget <= 1.0):
            raise ValueError(f"SLO {self.name}: budget must be in (0, 1]")
        if self.fast_window_s <= 0 or self.slow_window_s <= 0:
            raise ValueError(f"SLO {self.name}: windows must be positive")
        if self.fast_window_s > self.slow_window_s:
            raise ValueError(
                f"SLO {self.name}: fast window exceeds slow window"
            )
        if self.critical_bound is not None:
            if self.kind == "ceiling" and self.critical_bound < self.bound:
                raise ValueError(
                    f"SLO {self.name}: critical ceiling below degraded one"
                )
            if self.kind == "floor" and self.critical_bound > self.bound:
                raise ValueError(
                    f"SLO {self.name}: critical floor above degraded one"
                )


@dataclass(frozen=True)
class SLOSpec:
    """A named set of rules — the service's whole objective sheet."""

    name: str = "default"
    rules: tuple = ()

    def validate(self) -> None:
        seen: set[str] = set()
        for r in self.rules:
            r.validate()
            if r.name in seen:
                raise ValueError(f"duplicate SLO rule name {r.name!r}")
            seen.add(r.name)

    def rule(self, name: str) -> Optional[SLORule]:
        return next((r for r in self.rules if r.name == name), None)

    def with_overrides(self, **bounds: float) -> "SLOSpec":
        """A copy with per-rule bound overrides (``{rule_name: bound}``)
        — how a chaos/soak harness tightens the production spec to its
        own timescale without redeclaring it."""
        rules = tuple(
            replace(r, bound=bounds[r.name]) if r.name in bounds else r
            for r in self.rules
        )
        return replace(self, rules=rules)


def default_slo_spec(*, fast_window_s: float = 5.0,
                     slow_window_s: float = 60.0) -> SLOSpec:
    """The service's default objective sheet, grounded in the measured
    rounds: detection time and backlog-at-flip are ROADMAP item 1's
    gauges (round 16 measured 21.8 s detections and 160-deep flip
    backlogs under the mute), pool fill and shed pressure are the PR 8
    admission surface, WAL fsync is the durability budget, mesh fill the
    PR 11 wave-deepening floor.  Bounds are production aspirations, not
    descriptions of today: a healthy cluster emits none of the failure
    signals, and a failing one is judged against where the roadmap says
    it must land (sub-second detection, bounded backlog)."""
    w = {"fast_window_s": fast_window_s, "slow_window_s": slow_window_s}
    return SLOSpec(name="default", rules=(
        SLORule(
            name="viewchange.detection_seconds",
            signal="viewchange.detection_seconds",
            bound=1.0, critical_bound=30.0, kind="ceiling", **w,
            description="complain-timer arm-to-fire on a leader failure "
                        "(ROADMAP 1: sub-second failover detection)",
        ),
        SLORule(
            name="viewchange.backlog_at_flip",
            signal="viewchange.backlog_at_flip",
            bound=64.0, kind="ceiling", **w,
            description="request-pool depth at the view flip (the stalled "
                        "work the new view must drain)",
        ),
        SLORule(
            name="viewchange.active_seconds",
            signal="viewchange.active_seconds",
            bound=2.0, critical_bound=60.0, kind="ceiling", **w,
            description="wall/logical seconds the current view change has "
                        "been open (armed and not yet completed)",
        ),
        SLORule(
            name="pool.fill",
            signal="pool.fill",
            bound=0.9, critical_bound=1.0, kind="ceiling", budget=0.2, **w,
            description="request-pool occupancy fraction (sustained "
                        "near-capacity fill precedes shedding)",
        ),
        SLORule(
            name="pool.shed_recent",
            signal="pool.shed_recent",
            bound=0.0, kind="ceiling", budget=0.2, **w,
            description="1.0 while the admission gate shed requests within "
                        "the recent window (client-visible overload)",
        ),
        SLORule(
            name="latency.commit_p99_ms",
            signal="latency.commit_p99_ms",
            bound=2000.0, critical_bound=30000.0, kind="ceiling", **w,
            description="submit->commit p99 over the live tracker window",
        ),
        SLORule(
            name="verify.breaker_open",
            signal="verify.breaker_open",
            bound=0.0, kind="ceiling", budget=0.2, **w,
            description="1.0 while the verify plane serves on the host "
                        "fallback (device outage; degraded by definition)",
        ),
        SLORule(
            name="mesh.device_fill_pct",
            signal="mesh.device_fill_pct",
            bound=10.0, kind="floor", budget=0.5, **w,
            description="minimum per-device fill of mesh launches (a "
                        "starved mesh wastes its devices)",
        ),
        SLORule(
            name="wal.fsync_p99_ms",
            signal="wal.fsync_p99_ms",
            bound=250.0, critical_bound=2000.0, kind="ceiling", **w,
            description="group-commit fsync p99 (the durability budget)",
        ),
        SLORule(
            name="snapshot.lag_intervals",
            signal="snapshot.lag_intervals",
            bound=3.0, critical_bound=10.0, kind="ceiling", budget=0.2, **w,
            description="decisions since the last snapshot, in units of the "
                        "configured snapshot interval (ISSUE 17: the "
                        "disk-bound objective — a stuck capture loop lets "
                        "the ledger/WAL prefix grow without bound; only "
                        "emitted when snapshots are enabled, so replicas "
                        "running without compaction never breach it)",
        ),
        SLORule(
            name="read.shed_recent",
            signal="read.shed_recent",
            bound=0.0, kind="ceiling", budget=0.2, **w,
            description="1.0 while the read gate shed reads within the "
                        "recent window (ISSUE 19: a read storm being "
                        "absorbed — degraded for readers, and proof the "
                        "storm never reached the write path)",
        ),
        SLORule(
            name="read.base_refused_recent",
            signal="read.base_refused_recent",
            bound=0.0, critical_bound=0.5, kind="ceiling", budget=0.2, **w,
            description="1.0 while a snapshot-anchored read was refused "
                        "over a torn/tampered base within the window — an "
                        "integrity event, critical on repetition",
        ),
        SLORule(
            name="read.staleness_decisions",
            signal="read.staleness_decisions",
            bound=1024.0, critical_bound=8192.0, kind="ceiling",
            budget=0.2, **w,
            description="worst anchor lag (decisions behind the live "
                        "frontier) served by snapshot-anchored reads while "
                        "they are actively landing — bounded by the capture "
                        "cadence on a healthy replica",
        ),
    ))


class _RuleState:
    __slots__ = ("rule", "samples")

    def __init__(self, rule: SLORule):
        self.rule = rule
        #: (t, value) samples inside the slow window, oldest first
        self.samples: deque = deque()


@dataclass
class SLOBreach:
    """One breached rule in a verdict, with its burn evidence."""

    slo: str
    severity: str
    value: float
    bound: float
    burn_fast: float
    burn_slow: float

    def as_dict(self) -> dict:
        return {
            "slo": self.slo,
            "severity": self.severity,
            "value": round(self.value, 4),
            "bound": self.bound,
            "burn_fast": round(self.burn_fast, 2),
            "burn_slow": round(self.burn_slow, 2),
        }


@dataclass
class SLOVerdict:
    status: str = HEALTHY
    breaches: list = field(default_factory=list)

    @property
    def reasons(self) -> list[str]:
        return [b.slo for b in self.breaches]

    def as_dict(self) -> dict:
        return {
            "status": self.status,
            "reasons": [b.as_dict() for b in self.breaches],
        }


class SLOEvaluator:
    """Samples signals against a spec and renders burn-rate verdicts.

    ``observe(signals)`` appends one sample per rule whose signal is
    present; ``evaluate()`` computes per-rule budget burn over the fast
    and slow windows and returns the :class:`SLOVerdict` (breached rules
    ranked worst burn first).  Stateless consumers call
    ``observe`` + ``evaluate`` from one tick loop; everything is O(rules
    x window samples) with windows bounded by time."""

    def __init__(self, spec: SLOSpec, *, clock=None):
        spec.validate()
        self.spec = spec
        self._clock = clock if clock is not None else time.monotonic
        self._states = {r.name: _RuleState(r) for r in spec.rules}
        self.observations = 0

    def observe(self, signals: dict, t: Optional[float] = None) -> None:
        now = self._clock() if t is None else t
        self.observations += 1
        for st in self._states.values():
            value = signals.get(st.rule.signal)
            if value is None:
                continue
            st.samples.append((now, float(value)))
            horizon = now - st.rule.slow_window_s
            while st.samples and st.samples[0][0] < horizon:
                st.samples.popleft()

    @staticmethod
    def _burn(rule: SLORule, samples: Sequence, now: float,
              window: float, bound: float) -> tuple[float, float]:
        """(burn, worst_violating_value) over the trailing ``window``:
        burn = violating-sample fraction / error budget."""
        lo = now - window
        total = violating = 0
        worst: Optional[float] = None
        for t, v in samples:
            if t < lo:
                continue
            total += 1
            if rule.violates(v, bound):
                violating += 1
                if worst is None:
                    worst = v
                elif rule.kind == "ceiling":
                    worst = max(worst, v)
                else:
                    worst = min(worst, v)
        if not total:
            return 0.0, 0.0
        return (violating / total) / rule.budget, (worst or 0.0)

    def evaluate(self, t: Optional[float] = None) -> SLOVerdict:
        now = self._clock() if t is None else t
        breaches: list[SLOBreach] = []
        for st in self._states.values():
            rule = st.rule
            if not st.samples:
                continue
            # fast window first: in the healthy steady state it misses,
            # and the slow-window sweep (the expensive one) is skipped
            fast, worst_f = self._burn(rule, st.samples, now,
                                       rule.fast_window_s, rule.bound)
            if fast < 1.0:
                continue
            slow, _ = self._burn(rule, st.samples, now,
                                 rule.slow_window_s, rule.bound)
            if slow < 1.0:
                continue
            severity = DEGRADED
            if rule.critical_bound is not None:
                cfast, cworst = self._burn(rule, st.samples, now,
                                           rule.fast_window_s,
                                           rule.critical_bound)
                cslow, _ = self._burn(rule, st.samples, now,
                                      rule.slow_window_s,
                                      rule.critical_bound)
                if cfast >= 1.0 and cslow >= 1.0:
                    severity = CRITICAL
                    worst_f = cworst
            breaches.append(SLOBreach(
                slo=rule.name, severity=severity, value=worst_f,
                bound=rule.bound, burn_fast=fast, burn_slow=slow,
            ))
        breaches.sort(key=lambda b: (b.severity != CRITICAL, -b.burn_fast))
        status = HEALTHY
        for b in breaches:
            status = worse(status, b.severity)
        return SLOVerdict(status=status, breaches=breaches)
