"""View-change sub-phase decomposition: where do the seconds go?

PERF round 12 crowned the forced view change the worst failure mode
(p99 21x healthy, the only phase that sheds) — but nothing could say
WHERE inside the complain → depose → ViewData → new-view pipeline the
time went.  :class:`ViewChangePhaseTracker` is that instrument: the
ViewChanger and Controller mark the pipeline's transition points on one
injectable clock, and every completed view change yields a per-phase
breakdown whose phase durations SUM to its end-to-end duration by
construction (consecutive deltas on one clock), so the decomposition
can never silently disagree with the total it explains.

Phase vocabulary (each phase is the interval ENDING at its mark):

==================  =====================================================
``complain``        complain armed (this node started/joined a view
                    change) → complaint quorum reached (node commits to
                    the next view)
``depose``          quorum → ViewData prepared + sent to the new leader
                    (includes aborting the current view)
``viewdata_collect``  (new leader only) ViewData sent → quorum of
                    ViewData collected and the in-flight check passed
``newview``         ViewData sent/collected → NewView validated and the
                    NewViewRecord persisted (includes committing agreed
                    in-flight rungs)
``first_commit``    new view installed → first decision delivered in it
==================  =====================================================

Memory is bounded: one in-flight mark set, a ``keep``-deep deque of raw
per-VC records (the bench block's input), and fixed-bucket histograms.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional, Sequence

from ..metrics import LogScaleHistogram
from .recorder import NOP_RECORDER, pct as _pct

__all__ = ["ViewChangePhaseTracker", "assemble_viewchange_block"]

#: mark -> the phase name of the interval that ENDS at this mark, in
#: pipeline order (missing marks skip; the next present mark's phase
#: absorbs the interval, keeping sum == total)
_MARK_PHASE = (
    ("joined", "complain"),
    ("viewdata_sent", "depose"),
    ("viewdata_quorum", "viewdata_collect"),
    ("newview", "newview"),
)

PHASES = tuple(p for _, p in _MARK_PHASE) + ("first_commit",)


class ViewChangePhaseTracker:
    """Per-node view-change sub-phase clock.  One instance per Consensus
    (it outlives reconfig-rebuilt ViewChangers), fed by the ViewChanger's
    transition points and closed by the Controller's first delivery in
    the new view."""

    def __init__(self, *, clock=None, node: str = "", recorder=None,
                 metrics=None, keep: int = 64):
        self._clock = clock if clock is not None else time.monotonic
        self.node = node
        self.recorder = recorder if recorder is not None else NOP_RECORDER
        #: optional ViewChangeMetrics bundle — the time-in-view-change
        #: gauge and round counter feed it so Prometheus/statsd see VC
        #: health without the trace enabled
        self.metrics = metrics
        self.open = False
        self._view = -1
        self._marks: dict[str, float] = {}
        self.rounds = 0
        self.abandoned = 0
        self.completed_total = 0
        #: raw per-VC records (bounded) — the assemble block's input
        self._records: deque = deque(maxlen=max(int(keep), 1))
        self.spans = {p: LogScaleHistogram() for p in PHASES}
        self.total_hist = LogScaleHistogram()
        #: heartbeat-timeout arm-to-fire samples (ms, bounded) — the
        #: DETECTION latency round 15 blamed for ~99% of the VC cliff,
        #: now a first-class column of the viewchange bench block
        self._detections: deque = deque(maxlen=max(int(keep), 1))
        self.detections_total = 0
        #: the latest EFFECTIVE complain-timer derivation (ISSUE 15):
        #: {timeout_s, rtt_s, commit_interval_s, backoff_round} — one
        #: dict, overwritten in place by the heartbeat monitor so the
        #: bench block publishes what the timer actually was
        self.effective_timer: Optional[dict] = None
        #: hot-standby ViewData accounting (ISSUE 15): prebuilds the
        #: next-leader tick produced, and cache hits at ViewData-send
        #: time (a hit = the one-round-trip failover path was taken)
        self.standby_prebuilds = 0
        self.standby_hits = 0

    # -- marks (ViewChanger) ----------------------------------------------

    def armed(self, next_view: int) -> None:
        """This node started (or joined) a view change toward
        ``next_view``.  A re-arm toward a HIGHER view while one is open
        is a new round (timeout escalation): the stale round is counted
        abandoned, its partial marks discarded."""
        if self.open:
            if next_view <= self._view:
                return  # duplicate arm of the same round
            self._abandon("re-armed")
        self.open = True
        self._view = next_view
        self._marks = {"armed": self._clock()}
        self.rounds += 1
        if self.metrics is not None:
            self.metrics.count_view_change_rounds.add(1)
        rec = self.recorder
        if rec.enabled:
            rec.record("vc.armed", node=self.node, view=next_view)

    def detection(self, seconds: float) -> None:
        """A heartbeat/complain timer FIRED after ``seconds`` of armed
        silence (HeartbeatMonitor hook).  No tracing required: the sample
        feeds the viewchange metrics bundle (gauge + counter) and the
        bounded pool the bench block summarizes."""
        ms = max(seconds, 0.0) * 1e3
        self._detections.append(ms)
        self.detections_total += 1
        if self.metrics is not None:
            self.metrics.heartbeat_detection_seconds.set(max(seconds, 0.0))
            self.metrics.count_heartbeat_timeouts.add(1)
        rec = self.recorder
        if rec.enabled:
            rec.record("vc.detected", node=self.node, dur=max(seconds, 0.0))

    def note_effective_timer(self, timeout_s: float, rtt_s: float,
                             commit_interval_s: float,
                             backoff_round: int) -> None:
        """The heartbeat monitor's current effective complain timer and
        its inputs (ISSUE 15 satellite) — overwritten in place, O(1)."""
        self.effective_timer = {
            "timeout_s": round(timeout_s, 6),
            "rtt_s": round(rtt_s, 6),
            "commit_interval_s": round(commit_interval_s, 6),
            "backoff_round": backoff_round,
        }

    def note_standby(self, prebuilt: bool = False, hit: bool = False) -> None:
        """Hot-standby ViewData accounting (ISSUE 15)."""
        if prebuilt:
            self.standby_prebuilds += 1
        if hit:
            self.standby_hits += 1

    def _mark(self, name: str, kind: str, view: int) -> None:
        if not self.open or view < self._view or name in self._marks:
            return
        self._marks[name] = self._clock()
        rec = self.recorder
        if rec.enabled:
            rec.record(kind, node=self.node, view=self._view)

    def joined(self, view: int) -> None:
        """Complaint quorum reached; the node committed to the next view."""
        self._mark("joined", "vc.quorum", view)

    def viewdata_sent(self, view: int) -> None:
        self._mark("viewdata_sent", "vc.viewdata_sent", view)

    def viewdata_quorum(self, view: int) -> None:
        """(New leader) quorum of ViewData validated; NewView going out."""
        self._mark("viewdata_quorum", "vc.viewdata_quorum", view)

    def newview_done(self, view: int) -> None:
        self._mark("newview", "vc.newview", view)

    # -- closure (Controller) ---------------------------------------------

    def decision(self, view: int, backlog: int = -1) -> None:
        """A decision delivered; the first one at/after the VC's view with
        the NewView processed closes the open round as COMPLETED.
        ``backlog`` (when >= 0) is the caller's request-pool depth at the
        flip — the stalled work the new view must drain, the other half
        of the round-15 cliff."""
        if not self.open or "newview" not in self._marks \
                or view < self._view:
            return
        now = self._clock()
        marks = self._marks
        t0 = marks["armed"]
        phases: dict[str, float] = {}
        prev = t0
        for mark, phase in _MARK_PHASE:
            t = marks.get(mark)
            if t is None:
                continue
            phases[phase] = max(t - prev, 0.0)
            prev = t
        phases["first_commit"] = max(now - prev, 0.0)
        total = max(now - t0, 0.0)
        for phase, dt in phases.items():
            self.spans[phase].observe(dt)
        self.total_hist.observe(total)
        self.completed_total += 1
        record = {
            "view": self._view,
            "node": self.node,
            "total_ms": round(total * 1e3, 3),
            "phases": {p: round(dt * 1e3, 3) for p, dt in phases.items()},
        }
        if backlog >= 0:
            record["backlog_at_flip"] = backlog
        self._records.append(record)
        if self.metrics is not None:
            self.metrics.time_in_view_change.set(total)
            if backlog >= 0:
                self.metrics.backlog_at_view_flip.set(backlog)
        rec = self.recorder
        if rec.enabled:
            rec.record("vc.complete", node=self.node, view=self._view,
                       dur=total,
                       extra={p: round(dt * 1e3, 3)
                              for p, dt in phases.items()})
        self.open = False
        self._marks = {}

    def abandoned_by_sync(self, view: int) -> None:
        """A sync/inform installed the new view around the VC protocol —
        the open round never completed through the pipeline."""
        if self.open and view >= self._view:
            self._abandon("sync")

    def timeout_escalated(self) -> None:
        """The view-change timeout fired: the ViewChanger is forcing a
        sync and RESTARTING the round (viewchanger.go:254-270 backoff
        escalation).  The open round is recycled — count it abandoned so
        its stale marks cannot keep reading as a still-in-progress view
        change (a restarted replica that restored a moot VC round would
        otherwise report viewchange.active_seconds growing forever)."""
        if self.open:
            self._abandon("timeout")

    def _abandon(self, reason: str) -> None:
        self.abandoned += 1
        rec = self.recorder
        if rec.enabled:
            rec.record("vc.abandoned", node=self.node, view=self._view,
                       extra={"reason": reason})
        self.open = False
        self._marks = {}

    def note_tick(self) -> None:
        """Tick hook: keep the time-in-view-change gauge live while a
        round is open (it freezes at the total on completion)."""
        if self.open and self.metrics is not None:
            self.metrics.time_in_view_change.set(
                max(self._clock() - self._marks["armed"], 0.0)
            )

    # -- reading -----------------------------------------------------------

    def records(self) -> list[dict]:
        return list(self._records)

    def snapshot(self) -> dict:
        return {
            "completed": self.completed_total,
            "rounds": self.rounds,
            "abandoned": self.abandoned,
            "open": self.open,
            "phases": {p: h.snapshot() for p, h in self.spans.items()},
            "total": self.total_hist.snapshot(),
            "last": self._records[-1] if self._records else None,
        }


def _timer_block(trackers: Sequence["ViewChangePhaseTracker"]) -> dict:
    """Fold the per-node effective-timer derivations into one summary."""
    samples = [t.effective_timer for t in trackers
               if getattr(t, "effective_timer", None)]
    if not samples:
        return {"derived": False}
    timeouts = [s["timeout_s"] for s in samples]
    return {
        "derived": True,
        "nodes": len(samples),
        "timeout_s_min": min(timeouts),
        "timeout_s_max": max(timeouts),
        "rtt_s_max": max(s["rtt_s"] for s in samples),
        "commit_interval_s_max": max(s["commit_interval_s"]
                                     for s in samples),
        "backoff_round_max": max(s["backoff_round"] for s in samples),
    }


def assemble_viewchange_block(trackers: Sequence["ViewChangePhaseTracker"]
                              ) -> dict:
    """Fold N per-node trackers into the ONE ``viewchange`` block a bench
    row carries (pure function, PR 8 idiom).  Percentiles are EXACT over
    the pooled raw per-VC records (VCs are rare, the records are bounded
    deques), so the published decomposition is the measured distribution,
    not a merge of approximations.  ``sums_consistent`` pins the
    instrument's core promise: every record's phase durations sum to its
    end-to-end total (worst residual reported in ms)."""
    recs = [r for t in trackers for r in t.records()]
    totals = sorted(r["total_ms"] for r in recs)
    per_phase: dict[str, list] = {p: [] for p in PHASES}
    worst_residual = 0.0
    for r in recs:
        for p, ms in r["phases"].items():
            per_phase.setdefault(p, []).append(ms)
        worst_residual = max(
            worst_residual,
            abs(sum(r["phases"].values()) - r["total_ms"]),
        )
    phases = {}
    sum_total = sum(totals)
    mean_total = (sum_total / len(totals)) if totals else 0.0
    for p, vals in per_phase.items():
        vals.sort()
        mean = (sum(vals) / len(vals)) if vals else 0.0
        phases[p] = {
            "count": len(vals),
            "p50_ms": round(_pct(vals, 0.50), 3),
            "p95_ms": round(_pct(vals, 0.95), 3),
            "p99_ms": round(_pct(vals, 0.99), 3),
            "max_ms": round(vals[-1], 3) if vals else 0.0,
            "mean_ms": round(mean, 3),
            # the decomposition column PERF round 15 publishes: the
            # fraction of ALL measured view-change time spent in this
            # phase (shares sum to ~1 across phases, modulo residual)
            "share": round(sum(vals) / sum_total, 3) if sum_total else 0.0,
        }
    dominant = max(
        (p for p in phases if phases[p]["count"]),
        key=lambda p: phases[p]["share"], default=None,
    )
    detections = sorted(d for t in trackers
                        for d in getattr(t, "_detections", ()))
    backlogs = sorted(r["backlog_at_flip"] for r in recs
                      if "backlog_at_flip" in r)
    return {
        "count": len(recs),
        "rounds": sum(t.rounds for t in trackers),
        "abandoned": sum(t.abandoned for t in trackers),
        # ROADMAP item 1 gauges: complain-timer arm-to-fire time (the
        # detection latency that precedes every armed round) and the
        # per-replica pool backlog at the view flip (the stalled work the
        # new view drains) — both measured, no tracing required
        "detection": {
            "count": sum(getattr(t, "detections_total", 0)
                         for t in trackers),
            "p50_ms": round(_pct(detections, 0.50), 3),
            "p95_ms": round(_pct(detections, 0.95), 3),
            "p99_ms": round(_pct(detections, 0.99), 3),
            "max_ms": round(detections[-1], 3) if detections else 0.0,
        },
        "backlog_at_flip": {
            "count": len(backlogs),
            "p50": _pct(backlogs, 0.50),
            "max": backlogs[-1] if backlogs else 0,
        },
        # ISSUE 15: the effective (derived) complain timer across the
        # pooled trackers — min/max of the last per-node derivations plus
        # the worst backoff round — and the hot-standby ViewData cache
        # accounting (hits = view changes that took the one-round-trip
        # prebuilt path)
        "timer": _timer_block(trackers),
        "standby": {
            "prebuilds": sum(getattr(t, "standby_prebuilds", 0)
                             for t in trackers),
            "hits": sum(getattr(t, "standby_hits", 0) for t in trackers),
        },
        "end_to_end": {
            "count": len(totals),
            "p50_ms": round(_pct(totals, 0.50), 3),
            "p95_ms": round(_pct(totals, 0.95), 3),
            "p99_ms": round(_pct(totals, 0.99), 3),
            "max_ms": round(totals[-1], 3) if totals else 0.0,
            "mean_ms": round(mean_total, 3),
        },
        "phases": phases,
        "dominant_phase": dominant,
        "sums_consistent": worst_residual <= 0.005,
        "worst_residual_ms": round(worst_residual, 4),
    }
