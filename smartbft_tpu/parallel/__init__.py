"""Device-mesh parallelism for the crypto plane.

The reference scales by adding replicas (one process each); its only
in-process parallelism is goroutine fan-out per signature
(/root/reference/internal/bft/view.go:537-541).  Here the same work is data
parallel over kernel lanes, so it shards over a TPU pod slice with
`jax.sharding` — no NCCL/MPI analog needed: XLA inserts the collectives.

Two products:

* :class:`ShardedVerifyEngine` — a drop-in verify engine (same surface as
  ``JaxVerifyEngine``) that annotates the batch axis with a 1D 'lane' mesh
  sharding; XLA partitions the vmap'd kernel across devices with zero
  communication (verification is embarrassingly parallel until the final
  host-side mask read).
* :func:`quorum_decide` — the 2D (seq x vote) quorum step: each device
  verifies its (sequences, votes) tile, vote counts reduce with a `psum`
  over the 'vote' axis, and the decided mask shards over 'seq'.  This is
  the flagship multi-chip step `__graft_entry__.dryrun_multichip` compiles.
* :class:`QuorumMeshVerifyEngine` — that quorum step as a LIVE verify
  engine (ISSUE 11): selectable through ``Configuration.
  verify_mesh_topology = "2d"`` on the same ``verify_mesh_devices`` knob
  path as :class:`MeshVerifyEngine`, with per-item verdicts bit-identical
  to the 1D engine and per-sequence vote counts psum'd on device.
"""

from .engine import (
    MeshUnavailable,
    MeshVerifyEngine,
    QuorumMeshVerifyEngine,
    ShardedVerifyEngine,
    build_mesh,
    mesh_device_count,
    quorum_decide,
    shard_map_available,
)

__all__ = [
    "MeshUnavailable",
    "MeshVerifyEngine",
    "QuorumMeshVerifyEngine",
    "ShardedVerifyEngine",
    "build_mesh",
    "mesh_device_count",
    "quorum_decide",
    "shard_map_available",
]
