"""Mesh-sharded signature verification and the distributed quorum step.

Design notes (TPU-first):

* Verification lanes are independent — the ideal SPMD workload.  The
  engine pads each batch to a lane count divisible by the mesh and places
  inputs with ``NamedSharding(mesh, P('lane'))``; ``jax.jit`` then
  partitions the whole kernel body across devices without any hand-written
  collectives.
* The quorum step is the one place a cross-device reduction exists: vote
  counts sum over the 'vote' mesh axis (``lax.psum`` riding ICI), the
  cheapest possible collective (one scalar per in-flight sequence).
* Both paths reuse the scheme modules' single-chip kernels unchanged —
  sharding is an annotation, not a rewrite.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..crypto import p256
from ..crypto.provider import JaxVerifyEngine, MeshVerifyStats


#: one-shot memo for the shard_map probe: [wrapper-or-None] once resolved.
#: The fallback-import dance (attr walk + jax.experimental import attempt)
#: used to re-run on EVERY engine construction; the answer is a property
#: of the jax build and cannot change within a process, so it is cached —
#: and exported into the metrics ``mesh`` block (shard_map_available) so
#: bench rows record which path actually ran.
_SHARD_MAP_MEMO: list = []


def _probe_shard_map():
    """The raw probe (see :func:`resolve_shard_map`); runs at most once."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        try:
            from jax.experimental.shard_map import shard_map as sm
        except Exception:
            return None

    def call(f, *, mesh, in_specs, out_specs):
        try:
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)
        except TypeError:  # older spelling
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)

    return call


def resolve_shard_map(required: bool = False):
    """The usable shard_map entry point of this jax build, or None.

    jax graduated ``jax.experimental.shard_map.shard_map`` (replication
    check spelled ``check_rep``) to top-level ``jax.shard_map``
    (``check_vma``); container images pin various points of that timeline.
    Returns a uniform ``call(f, mesh=, in_specs=, out_specs=)`` wrapper
    with the replication/varying-manual-axes check disabled (the bignum
    carry-chain scans initialize carries from unvarying constants, which
    the checker rejects).  When neither API exists: returns None, or with
    ``required=True`` raises the capability error — callers either gate on
    :func:`shard_map_available` or demand it outright.

    Memoized: the probe runs once per process (the answer is fixed by the
    jax build); repeated engine constructions reuse the cached wrapper.
    """
    if not _SHARD_MAP_MEMO:
        _SHARD_MAP_MEMO.append(_probe_shard_map())
    call = _SHARD_MAP_MEMO[0]
    if call is None and required:
        raise RuntimeError(
            "no usable shard_map API in this jax build (neither "
            "jax.shard_map nor jax.experimental.shard_map)"
        )
    return call


def shard_map_available() -> bool:
    """Capability probe for the mesh quorum step (tests skip-gate on it)."""
    return resolve_shard_map() is not None


def build_mesh(shape: Optional[tuple[int, ...]] = None,
               axis_names: tuple[str, ...] = ("lane",),
               devices=None):
    """A `jax.sharding.Mesh` over the first prod(shape) devices.

    Default: all visible devices on a 1D 'lane' axis.  For the quorum step
    pass ``shape=(seq_par, vote_par)`` and ``axis_names=('seq', 'vote')``.
    """
    import jax

    devices = list(jax.devices() if devices is None else devices)
    if shape is None:
        shape = (len(devices),)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, "
                         f"have {len(devices)}")
    from jax.sharding import Mesh

    return Mesh(np.asarray(devices[:n]).reshape(shape), axis_names)


class ShardedVerifyEngine(JaxVerifyEngine):
    """`JaxVerifyEngine` with batch lanes sharded over a 1D device mesh.

    Same engine surface, so it plugs into ``CryptoProvider`` and the async
    coalescer unchanged.  Pad sizes are rounded up to multiples of the mesh
    size so every device gets equal, static tiles; padded inputs are placed
    with a lane sharding and XLA partitions the kernel.
    """

    # the fused Pallas kernel is single-device (no partitioning rules);
    # mesh-placed lanes must stay on the XLA kernel so jit partitions them
    supports_pallas = False

    def __init__(self, mesh=None,
                 pad_sizes: tuple[int, ...] = (64, 256, 1024), scheme=p256):
        from jax.sharding import NamedSharding, PartitionSpec

        self.mesh = mesh if mesh is not None else build_mesh()
        if len(self.mesh.axis_names) != 1:
            raise ValueError("ShardedVerifyEngine wants a 1D mesh; use "
                             "quorum_decide for 2D (seq x vote) meshes")
        self.lanes = int(np.prod(self.mesh.devices.shape))
        rounded = sorted({-(-s // self.lanes) * self.lanes for s in pad_sizes})
        super().__init__(pad_sizes=rounded, scheme=scheme)
        self._sharding = NamedSharding(
            self.mesh, PartitionSpec(self.mesh.axis_names[0])
        )

    def _place(self, a):
        return self._jax.device_put(a, self._sharding)


class MeshUnavailable(RuntimeError):
    """The configured verify mesh cannot be built on this host (fewer
    visible devices than requested).  The wiring seam
    (``CryptoProvider.configure_verify_mesh``) catches this and constructs
    the single-device engine LOUDLY with a counted downgrade — a
    mis-provisioned host degrades to reduced width instead of dying."""


def mesh_device_count() -> int:
    """Visible device count (0 when jax cannot initialize a backend)."""
    import jax

    try:
        return len(jax.devices())
    except Exception:  # noqa: BLE001 — capability probe, never fatal
        return 0


#: default per-device lane ladder for the graduated mesh engine: each
#: device contributes a fixed lane budget, so aggregate per-launch
#: capacity scales linearly with the mesh width (the whole point of
#: amortizing the ~fixed launch overhead across N devices)
MESH_PER_DEVICE_LANES = (8, 64, 512, 2048)


class MeshVerifyEngine(ShardedVerifyEngine):
    """The GRADUATED live-path mesh engine (ISSUE 10, ROADMAP item 1).

    Each coalesced wave is padded to a device-count multiple, partitioned
    along the batch axis with ``NamedSharding(mesh, P('batch'))`` (the
    SNIPPETS.md [1]/[2] idiom), and verified in ONE logical launch that
    spans the whole mesh; per-item verdicts gather back to the host and
    the coalescer slices them per submitter/tag exactly as on the
    single-device engine.  Construction raises :class:`MeshUnavailable`
    when the host has fewer visible devices than requested — the wiring
    seam turns that into a loud counted downgrade, never a crash.

    ``pad_sizes=None`` derives a ladder of ``MESH_PER_DEVICE_LANES`` lanes
    PER DEVICE, so per-launch capacity (``pad_sizes[-1]``) scales with the
    mesh width; an explicit ladder is rounded up to device multiples like
    any :class:`ShardedVerifyEngine`.  ``stats`` is a
    :class:`~smartbft_tpu.crypto.provider.MeshVerifyStats`: per-launch
    per-device fill and pad waste ride every record, exported through
    ``AsyncBatchCoalescer.mesh_snapshot`` into the bench ``mesh`` block.

    **Strided placement** (ISSUE 11 satellite): items round-robin over
    devices (item *j* lands in device ``j % D``'s tile) instead of
    filling devices front to back, so pad slots spread EVENLY — round 13
    measured one contiguous launch running 6 devices at 100 % and 2 at
    0 %; strided, per-device item counts differ by at most one.
    Verification lanes are independent, so the permutation cannot change
    any verdict; results un-permute before slicing, keeping the output
    bit-identical to the single-device engine.
    """

    #: bench/wiring marker: which mesh shape this engine runs (the 2D
    #: seq×vote engine says "2d"); configure_verify_mesh keys idempotence
    #: on (devices, topology)
    topology = "1d"

    def __init__(self, devices: Optional[int] = None, mesh=None,
                 pad_sizes: Optional[tuple[int, ...]] = None, scheme=p256,
                 metrics=None):
        if mesh is None:
            import jax

            avail = list(jax.devices())
            want = len(avail) if not devices else int(devices)
            if want < 1 or want > len(avail):
                raise MeshUnavailable(
                    f"verify mesh wants {want} device(s), host has "
                    f"{len(avail)}"
                )
            mesh = build_mesh((want,), ("batch",), devices=avail[:want])
        n_dev = int(np.prod(mesh.devices.shape))
        if pad_sizes is None:
            pad_sizes = tuple(l * n_dev for l in MESH_PER_DEVICE_LANES)
        super().__init__(mesh=mesh, pad_sizes=tuple(pad_sizes), scheme=scheme)
        #: mesh width — the attribute the wiring seam keys idempotence on
        #: (FaultyEngine delegates it, so a fault-wrapped mesh still reads
        #: as "already graduated")
        self.devices = self.lanes
        self.stats = MeshVerifyStats(devices=self.devices, metrics=metrics)

    def mesh_snapshot(self) -> dict:
        """JSON-able block: devices, per-launch fill per device, pad
        waste — the engine half of the bench ``mesh`` block."""
        out = self.stats.mesh_block(capacity=self.pad_sizes[-1])
        out["topology"] = self.topology
        return out

    def _verify_chunk(self, items) -> list[bool]:
        """Strided chunk verify: scatter item *j* to padded row
        ``(j % D) * per_dev + j // D`` — device *d*'s tile holds items
        ``d, d+D, d+2D, ...`` — run ONE mesh launch, then un-permute the
        mask back to submission order.  Pad rows stay zero (they verify
        False and are never read back)."""
        n = len(items)
        size = self._pad_to(n)
        d_count = self.devices
        per_dev = size // d_count
        idx = np.arange(n)
        rows = (idx % d_count) * per_dev + idx // d_count
        t0 = time.perf_counter()
        arrays = self.scheme.verify_inputs(items)

        def scatter(a):
            out = np.zeros((size,) + a.shape[1:], a.dtype)
            out[rows] = a
            return self._place(out)

        mask = np.asarray(self._kernel(*(scatter(a) for a in arrays)))
        dt = time.perf_counter() - t0
        counts = [len(range(d, n, d_count)) for d in range(d_count)]
        with self._lock:
            self.stats.record(n, size, dt, per_device=counts)
        return [bool(v) for v in mask[rows]]


class QuorumMeshVerifyEngine(JaxVerifyEngine):
    """2D (seq x vote) mesh engine: live cluster waves through the psum.

    A coalesced cluster flush holds commit votes for one or more in-flight
    sequences (each vote's message bytes identify its sequence).  This
    engine groups the flush into a (seq_tile x vote_tile) quorum block —
    one row per distinct message — and runs ONE sharded step per block:
    each device verifies its tile of the block, then weighted vote counts
    ``psum`` across the 'vote' mesh axis (the quorum-decision collective
    of :func:`quorum_decide`).  Per-item verdicts feed the protocol's
    certificate construction unchanged; the psum'd per-sequence counts are
    exposed via :attr:`last_counts` and checked against the host-side
    quorum decisions in CI.

    Padding cells replicate a real item of the same block with weight 0,
    so they cannot inflate counts and the compiled shape is static.

    GRADUATED into the live path (ISSUE 11 tentpole b): selectable
    through ``Configuration.verify_mesh_topology = "2d"`` via the same
    ``CryptoProvider.configure_verify_mesh`` seam as the 1D engine —
    construction from a ``devices`` count builds the (seq × vote) mesh
    (vote axis 2-wide on even widths), raises :class:`MeshUnavailable`
    on narrower hosts OR when this jax build has no usable shard_map
    (both downgrade loudly at the seam), and the PR 3
    deadline/retry/breaker/canary contract wraps ``verify`` per mesh
    launch exactly like the 1D engine's.
    """

    supports_pallas = False  # mesh-placed lanes stay on the XLA kernel
    topology = "2d"

    def __init__(self, devices: Optional[int] = None, mesh=None,
                 quorum: int = 3, seq_tile: int = 8,
                 vote_tile: int = 16, scheme=p256, metrics=None):
        if mesh is None:
            import jax

            avail = list(jax.devices())
            want = len(avail) if not devices else int(devices)
            if want < 1 or want > len(avail):
                raise MeshUnavailable(
                    f"2d verify mesh wants {want} device(s), host has "
                    f"{len(avail)}"
                )
            vote_par = 2 if want % 2 == 0 else 1
            mesh = build_mesh((want // vote_par, vote_par), ("seq", "vote"),
                              devices=avail[:want])
        if tuple(mesh.axis_names) != ("seq", "vote"):
            raise ValueError("QuorumMeshVerifyEngine wants a ('seq','vote') mesh")
        if resolve_shard_map() is None:
            raise MeshUnavailable(
                "2d verify mesh needs a shard_map API (neither jax.shard_map "
                "nor jax.experimental.shard_map is usable in this build)"
            )
        self.mesh = mesh
        seq_par, vote_par = (int(x) for x in mesh.devices.shape)
        self._seq_par, self._vote_par = seq_par, vote_par
        self.seq_tile = -(-seq_tile // seq_par) * seq_par
        self.vote_tile = -(-vote_tile // vote_par) * vote_par
        self.quorum = quorum
        super().__init__(pad_sizes=(self.seq_tile * self.vote_tile,),
                         scheme=scheme, metrics=metrics)
        #: mesh width — the attribute configure_verify_mesh keys
        #: idempotence on (together with ``topology``)
        self.devices = seq_par * vote_par
        self.stats = MeshVerifyStats(devices=self.devices, metrics=metrics)
        self._steps: dict[tuple[int, ...], object] = {}
        #: sharded quorum steps executed (each = one psum over 'vote')
        self.psum_steps = 0
        #: message bytes -> psum'd valid-vote count, from the last flush
        self.last_counts: dict[bytes, int] = {}
        #: message bytes -> count >= quorum, the mesh-side quorum decision
        self.last_decided: dict[bytes, bool] = {}

    def mesh_snapshot(self) -> dict:
        """The engine half of the bench ``mesh`` block (same schema as
        the 1D engine, plus the psum-step count)."""
        out = self.stats.mesh_block(capacity=self.pad_sizes[-1])
        out["topology"] = self.topology
        out["psum_steps"] = self.psum_steps
        return out

    def _build_step(self, ranks: tuple[int, ...]):
        """One jitted shard_map step per input-rank tuple: kernel inputs
        may be per-vote vectors (rank 3 as a quorum block) or per-vote
        scalars (rank 2, e.g. the toy scheme's key column) — specs are
        derived from the actual ranks like :func:`quorum_decide`."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        scheme = self.scheme

        def step(w, *arrays):
            local = scheme.verify_kernel(*arrays)  # (S/seq, V/vote)
            counts = jax.lax.psum(jnp.sum(local * w, axis=-1), "vote")
            return local, counts

        in_specs = (P("seq", "vote"),) + tuple(
            P("seq", "vote", None) if r == 3 else P("seq", "vote")
            for r in ranks
        )
        shard_map = resolve_shard_map(required=True)
        sharded = shard_map(step, mesh=self.mesh, in_specs=in_specs,
                            out_specs=(P("seq", "vote"), P("seq")))
        return jax.jit(sharded)

    def _probe_item(self):
        sk, pub = self.scheme.keygen(b"quorum-mesh-probe")
        return self.scheme.make_item(b"p", self.scheme.sign_raw(sk, b"p"), pub)

    def verify(self, items) -> list[bool]:
        if not items:
            return []
        import time as _time

        import jax.numpy as jnp

        # group the flush into rows by message; rows with more votes than
        # the tile split across rows (verdicts stay exact; the split rows'
        # counts are partial and merged host-side below)
        rows: list[tuple[bytes, list[int]]] = []
        by_msg: dict[bytes, int] = {}
        counted: set = set()  # distinct items whose lane weights count
        duplicate_lanes: set[int] = set()
        for idx, it in enumerate(items):
            msg = it[0]
            # duplicate votes (colocated replicas re-checking the same
            # signature in an un-deduped flush) get verified lanes but
            # weight 0, so the psum'd quorum count tallies DISTINCT valid
            # votes; unhashable scheme items degrade to counting all
            try:
                if it in counted:
                    duplicate_lanes.add(idx)
                else:
                    counted.add(it)
            except TypeError:
                pass
            at = by_msg.get(msg)
            if at is None or len(rows[at][1]) >= self.vote_tile:
                by_msg[msg] = len(rows)
                rows.append((msg, [idx]))
            else:
                rows[at][1].append(idx)

        out = [False] * len(items)
        self.last_counts = {}
        t0 = _time.perf_counter()
        lanes = 0
        # exact per-device REAL-item counts under the (seq x vote) tile
        # mapping: device (r-tile, v-tile) owns rows_per_dev x
        # votes_per_dev cells of each block — the honest fill vector
        # (the contiguous 1D model would fabricate idle devices here)
        dev_counts = [0] * self.devices
        rows_per_dev = self.seq_tile // self._seq_par
        votes_per_dev = self.vote_tile // self._vote_par
        for off in range(0, len(rows), self.seq_tile):
            block = rows[off : off + self.seq_tile]
            flat: list = []
            weights = np.zeros((self.seq_tile, self.vote_tile), np.uint32)
            for r in range(self.seq_tile):
                idxs = block[r][1] if r < len(block) else []
                fill = items[idxs[0]] if idxs else (
                    items[block[0][1][0]] if block else self._probe_item()
                )
                for v in range(self.vote_tile):
                    if v < len(idxs):
                        flat.append(items[idxs[v]])
                        if idxs[v] not in duplicate_lanes:
                            weights[r, v] = 1
                    else:
                        flat.append(fill)
            arrays = self.scheme.verify_inputs(flat)
            shape = (self.seq_tile, self.vote_tile)
            blocks = tuple(
                jnp.asarray(a.reshape(shape + a.shape[1:])) for a in arrays
            )
            ranks = tuple(b.ndim for b in blocks)
            fn = self._steps.get(ranks)
            if fn is None:
                fn = self._steps[ranks] = self._build_step(ranks)
            mask2d, counts = fn(jnp.asarray(weights), *blocks)
            mask2d = np.asarray(mask2d)
            counts = np.asarray(counts)
            self.psum_steps += 1
            lanes += self.seq_tile * self.vote_tile
            for r, (msg, idxs) in enumerate(block):
                for v, idx in enumerate(idxs):
                    out[idx] = bool(mask2d[r, v])
                    dev_counts[(r // rows_per_dev) * self._vote_par
                               + (v // votes_per_dev)] += 1
                self.last_counts[msg] = (
                    self.last_counts.get(msg, 0) + int(counts[r])
                )
        self.last_decided = {
            m: c >= self.quorum for m, c in self.last_counts.items()
        }
        self.stats.record(len(items), lanes, _time.perf_counter() - t0,
                          per_device=dev_counts)
        return out


def quorum_decide(mesh, quorum: int, scheme=p256):
    """The distributed quorum step: (S, V, ...) vote block -> (S,) decided.

    Shards sequences over 'seq' and votes over 'vote'; each device runs the
    scheme's verify kernel on its tile, then vote counts `psum` across the
    'vote' axis.  Returns a function over device arrays placed with
    ``NamedSharding(mesh, P('seq', 'vote', *))``.

    Scheme-generic: kernel inputs may be per-vote vectors (rank 3 as a
    quorum block) or per-vote scalars like the host-validity masks of
    ed25519/bls12381 (rank 2); partition specs are derived from the actual
    ranks at first call and the wrapped shard_map is cached per rank tuple.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if tuple(mesh.axis_names) != ("seq", "vote"):
        raise ValueError("quorum_decide wants a ('seq', 'vote') mesh")

    def step(*arrays):
        local = scheme.verify_kernel(*arrays)  # (S/seq, V/vote)
        counts = jax.lax.psum(jnp.sum(local, axis=-1), "vote")
        return counts >= quorum

    cache: dict[tuple[int, ...], object] = {}

    def wrap(ranks: tuple[int, ...]):
        if any(r not in (2, 3) for r in ranks):
            raise ValueError(f"quorum-block inputs must be rank 2 or 3, got {ranks}")
        specs = tuple(
            P("seq", "vote", None) if r == 3 else P("seq", "vote") for r in ranks
        )
        shard_map = resolve_shard_map(required=True)
        sharded = shard_map(step, mesh=mesh, in_specs=specs, out_specs=P("seq"))
        return jax.jit(sharded)

    def decide(*arrays):
        ranks = tuple(np.ndim(a) for a in arrays)
        fn = cache.get(ranks)
        if fn is None:
            fn = cache[ranks] = wrap(ranks)
        return fn(*arrays)

    return decide
