"""Mesh-sharded signature verification and the distributed quorum step.

Design notes (TPU-first):

* Verification lanes are independent — the ideal SPMD workload.  The
  engine pads each batch to a lane count divisible by the mesh and places
  inputs with ``NamedSharding(mesh, P('lane'))``; ``jax.jit`` then
  partitions the whole kernel body across devices without any hand-written
  collectives.
* The quorum step is the one place a cross-device reduction exists: vote
  counts sum over the 'vote' mesh axis (``lax.psum`` riding ICI), the
  cheapest possible collective (one scalar per in-flight sequence).
* Both paths reuse the scheme modules' single-chip kernels unchanged —
  sharding is an annotation, not a rewrite.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..crypto import p256
from ..crypto.provider import JaxVerifyEngine


def build_mesh(shape: Optional[tuple[int, ...]] = None,
               axis_names: tuple[str, ...] = ("lane",),
               devices=None):
    """A `jax.sharding.Mesh` over the first prod(shape) devices.

    Default: all visible devices on a 1D 'lane' axis.  For the quorum step
    pass ``shape=(seq_par, vote_par)`` and ``axis_names=('seq', 'vote')``.
    """
    import jax

    devices = list(jax.devices() if devices is None else devices)
    if shape is None:
        shape = (len(devices),)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, "
                         f"have {len(devices)}")
    from jax.sharding import Mesh

    return Mesh(np.asarray(devices[:n]).reshape(shape), axis_names)


class ShardedVerifyEngine(JaxVerifyEngine):
    """`JaxVerifyEngine` with batch lanes sharded over a 1D device mesh.

    Same engine surface, so it plugs into ``CryptoProvider`` and the async
    coalescer unchanged.  Pad sizes are rounded up to multiples of the mesh
    size so every device gets equal, static tiles; padded inputs are placed
    with a lane sharding and XLA partitions the kernel.
    """

    # the fused Pallas kernel is single-device (no partitioning rules);
    # mesh-placed lanes must stay on the XLA kernel so jit partitions them
    supports_pallas = False

    def __init__(self, mesh=None,
                 pad_sizes: tuple[int, ...] = (64, 256, 1024), scheme=p256):
        from jax.sharding import NamedSharding, PartitionSpec

        self.mesh = mesh if mesh is not None else build_mesh()
        if len(self.mesh.axis_names) != 1:
            raise ValueError("ShardedVerifyEngine wants a 1D mesh; use "
                             "quorum_decide for 2D (seq x vote) meshes")
        self.lanes = int(np.prod(self.mesh.devices.shape))
        rounded = sorted({-(-s // self.lanes) * self.lanes for s in pad_sizes})
        super().__init__(pad_sizes=rounded, scheme=scheme)
        self._sharding = NamedSharding(
            self.mesh, PartitionSpec(self.mesh.axis_names[0])
        )

    def _place(self, a):
        return self._jax.device_put(a, self._sharding)


def quorum_decide(mesh, quorum: int, scheme=p256):
    """The distributed quorum step: (S, V, ...) vote block -> (S,) decided.

    Shards sequences over 'seq' and votes over 'vote'; each device runs the
    scheme's verify kernel on its tile, then vote counts `psum` across the
    'vote' axis.  Returns a function over device arrays placed with
    ``NamedSharding(mesh, P('seq', 'vote', *))``.

    Scheme-generic: kernel inputs may be per-vote vectors (rank 3 as a
    quorum block) or per-vote scalars like the host-validity masks of
    ed25519/bls12381 (rank 2); partition specs are derived from the actual
    ranks at first call and the wrapped shard_map is cached per rank tuple.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if tuple(mesh.axis_names) != ("seq", "vote"):
        raise ValueError("quorum_decide wants a ('seq', 'vote') mesh")

    def step(*arrays):
        local = scheme.verify_kernel(*arrays)  # (S/seq, V/vote)
        counts = jax.lax.psum(jnp.sum(local, axis=-1), "vote")
        return counts >= quorum

    cache: dict[tuple[int, ...], object] = {}

    def wrap(ranks: tuple[int, ...]):
        if any(r not in (2, 3) for r in ranks):
            raise ValueError(f"quorum-block inputs must be rank 2 or 3, got {ranks}")
        specs = tuple(
            P("seq", "vote", None) if r == 3 else P("seq", "vote") for r in ranks
        )
        # check_vma=False: the bignum carry-chain scans initialize carries
        # from unvarying constants, which the varying-manual-axes checker
        # rejects; the computation is elementwise over lanes + one psum.
        try:
            sharded = jax.shard_map(step, mesh=mesh, in_specs=specs,
                                    out_specs=P("seq"), check_vma=False)
        except TypeError:  # older jax spells it check_rep
            sharded = jax.shard_map(step, mesh=mesh, in_specs=specs,
                                    out_specs=P("seq"), check_rep=False)
        return jax.jit(sharded)

    def decide(*arrays):
        ranks = tuple(np.ndim(a) for a in arrays)
        fn = cache.get(ranks)
        if fn is None:
            fn = cache[ranks] = wrap(ranks)
        return fn(*arrays)

    return decide
