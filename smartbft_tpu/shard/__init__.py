"""Sharded consensus groups over one shared TPU verify plane.

S independent consensus groups ("shards") run in one process behind a
single client-facing front door; their prepare/commit verify waves
coalesce into COMMON device launches through one shared
``AsyncBatchCoalescer``/``JaxVerifyEngine``, so launch fill — and with it
aggregate committed tx/s — multiplies with the shard count while launch
counts grow sublinearly (the Mir-BFT/SBFT multi-instance multiplier,
landed on this codebase's strongest axis).  See README "Sharded mode".

Components:
  ShardRouter  — deterministic, reconfig-friendly client-id -> shard map
  DeliveryMux  — combined committed stream, per-shard exactly-once/gapless
  ShardSet     — composition root / front door / metrics roll-up
"""

from .mux import CommittedEntry, DeliveryMux, ShardStreamViolation
from .router import ShardRouter, jump_hash
from .set import ShardHandle, ShardSet

__all__ = [
    "CommittedEntry",
    "DeliveryMux",
    "ShardHandle",
    "ShardRouter",
    "ShardSet",
    "ShardStreamViolation",
    "jump_hash",
]
