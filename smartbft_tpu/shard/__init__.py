"""Sharded consensus groups over one shared TPU verify plane.

S independent consensus groups ("shards") run in one process behind a
single client-facing front door; their prepare/commit verify waves
coalesce into COMMON device launches through one shared
``AsyncBatchCoalescer``/``JaxVerifyEngine``, so launch fill — and with it
aggregate committed tx/s — multiplies with the shard count while launch
counts grow sublinearly (the Mir-BFT/SBFT multi-instance multiplier,
landed on this codebase's strongest axis).  See README "Sharded mode".

The shard count is ELASTIC: ``ShardSet.reshard`` splits or merges groups
under live traffic through an epoch protocol (barrier commands committed
through each shard's own ordered stream, moved key-ranges drained behind
the barrier, atomic router flip, journaled for crash recovery), and an
occupancy-driven autoscaler can drive it from the pools' backpressure
signal.  See README "Elastic shards".

Components:
  ShardRouter         — deterministic, epoch-tagged client-id -> shard map
  DeliveryMux         — combined committed stream, per-shard exactly-once/
                        gapless across epoch transitions
  ShardSet            — composition root / front door / epoch machine /
                        metrics roll-up
  EpochJournal        — WAL-style journal of epoch-transition edges
  OccupancyAutoscaler — scale-out/in decisions over Pool.occupancy()
"""

from .autoscale import OccupancyAutoscaler, run_autoscaler
from .epoch import EpochJournal, ShardEpochError
from .mux import CommittedEntry, DeliveryMux, ShardStreamViolation
from .router import ShardRouter, jump_hash
from .set import ShardHandle, ShardSet

__all__ = [
    "CommittedEntry",
    "DeliveryMux",
    "EpochJournal",
    "OccupancyAutoscaler",
    "ShardEpochError",
    "ShardHandle",
    "ShardRouter",
    "ShardSet",
    "ShardStreamViolation",
    "jump_hash",
    "run_autoscaler",
]
