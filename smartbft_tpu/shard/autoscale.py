"""Occupancy-driven shard autoscaler.

Capacity follows load: the one backpressure signal the sharded front door
already exposes — ``Pool.occupancy()`` rolled up by
``ShardSet.occupancy()`` — drives the shard count.  When the combined
pool fill saturates (or submitters are parked waiting for space), the
deployment is under-provisioned and the autoscaler asks for one more
shard; when fill idles near zero it asks for one fewer.  Every decision
is clamped to ``[min_shards, max_shards]`` and gated by a cooldown: a
reshard is an epoch transition with a real drain, so the scaler must
never flap — scale-out and scale-in both re-arm the same cooldown clock,
and no evaluation fires while a transition is still in flight.

Two layers, separable on purpose:

* :class:`OccupancyAutoscaler` — the pure DECISION function
  (``evaluate(occupancy, num_shards) -> target | None``), unit-testable
  with synthetic occupancy snapshots and an injected clock;
* :func:`run_autoscaler` — the LOOP, polling a ShardSet and executing
  decisions through ``ShardSet.reshard`` (scale-out needs the embedder's
  ``make_shard`` factory).  Transition failures (drain-deadline aborts)
  count, re-arm the cooldown, and never kill the loop.

Thresholds live in :class:`~smartbft_tpu.config.Configuration`
(``autoscale_high_occupancy`` / ``autoscale_low_occupancy`` /
``autoscale_cooldown`` / ``autoscale_min_shards`` /
``autoscale_max_shards``) and ride reconfigurations through ConfigMirror
like every other knob; :meth:`OccupancyAutoscaler.from_config` reads
them.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional

__all__ = ["OccupancyAutoscaler", "run_autoscaler"]


class OccupancyAutoscaler:
    """Pure scale-out/in decision over combined occupancy snapshots."""

    def __init__(self, *, high: float = 0.85, low: float = 0.15,
                 cooldown: float = 60.0, min_shards: int = 1,
                 max_shards: int = 8, step: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        if not (0.0 < low < high <= 1.0):
            raise ValueError(
                f"need 0 < low < high <= 1, got low={low} high={high}"
            )
        if not (1 <= min_shards <= max_shards):
            raise ValueError(
                f"need 1 <= min_shards <= max_shards, got "
                f"{min_shards}..{max_shards}"
            )
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        self.high = high
        self.low = low
        self.cooldown = cooldown
        self.min_shards = min_shards
        self.max_shards = max_shards
        self.step = step
        self._clock = clock
        self._last_action: Optional[float] = None
        #: cumulative front-door sheds at the last evaluate — an increase
        #: is a saturation signal in its own right (see evaluate)
        self._last_shed: Optional[int] = None
        #: decision log for benches/tests: (monotonic, from_s, to_s, why)
        self.decisions: list[tuple] = []

    @classmethod
    def from_config(cls, config, **overrides) -> "OccupancyAutoscaler":
        kw = dict(
            high=config.autoscale_high_occupancy,
            low=config.autoscale_low_occupancy,
            cooldown=config.autoscale_cooldown,
            min_shards=config.autoscale_min_shards,
            max_shards=config.autoscale_max_shards,
        )
        kw.update(overrides)
        return cls(**kw)

    def in_cooldown(self) -> bool:
        return (self._last_action is not None
                and self._clock() - self._last_action < self.cooldown)

    def note_action(self) -> None:
        """Re-arm the cooldown (called for executed AND failed reshards —
        a failed drain is the strongest possible signal to back off)."""
        self._last_action = self._clock()

    def evaluate(self, occupancy: dict, num_shards: int) -> Optional[int]:
        """The target shard count, or None to hold.

        ``occupancy`` is a ``ShardSet.occupancy()`` snapshot: ``fill`` is
        the combined filled fraction, ``total_waiters`` counts submitters
        already parked on a full pool (saturation even when a race just
        freed a slot), and ``shed_admission``/``shed_timeout`` are the
        front door's cumulative sheds.  Shedding since the last
        evaluation is a saturation signal in its own right — with an
        admission gate armed below ``high`` (e.g. hw 0.8 vs high 0.85)
        fill can NEVER reach the threshold and waiters never form (the
        gate sheds before the pool fills), so without this signal the
        autoscaler would watch a shedding cluster forever and never
        scale out the one remedy it owns."""
        if self.in_cooldown():
            return None
        # baseline advances only on ACTIONABLE evaluations: sheds that
        # land mid-cooldown still read as saturation once it expires,
        # instead of being silently consumed by a held evaluation
        sheds = int(occupancy.get("shed_admission", 0)) \
            + int(occupancy.get("shed_timeout", 0))
        shedding = self._last_shed is not None and sheds > self._last_shed
        self._last_shed = sheds
        fill = float(occupancy.get("fill", 0.0))
        waiters = int(occupancy.get("total_waiters", 0))
        saturated = fill >= self.high or waiters > 0 or shedding
        # "nothing reporting" (explicit zero combined capacity — e.g. the
        # pools have not started yet) is indistinguishable from idle by
        # fill alone; hold rather than shrink a deployment that has not
        # come up.  Absent capacity (embedder snapshots without the key)
        # keeps plain fill semantics.
        idle = (fill <= self.low and waiters == 0 and not shedding
                and occupancy.get("total_capacity") != 0)
        if saturated and num_shards < self.max_shards:
            target = min(num_shards + self.step, self.max_shards)
            self.decisions.append(
                (self._clock(), num_shards, target,
                 f"fill={fill:.2f} waiters={waiters} shedding={shedding}")
            )
            return target
        if idle and num_shards > self.min_shards:
            target = max(num_shards - self.step, self.min_shards)
            self.decisions.append(
                (self._clock(), num_shards, target, f"fill={fill:.2f} idle")
            )
            return target
        return None


async def run_autoscaler(shard_set, autoscaler: OccupancyAutoscaler, *,
                         make_shard: Optional[Callable] = None,
                         interval: float = 1.0,
                         stop: Optional[asyncio.Event] = None,
                         on_reshard: Optional[Callable] = None,
                         arbiter=None,
                         logger=None) -> int:
    """The autoscaler loop: poll occupancy, execute decisions, never die.

    ``make_shard(shard_id, epoch)`` builds new groups on scale-out (the
    embedder's factory, same as ``ShardSet.reshard``).  ``on_reshard``
    (optional, sync) observes each completed transition summary — the
    harness uses it to refresh its shard list.  ``arbiter`` (a
    :class:`~smartbft_tpu.control.TransitionArbiter`, shared with any
    :class:`~smartbft_tpu.control.ControlLoop` on the same set) makes the
    two transition initiators mutually exclusive: the old
    check-``reshard_in_progress``-then-reshard sequence was a TOCTOU —
    the controller could start a reshard between this loop's check and
    its own ``reshard`` call, double-transitioning the epoch.  The
    arbiter is acquired BEFORE evaluate and released after the
    transition completes (or fails), closing that window.  Runs until
    ``stop`` is set (required for bounded runs; pass ``asyncio.Event()``),
    returning the number of reshards executed."""
    stop = stop or asyncio.Event()
    executed = 0
    while not stop.is_set():
        held = arbiter is None or arbiter.try_acquire("autoscaler")
        if held:
            try:
                if not shard_set.reshard_in_progress:
                    target = autoscaler.evaluate(
                        shard_set.occupancy(), shard_set.num_shards
                    )
                    if target is not None:
                        autoscaler.note_action()
                        try:
                            summary = await shard_set.reshard(
                                target, make_shard=make_shard
                            )
                            executed += 1
                            if on_reshard is not None:
                                on_reshard(summary)
                        except asyncio.CancelledError:
                            raise
                        except Exception as e:  # noqa: BLE001 — the loop's
                            # contract is "execute decisions, never die": a
                            # drain abort (ShardEpochError), a missing
                            # make_shard (ValueError on scale-out), or a
                            # transient group-start failure must not kill
                            # future evaluations; the cooldown is already
                            # re-armed above
                            if logger is not None:
                                logger.warnf(
                                    "autoscale reshard to %d failed: %r",
                                    target, e)
            finally:
                if arbiter is not None:
                    arbiter.release("autoscaler")
        # wake promptly on stop, tick on interval otherwise
        try:
            await asyncio.wait_for(stop.wait(), timeout=interval)
        except asyncio.TimeoutError:
            pass
    return executed
