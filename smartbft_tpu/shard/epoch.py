"""Epoch protocol primitives for live resharding.

A reshard (S -> S') is not a configuration flag — it is an ordered,
crash-recoverable state transition, and this module holds its three
building blocks:

* **The barrier command** (:func:`reshard_command_payload` /
  :func:`detect_reshard`): the resize decision rides each shard's own
  ordered stream as an ordinary request (client ``RESHARD_CLIENT``,
  request id ``reshard-e<epoch>``).  The sequence at which a shard
  commits its marker is that shard's *barrier*: every decision at or
  below it belongs to the old epoch, everything after it can assume the
  drain of moved key-ranges has begun.  Committing the decision through
  the shards themselves is the Vertical-Paxos / SMR-reconfiguration rule
  (PAPERS.md [4]): a resize decided on a side channel can always race
  the stream it is trying to fence.  Because the marker is a normal
  request, the per-shard pool's client dedup makes re-submission after a
  coordinator recovery exactly-once for free.

* **The epoch journal** (:class:`EpochJournal`): a WAL-style JSON-lines
  file recording every transition edge (``prepare`` -> ``barrier``\\*N ->
  ``flip`` -> ``done``, or ``abort``), fsync'd per append, replayed with
  torn-tail tolerance.  :func:`recover_epochs` folds a replay into the
  durable facts a restarting front door needs: the last completed epoch,
  the epoch numbers already consumed (aborted transitions burn their
  number — their markers may have committed, so the number can never be
  reused), and the one incomplete transition, if any, with how far it
  got.  A coordinator that crashed mid-drain resumes (or completes a
  journaled flip) instead of guessing.

* **The error contract** (:class:`ShardEpochError`): the single loud
  failure of the live path — raised to submitters of a *moved*
  key-range when the bounded drain deadline expires (or the transition
  aborts), and to a caller trying to start a second concurrent reshard.
  Unmoved key-ranges never see it; their shards never stop serving.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..codec import decode, encode, wiremsg

__all__ = [
    "RESHARD_CLIENT",
    "ReshardCommand",
    "ShardEpochError",
    "EpochJournal",
    "barrier_request_id",
    "barrier_marker",
    "reshard_command_payload",
    "detect_reshard",
    "recover_epochs",
]

#: the reserved client id every barrier command is submitted under; the
#: front door's routing, drain accounting, and the delivery mux treat it
#: as control-plane traffic (it is excluded from moved-key checks)
RESHARD_CLIENT = "__reshard__"

#: payload prefix marking a request as a reshard barrier command (same
#: convention as testing.reconfig.RECONFIG_MAGIC)
RESHARD_MAGIC = b"smartbft-reshard\x00"


class ShardEpochError(RuntimeError):
    """The live-reshard error contract (see module docstring)."""


@wiremsg
class ReshardCommand:
    """The ordered resize decision: what the barrier request carries."""

    epoch: int = 0
    old_shards: int = 0
    new_shards: int = 0


def barrier_request_id(epoch: int) -> str:
    """The request id of epoch ``epoch``'s barrier command."""
    return f"reshard-e{epoch}"


def barrier_marker(epoch: int) -> str:
    """The ``client:request_id`` string a committed barrier shows as in a
    delivery-mux entry's ``request_ids`` (RequestInfo.__str__ format) —
    what the front door scans committed streams for."""
    return f"{RESHARD_CLIENT}:{barrier_request_id(epoch)}"


def reshard_command_payload(epoch: int, old_shards: int, new_shards: int) -> bytes:
    """Payload bytes of the barrier request (embedders wrap these in their
    own request envelope, e.g. testing.app.TestRequest)."""
    return RESHARD_MAGIC + encode(ReshardCommand(
        epoch=epoch, old_shards=old_shards, new_shards=new_shards
    ))


def detect_reshard(payload: bytes) -> Optional[ReshardCommand]:
    """Parse a request payload; None when it is not a barrier command."""
    if not payload.startswith(RESHARD_MAGIC):
        return None
    return decode(ReshardCommand, payload[len(RESHARD_MAGIC):])


class EpochJournal:
    """Append-only JSON-lines journal of epoch-transition edges.

    Record shapes (one JSON object per line)::

        {"t": "prepare", "epoch": E, "old": S,   "new": S'}
        {"t": "barrier", "epoch": E, "shard": s, "seq": n}
        {"t": "flip",    "epoch": E, "shards": [ids...]}
        {"t": "done",    "epoch": E}
        {"t": "abort",   "epoch": E, "reason": "..."}

    ``append`` flushes and fsyncs before returning — a journaled edge
    survives a SIGKILL in the very next instruction.  ``replay`` tolerates
    a torn tail (a partial or corrupt final line ends the replay; the
    transition simply recovers one edge earlier, which every edge is
    designed to make safe: re-preparing is a no-op, re-submitting a
    barrier dedups in the pool, re-flipping is idempotent)."""

    def __init__(self, path: str):
        self.path = str(path)
        self._fh = None

    def replay(self) -> list[dict]:
        records: list[dict] = []
        if not os.path.exists(self.path):
            return records
        with open(self.path, "rb") as fh:
            data = fh.read()
        for line in data.split(b"\n"):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                break  # torn tail: everything after is unreadable
            if not isinstance(rec, dict) or "t" not in rec:
                break
            records.append(rec)
        return records

    def append(self, record: dict) -> None:
        if self._fh is None:
            # seal a crash-torn tail BEFORE the first append: replay stops
            # at the first unreadable line, so writing after torn bytes
            # would glue onto them and permanently hide this record (and
            # every later one) from recovery — the torn-tail-truncation
            # rule the WAL package applies, here at JSON-line granularity
            self._seal_torn_tail()
            self._fh = open(self.path, "ab")
        self._fh.write((json.dumps(record, sort_keys=True) + "\n").encode())
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def _seal_torn_tail(self) -> None:
        """Truncate the file to its longest replayable prefix (exactly
        what replay() accepts): an unterminated or unparseable tail is a
        torn final write and is dropped, never written after."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as fh:
            data = fh.read()
        good = 0
        pos = 0
        while True:
            nl = data.find(b"\n", pos)
            if nl < 0:
                break  # unterminated tail: torn
            line = data[pos:nl].strip()
            pos = nl + 1
            if line:
                try:
                    rec = json.loads(line)
                except ValueError:
                    break
                if not isinstance(rec, dict) or "t" not in rec:
                    break
            good = pos
        if good < len(data):
            with open(self.path, "r+b") as fh:
                fh.truncate(good)
                fh.flush()
                os.fsync(fh.fileno())

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def recover_epochs(records: list[dict]) -> dict:
    """Fold a journal replay into the recovery facts.

    Returns ``{"epoch": last completed epoch (0 if none),
    "shards": that epoch's shard count (None if no completed transition),
    "next_epoch": first epoch number safe to allocate,
    "incomplete": None | {"epoch", "old", "new", "barriers", "flipped"}}``.

    An ``abort`` or ``done`` closes its transition; a ``prepare`` without
    either is the (single) incomplete one.  Epoch numbers are consumed by
    every prepare — aborted or not — because the transition's barrier
    markers may already sit in committed history."""
    epoch = 0
    shards: Optional[int] = None
    next_epoch = 1
    open_tr: Optional[dict] = None
    for rec in records:
        t = rec.get("t")
        if t == "prepare":
            open_tr = {
                "epoch": int(rec["epoch"]),
                "old": int(rec.get("old", 0)),
                "new": int(rec.get("new", 0)),
                "barriers": {},
                "flipped": False,
            }
            next_epoch = max(next_epoch, open_tr["epoch"] + 1)
        elif t == "barrier" and open_tr is not None \
                and int(rec.get("epoch", -1)) == open_tr["epoch"]:
            open_tr["barriers"][int(rec["shard"])] = int(rec["seq"])
        elif t == "flip" and open_tr is not None \
                and int(rec.get("epoch", -1)) == open_tr["epoch"]:
            open_tr["flipped"] = True
        elif t == "done":
            done_epoch = int(rec.get("epoch", 0))
            if done_epoch >= epoch:
                epoch = done_epoch
                if open_tr is not None and open_tr["epoch"] == done_epoch:
                    shards = open_tr["new"]
            next_epoch = max(next_epoch, epoch + 1)
            open_tr = None
        elif t == "abort":
            open_tr = None
    return {"epoch": epoch, "shards": shards, "next_epoch": next_epoch,
            "incomplete": open_tr}
