"""Delivery multiplexer: one combined committed stream over S shards.

Each shard commits an independent, totally-ordered chain; the embedder of
a sharded deployment wants ONE stream of committed entries (to apply to
state, index, or serve reads from) without losing the per-shard ordering
guarantees.  :class:`DeliveryMux` is that seam: shards feed their newly
committed decisions in, the mux enforces the per-shard invariants —
**gapless** (each shard's sequence numbers arrive as 1,2,3,... with no
hole) and **exactly-once** (no request id delivered twice within a shard)
— and appends to a combined, arrival-ordered stream of
:class:`CommittedEntry`.

There is deliberately NO cross-shard ordering claim: entries from
different shards interleave in arrival order only.  Cross-shard
transactions are out of scope (README "Sharded mode"); anything needing
an order across shards must impose it above this layer.

A violation raises :class:`ShardStreamViolation` — a sharded deployment
that forked or double-delivered must fail loudly at the front door, not
smear bad entries into the embedder's state machine.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional, Sequence

__all__ = ["CommittedEntry", "DeliveryMux", "ShardStreamViolation"]


class ShardStreamViolation(RuntimeError):
    """A shard's committed feed broke gaplessness or exactly-once."""


@dataclass(frozen=True)
class CommittedEntry:
    """One committed decision in the combined stream."""

    shard_id: int
    seq: int          # the shard-local consensus sequence (1-based, gapless)
    index: int        # position in the combined stream (0-based, arrival order)
    decision: object  # the shard's Decision (proposal + signatures)
    request_ids: tuple = ()


@dataclass
class _ShardCursor:
    next_seq: int = 1
    delivered: int = 0
    requests: int = 0  # total request ids delivered (survives pruning)
    seen_requests: set = field(default_factory=set)


class DeliveryMux:
    """Combined committed stream with per-shard invariant enforcement.

    ``ingest(shard_id, decision, seq=..., request_ids=...)`` appends one
    decision; feeds usually come from :class:`~smartbft_tpu.shard.set.
    ShardSet.poll_committed`, which extracts ``seq`` from the decision's
    ViewMetadata and the request ids from the shard's inspector.  Readers
    either poll ``combined[since:]`` or register an ``on_deliver``
    callback (called synchronously per entry, in stream order).  A
    long-lived embedder calls ``prune(upto)`` once entries are applied, so
    the committed path does not grow memory with history.
    """

    def __init__(self, shard_ids: Sequence[int],
                 on_deliver: Optional[Callable[[CommittedEntry], None]] = None):
        self._cursors: dict[int, _ShardCursor] = {
            int(s): _ShardCursor() for s in shard_ids
        }
        self.combined: list[CommittedEntry] = []
        self._pruned = 0  # entries dropped by prune(); indexes stay absolute
        self._on_deliver = on_deliver

    # -- feeding -----------------------------------------------------------

    def ingest(self, shard_id: int, decision, *, seq: int,
               request_ids: Iterable = ()) -> CommittedEntry:
        cur = self._cursors.get(shard_id)
        if cur is None:
            raise ShardStreamViolation(
                f"decision from unknown shard {shard_id}"
            )
        if seq != cur.next_seq:
            raise ShardStreamViolation(
                f"shard {shard_id} stream gap: got seq {seq}, "
                f"expected {cur.next_seq}"
            )
        ids = tuple(str(r) for r in request_ids)
        # duplicates against everything delivered before AND within this
        # very decision — both violate per-shard exactly-once
        seen_here: set = set()
        dupes = []
        for r in ids:
            if r in cur.seen_requests or r in seen_here:
                dupes.append(r)
            seen_here.add(r)
        if dupes:
            raise ShardStreamViolation(
                f"shard {shard_id} delivered duplicates at seq {seq}: "
                f"{sorted(set(dupes))}"
            )
        cur.seen_requests.update(ids)
        cur.next_seq += 1
        cur.delivered += 1
        cur.requests += len(ids)
        entry = CommittedEntry(
            shard_id=shard_id, seq=seq,
            index=self._pruned + len(self.combined),
            decision=decision, request_ids=ids,
        )
        self.combined.append(entry)
        if self._on_deliver is not None:
            self._on_deliver(entry)
        return entry

    # -- reading -----------------------------------------------------------

    def since(self, index: int) -> list[CommittedEntry]:
        """Combined entries from stream position ``index`` on (entries
        below the prune watermark are gone)."""
        return self.combined[max(index - self._pruned, 0):]

    def prune(self, upto: int) -> int:
        """Drop combined entries with stream index < ``upto`` — the
        embedder's acknowledgment that they are applied/persisted.  Keeps
        the committed-path memory bounded in long-lived deployments
        (everything else history-driven in this codebase is bounded too).
        Per-shard cursors and counters are untouched; duplicate-request
        detection narrows to the ids delivered at/after the watermark (the
        per-shard request pool's client dedup covers the full history).
        Returns the number of entries dropped."""
        drop = min(max(upto - self._pruned, 0), len(self.combined))
        if not drop:
            return 0
        for e in self.combined[:drop]:
            self._cursors[e.shard_id].seen_requests.difference_update(
                e.request_ids
            )
        del self.combined[:drop]
        self._pruned += drop
        return drop

    def height(self, shard_id: int) -> int:
        """Decisions delivered through the mux for one shard."""
        return self._cursors[shard_id].delivered

    def heights(self) -> dict[int, int]:
        return {s: c.delivered for s, c in self._cursors.items()}

    def total(self) -> int:
        return self._pruned + len(self.combined)

    def requests_delivered(self, shard_id: int) -> int:
        return self._cursors[shard_id].requests

    def snapshot(self) -> dict:
        """JSON-able per-shard + combined block for bench rows."""
        return {
            "total": self.total(),
            "pruned": self._pruned,
            "per_shard": {
                s: {"decisions": c.delivered,
                    "requests": c.requests,
                    "next_seq": c.next_seq}
                for s, c in sorted(self._cursors.items())
            },
        }
