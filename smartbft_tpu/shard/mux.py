"""Delivery multiplexer: one combined committed stream over S shards.

Each shard commits an independent, totally-ordered chain; the embedder of
a sharded deployment wants ONE stream of committed entries (to apply to
state, index, or serve reads from) without losing the per-shard ordering
guarantees.  :class:`DeliveryMux` is that seam: shards feed their newly
committed decisions in, the mux enforces the per-shard invariants —
**gapless** (each shard's sequence numbers arrive as 1,2,3,... with no
hole) and **exactly-once** (no request id delivered twice within a shard)
— and appends to a combined, arrival-ordered stream of
:class:`CommittedEntry`.

The shard SET is no longer fixed for the stream's lifetime: a live
reshard calls :meth:`begin_epoch` at the flip, which opens cursors for
new shards, freezes retired ones (any later ingest for them is a
violation — a retired shard that still commits after its drain forked
the transition), and stamps an **epoch watermark** into the stream: the
combined index at which the epoch changed, the shard ids on each side,
and the per-shard barrier sequences.  Entries carry the epoch they were
delivered under, and per-shard gaplessness spans the transition —
surviving shards keep counting, new shards start at 1.

Cross-epoch duplication prevention is EXPLICIT (the Mir-BFT rule for
re-bucketing client spaces): at the flip the mux rebuilds the hand-off
set from every still-unpruned delivered CLIENT request id (control-plane
barrier ids commit once per shard and are excluded), and an ingest in the
new epoch that repeats one — the moved client whose request committed in
its old shard and then again in its new one — is as loud a violation as
an intra-shard duplicate.  Rebuilding (never accumulating) keeps the set
bounded by the retention window across unbounded transitions.

There is deliberately NO cross-shard ordering claim: entries from
different shards interleave in arrival order only.  Cross-shard
transactions are out of scope (README "Sharded mode"); anything needing
an order across shards must impose it above this layer.

A violation raises :class:`ShardStreamViolation` — a sharded deployment
that forked or double-delivered must fail loudly at the front door, not
smear bad entries into the embedder's state machine.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional, Sequence

from .epoch import RESHARD_CLIENT

__all__ = ["CommittedEntry", "DeliveryMux", "ShardStreamViolation"]

#: request-id prefix of per-shard control commands (reshard barriers):
#: legitimately committed once per SHARD, so they are excluded from the
#: cross-epoch hand-off set — a stale barrier from an ABORTED transition
#: that finally orders on its shard after a later successful flip must
#: not read as a moved-client duplicate (per-shard exactly-once for them
#: is still enforced by each cursor's own seen set)
_CONTROL_PREFIX = RESHARD_CLIENT + ":"


class ShardStreamViolation(RuntimeError):
    """A shard's committed feed broke gaplessness or exactly-once."""


@dataclass(frozen=True)
class CommittedEntry:
    """One committed decision in the combined stream."""

    shard_id: int
    seq: int          # the shard-local consensus sequence (1-based, gapless)
    index: int        # position in the combined stream (0-based, arrival order)
    decision: object  # the shard's Decision (proposal + signatures)
    request_ids: tuple = ()
    epoch: int = 0    # the epoch this entry was delivered under


@dataclass
class _ShardCursor:
    next_seq: int = 1
    delivered: int = 0
    requests: int = 0  # total request ids delivered (survives pruning)
    seen_requests: set = field(default_factory=set)
    retired: bool = False  # frozen by a scale-in flip; ingest raises


class DeliveryMux:
    """Combined committed stream with per-shard invariant enforcement.

    ``ingest(shard_id, decision, seq=..., request_ids=...)`` appends one
    decision; feeds usually come from :class:`~smartbft_tpu.shard.set.
    ShardSet.poll_committed`, which extracts ``seq`` from the decision's
    ViewMetadata and the request ids from the shard's inspector.  Readers
    either poll ``combined[since:]`` or register an ``on_deliver``
    callback (called synchronously per entry, in stream order).  A
    long-lived embedder calls ``prune(upto)`` once entries are applied,
    so the committed path does not grow memory with history (ShardSet
    wires this automatically to its delivery watermark).
    """

    def __init__(self, shard_ids: Sequence[int],
                 on_deliver: Optional[Callable[[CommittedEntry], None]] = None,
                 on_deliver_batch: Optional[
                     Callable[[list[CommittedEntry]], None]] = None):
        self._cursors: dict[int, _ShardCursor] = {
            int(s): _ShardCursor() for s in shard_ids
        }
        self.combined: list[CommittedEntry] = []
        self._pruned = 0  # entries dropped by prune(); indexes stay absolute
        self._on_deliver = on_deliver
        # egress twin of the view's ingest_batch: when set, a whole wave of
        # entries reaches the application in ONE call (stream order inside
        # the list) instead of one callback dispatch per decision;
        # on_deliver is then never called
        self._on_deliver_batch = on_deliver_batch
        self._epoch = 0
        #: request ids delivered before the current epoch's flip that must
        #: never re-deliver after it (explicit cross-epoch dedup).  REBUILT
        #: at each flip from the cursors' still-unpruned history — bounded
        #: by the retention window like intra-shard dedup, with older
        #: duplicates falling to the pools' history exactly as prune()
        #: documents
        self._handoff_seen: set = set()
        #: requests delivered by retired-incarnation cursors replaced by a
        #: re-entering shard id (keeps requests_total()/committed counts
        #: monotone across shrink-then-grow paths)
        self._replaced_requests = 0
        #: their still-unpruned ids — a dead generation has no cursor to
        #: feed the hand-off rebuild, so these carry its dedup horizon
        #: (trimmed by prune() on the same watermark as cursor history)
        self._replaced_seen: set = set()
        #: one record per begin_epoch: where in the stream the flip landed
        self._watermarks: list[dict] = []

    # -- feeding -----------------------------------------------------------

    def ingest(self, shard_id: int, decision, *, seq: int,
               request_ids: Iterable = ()) -> CommittedEntry:
        return self.ingest_batch(
            shard_id, [(seq, request_ids, decision)]
        )[0]

    def ingest_batch(
        self, shard_id: int, decisions: Sequence[tuple]
    ) -> list[CommittedEntry]:
        """Wave-batched feed: ``decisions`` is a consecutive run of
        ``(seq, request_ids, decision)`` for ONE shard — the shape a
        committed wave leaves the pipelined window in.  The cursor is
        resolved once, every invariant (gapless, exactly-once, hand-off
        dedup) is enforced across the whole run in one pass, and the
        application sees ONE ``on_deliver_batch`` call per wave (falling
        back to per-entry ``on_deliver``, in stream order).  A violation
        raises AFTER the validated prefix is dispatched — callbacks track
        the stream, so everything that entered ``combined`` reaches the
        application exactly once.  ``ingest`` is the single-decision
        special case."""
        cur = self._cursors.get(shard_id)
        if cur is None:
            raise ShardStreamViolation(
                f"decision from unknown shard {shard_id}"
            )
        entries: list[CommittedEntry] = []
        try:
            self._ingest_run(shard_id, cur, decisions, entries)
        finally:
            # callbacks track the STREAM, not the call: every entry that
            # entered `combined` is dispatched exactly once even when a
            # later decision in the run violates (the violation still
            # raises after the validated prefix is delivered)
            if entries:
                if self._on_deliver_batch is not None:
                    self._on_deliver_batch(entries)
                elif self._on_deliver is not None:
                    for entry in entries:
                        self._on_deliver(entry)
        return entries

    def _ingest_run(self, shard_id: int, cur: _ShardCursor,
                    decisions: Sequence[tuple],
                    entries: list) -> None:
        for seq, request_ids, decision in decisions:
            if cur.retired:
                raise ShardStreamViolation(
                    f"shard {shard_id} is retired (epoch {self._epoch}) but "
                    f"delivered seq {seq} — it committed past its drain barrier"
                )
            if seq != cur.next_seq:
                raise ShardStreamViolation(
                    f"shard {shard_id} stream gap: got seq {seq}, "
                    f"expected {cur.next_seq}"
                )
            ids = tuple(str(r) for r in request_ids)
            # duplicates against everything delivered before AND within this
            # very decision — both violate per-shard exactly-once — and, across
            # an epoch flip, against the hand-off snapshot of every shard's
            # unpruned history (a moved client's request must not commit twice)
            seen_here: set = set()
            dupes = []
            handoff_dupes = []
            for r in ids:
                if r in cur.seen_requests or r in seen_here:
                    dupes.append(r)
                elif r in self._handoff_seen:
                    handoff_dupes.append(r)
                seen_here.add(r)
            if dupes:
                raise ShardStreamViolation(
                    f"shard {shard_id} delivered duplicates at seq {seq}: "
                    f"{sorted(set(dupes))}"
                )
            if handoff_dupes:
                raise ShardStreamViolation(
                    f"shard {shard_id} re-delivered handed-off requests at seq "
                    f"{seq} (already committed before the epoch {self._epoch} "
                    f"flip): {sorted(set(handoff_dupes))}"
                )
            cur.seen_requests.update(ids)
            cur.next_seq += 1
            cur.delivered += 1
            cur.requests += len(ids)
            entry = CommittedEntry(
                shard_id=shard_id, seq=seq,
                index=self._pruned + len(self.combined),
                decision=decision, request_ids=ids,
                epoch=self._epoch,
            )
            self.combined.append(entry)
            entries.append(entry)

    # -- epochs ------------------------------------------------------------

    @property
    def epoch(self) -> int:
        return self._epoch

    def begin_epoch(self, epoch: int, shard_ids: Sequence[int], *,
                    retire: Sequence[int] = (),
                    barriers: Optional[dict] = None) -> dict:
        """Flip the stream to a new epoch (called by the reshard
        orchestrator at the atomic router flip).

        ``shard_ids`` is the NEW epoch's full shard set; ``retire`` names
        shards leaving it (their cursors freeze — later ingest raises);
        ``barriers`` records each old shard's barrier sequence for the
        watermark.  A shard id re-entering after an earlier retirement
        gets a FRESH cursor (a new consensus-group generation restarts at
        seq 1); its old ids stay caught by the hand-off set.  Returns the
        watermark record appended to ``snapshot()['watermarks']``."""
        if epoch <= self._epoch:
            raise ValueError(
                f"epoch must exceed the current {self._epoch}, got {epoch}"
            )
        new_ids = {int(s) for s in shard_ids}
        retire_ids = {int(s) for s in retire}
        if retire_ids & new_ids:
            raise ValueError(
                f"shards cannot be both retired and live: "
                f"{sorted(retire_ids & new_ids)}"
            )
        # the hand-off snapshot: every unpruned id any cursor (live or
        # already-retired) has delivered — the explicit duplication
        # prevention for moved key-ranges.  Rebuilt (not accumulated) so
        # the set stays bounded by the retention window across unbounded
        # autoscaler transitions; pruned history falls to pool dedup.
        handoff: set = {
            r for r in self._replaced_seen
            if not r.startswith(_CONTROL_PREFIX)
        }
        for cur in self._cursors.values():
            handoff.update(r for r in cur.seen_requests
                           if not r.startswith(_CONTROL_PREFIX))
        for sid in retire_ids:
            cur = self._cursors.get(sid)
            if cur is None:
                raise ValueError(f"cannot retire unknown shard {sid}")
            cur.retired = True
        for sid in new_ids:
            cur = self._cursors.get(sid)
            if cur is None or cur.retired:
                # brand-new shard, or a retired id re-entering as a new
                # consensus-group generation; the dead incarnation's
                # delivered-request count stays in the monotone total and
                # its unpruned ids stay in the dedup horizon
                if cur is not None:
                    self._replaced_requests += cur.requests
                    self._replaced_seen.update(cur.seen_requests)
                self._cursors[sid] = _ShardCursor()
        self._handoff_seen = handoff
        mark = {
            "epoch": int(epoch),
            "index": self.total(),
            "shards": sorted(new_ids),
            "retired": sorted(retire_ids),
            "barriers": {int(k): int(v) for k, v in (barriers or {}).items()},
        }
        self._watermarks.append(mark)
        self._epoch = int(epoch)
        return mark

    # -- reading -----------------------------------------------------------

    def since(self, index: int) -> list[CommittedEntry]:
        """Combined entries from stream position ``index`` on (entries
        below the prune watermark are gone)."""
        return self.combined[max(index - self._pruned, 0):]

    def prune(self, upto: int) -> int:
        """Drop combined entries with stream index < ``upto`` — the
        embedder's acknowledgment that they are applied/persisted.  Keeps
        the committed-path memory bounded in long-lived deployments
        (everything else history-driven in this codebase is bounded too).
        Per-shard cursors and counters are untouched; duplicate-request
        detection narrows to the ids delivered at/after the watermark (the
        per-shard request pool's client dedup covers the full history).
        Returns the number of entries dropped."""
        drop = min(max(upto - self._pruned, 0), len(self.combined))
        if not drop:
            return 0
        for e in self.combined[:drop]:
            self._cursors[e.shard_id].seen_requests.difference_update(
                e.request_ids
            )
            # a replaced incarnation's entries map to its successor's
            # cursor above (a no-op); their ids are trimmed here
            self._replaced_seen.difference_update(e.request_ids)
        del self.combined[:drop]
        self._pruned += drop
        return drop

    def shard_ids(self) -> list[int]:
        """Every shard the stream has ever carried (retired included)."""
        return sorted(self._cursors)

    def live_shard_ids(self) -> list[int]:
        return sorted(s for s, c in self._cursors.items() if not c.retired)

    def height(self, shard_id: int) -> int:
        """Decisions delivered through the mux for one shard (0 for a
        shard the stream has not opened a cursor for yet — e.g. a new
        group mid-transition, before its epoch flips)."""
        cur = self._cursors.get(shard_id)
        return cur.delivered if cur is not None else 0

    def heights(self) -> dict[int, int]:
        return {s: c.delivered for s, c in self._cursors.items()}

    def total(self) -> int:
        return self._pruned + len(self.combined)

    def requests_delivered(self, shard_id: int) -> int:
        cur = self._cursors.get(shard_id)
        return cur.requests if cur is not None else 0

    def requests_total(self) -> int:
        """Total request ids ever delivered through the stream — MONOTONE
        across epoch flips (retired incarnations replaced by re-entering
        shard ids keep their counts here)."""
        return self._replaced_requests + sum(
            c.requests for c in self._cursors.values()
        )

    def snapshot(self) -> dict:
        """JSON-able per-shard + combined block for bench rows."""
        return {
            "total": self.total(),
            "pruned": self._pruned,
            "epoch": self._epoch,
            "watermarks": [dict(m) for m in self._watermarks],
            "per_shard": {
                s: {"decisions": c.delivered,
                    "requests": c.requests,
                    "next_seq": c.next_seq,
                    "retired": c.retired}
                for s, c in sorted(self._cursors.items())
            },
        }
