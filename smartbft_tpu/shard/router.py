"""Deterministic client-id -> shard routing.

The sharded front door must send every request of one client to ONE
consensus group: per-shard exactly-once dedup (the request pool's
client/request-id memory) only works if a client's retries land on the
same shard, and cross-shard transactions are out of scope by design (see
README "Sharded mode").  Two properties matter:

* **Determinism** — any front-door process (and any test/bench) computes
  the same mapping from the same (seed, num_shards), with no shared state;
* **Re-routable on reconfig** — growing or shrinking the shard count must
  not reshuffle the world.  Routing uses Lamping & Veach's *jump
  consistent hash*: changing S -> S' moves only ~|S'-S|/max(S,S') of the
  key space, so scale-out drains a bounded slice of clients per added
  shard instead of invalidating every shard's dedup memory.

Mir-BFT (Stathakopoulou et al., 2021) partitions the request space by
client-id hash for the same reason: independent instances over disjoint
request spaces multiply throughput without weakening per-group safety.
"""

from __future__ import annotations

import hashlib

__all__ = ["ShardRouter", "jump_hash"]

_JUMP_MULT = 2862933555777941757  # the 64-bit LCG constant of the paper
_MASK64 = (1 << 64) - 1


def jump_hash(key: int, buckets: int) -> int:
    """Jump consistent hash (Lamping & Veach 2014): uniform, stateless,
    and monotone — growing ``buckets`` only ever moves keys INTO the new
    buckets, never between old ones."""
    if buckets <= 0:
        raise ValueError(f"buckets must be positive, got {buckets}")
    key &= _MASK64
    b, j = -1, 0
    while j < buckets:
        b = j
        key = (key * _JUMP_MULT + 1) & _MASK64
        j = int((b + 1) * (1 << 31) / ((key >> 33) + 1))
    return b


class ShardRouter:
    """Deterministic, re-routable client-id -> shard mapping.

    ``route`` hashes the client id (blake2b-64, keyed by ``seed`` so
    disjoint deployments get independent mappings) and jump-hashes into
    ``num_shards`` buckets.  ``reshard`` installs a new shard count in
    place — the front door keeps one router and re-points it on reconfig;
    the jump hash guarantees minimal movement (see module docstring).
    """

    def __init__(self, num_shards: int, seed: int = 0):
        if num_shards <= 0:
            raise ValueError(f"num_shards must be positive, got {num_shards}")
        self._num_shards = num_shards
        self._seed = seed
        # canonical 64-bit reduction: distinct seeds in [-2^63, 2^64) get
        # distinct salts (seed=-s and seed=+s must NOT collide)
        self._salt = (seed % (1 << 64)).to_bytes(8, "big")

    @property
    def num_shards(self) -> int:
        return self._num_shards

    @property
    def seed(self) -> int:
        return self._seed

    def key_of(self, client_id) -> int:
        """The stable 64-bit hash a client id routes by (exposed so tests
        and drain tooling can reason about placement)."""
        raw = client_id if isinstance(client_id, (bytes, bytearray)) \
            else str(client_id).encode()
        return int.from_bytes(
            hashlib.blake2b(raw, digest_size=8, key=self._salt).digest(),
            "big",
        )

    def route(self, client_id) -> int:
        """The shard index (0..num_shards-1) owning ``client_id``."""
        return jump_hash(self.key_of(client_id), self._num_shards)

    def reshard(self, num_shards: int) -> dict:
        """Re-point the router at a new shard count (reconfig).

        Returns a summary ``{"old": S, "new": S'}`` for the caller's log.
        The caller owns draining: requests already routed keep their old
        shard's dedup history, so a deployment shrinking S must quiesce
        the removed shards first (exactly the Mir-BFT epoch-change dance);
        this object only guarantees the MAPPING moves minimally."""
        if num_shards <= 0:
            raise ValueError(f"num_shards must be positive, got {num_shards}")
        old = self._num_shards
        self._num_shards = num_shards
        return {"old": old, "new": num_shards}
