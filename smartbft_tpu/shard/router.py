"""Deterministic client-id -> shard routing.

The sharded front door must send every request of one client to ONE
consensus group: per-shard exactly-once dedup (the request pool's
client/request-id memory) only works if a client's retries land on the
same shard, and cross-shard transactions are out of scope by design (see
README "Sharded mode").  Two properties matter:

* **Determinism** — any front-door process (and any test/bench) computes
  the same mapping from the same (seed, num_shards), with no shared state;
* **Re-routable on reconfig** — growing or shrinking the shard count must
  not reshuffle the world.  Routing uses Lamping & Veach's *jump
  consistent hash*: changing S -> S' moves only ~|S'-S|/max(S,S') of the
  key space, so scale-out drains a bounded slice of clients per added
  shard instead of invalidating every shard's dedup memory.

Mir-BFT (Stathakopoulou et al., 2021) partitions the request space by
client-id hash for the same reason: independent instances over disjoint
request spaces multiply throughput without weakening per-group safety.
"""

from __future__ import annotations

import hashlib
from typing import Optional

__all__ = ["ShardRouter", "jump_hash"]

_JUMP_MULT = 2862933555777941757  # the 64-bit LCG constant of the paper
_MASK64 = (1 << 64) - 1


def jump_hash(key: int, buckets: int) -> int:
    """Jump consistent hash (Lamping & Veach 2014): uniform, stateless,
    and monotone — growing ``buckets`` only ever moves keys INTO the new
    buckets, never between old ones."""
    if buckets <= 0:
        raise ValueError(f"buckets must be positive, got {buckets}")
    key &= _MASK64
    b, j = -1, 0
    while j < buckets:
        b = j
        key = (key * _JUMP_MULT + 1) & _MASK64
        j = int((b + 1) * (1 << 31) / ((key >> 33) + 1))
    return b


class ShardRouter:
    """Deterministic, epoch-tagged client-id -> shard mapping.

    ``route`` hashes the client id (blake2b-64, keyed by ``seed`` so
    disjoint deployments get independent mappings) and jump-hashes into
    ``num_shards`` buckets.  ``reshard`` installs a new shard count AS A
    NEW EPOCH — the router keeps the full ``(epoch, num_shards)`` history
    so routing can be pinned to any installed epoch (``route(cid,
    epoch=e)``): the live-reshard drain needs to reason about where a
    client lived *before* and where it lives *after* without the answer
    shifting under it.  Epoch numbers increase strictly but may skip —
    an aborted transition burns its number (its barrier markers may have
    committed) without ever being installed.  The jump hash guarantees
    minimal movement between any two epochs (see module docstring).
    """

    def __init__(self, num_shards: int, seed: int = 0):
        if num_shards <= 0:
            raise ValueError(f"num_shards must be positive, got {num_shards}")
        self._seed = seed
        # canonical 64-bit reduction: distinct seeds in [-2^63, 2^64) get
        # distinct salts (seed=-s and seed=+s must NOT collide)
        self._salt = (seed % (1 << 64)).to_bytes(8, "big")
        #: installed epochs, ascending: (epoch number, shard count)
        self._epochs: list[tuple[int, int]] = [(0, num_shards)]

    @property
    def num_shards(self) -> int:
        return self._epochs[-1][1]

    @property
    def epoch(self) -> int:
        """The latest INSTALLED epoch (a transition in flight that has
        not flipped yet is not an epoch)."""
        return self._epochs[-1][0]

    @property
    def seed(self) -> int:
        return self._seed

    def epochs(self) -> list[tuple[int, int]]:
        """The installed ``(epoch, num_shards)`` history, ascending."""
        return list(self._epochs)

    def shards_at(self, epoch: int) -> int:
        """Shard count governing ``epoch`` — the newest installed epoch
        at or below it (skipped numbers never changed the mapping).
        Scanned from the newest end: the hot path (every routed submit
        asks about the ACTIVE epoch) resolves in O(1); only recovery-time
        queries about ancient epochs walk deeper."""
        for e, s in reversed(self._epochs):
            if e <= epoch:
                return s
        raise ValueError(
            f"epoch {epoch} predates the router's first epoch "
            f"{self._epochs[0][0]}"
        )

    def key_of(self, client_id) -> int:
        """The stable 64-bit hash a client id routes by (exposed so tests
        and drain tooling can reason about placement)."""
        raw = client_id if isinstance(client_id, (bytes, bytearray)) \
            else str(client_id).encode()
        return int.from_bytes(
            hashlib.blake2b(raw, digest_size=8, key=self._salt).digest(),
            "big",
        )

    def route(self, client_id, epoch: Optional[int] = None) -> int:
        """The shard index owning ``client_id`` — in the current epoch by
        default, or pinned to any installed ``epoch``.  A client key never
        mixes epochs: for a fixed epoch the answer is a pure function of
        (seed, client_id, shards_at(epoch))."""
        shards = self.num_shards if epoch is None else self.shards_at(epoch)
        return jump_hash(self.key_of(client_id), shards)

    def route_with(self, client_id, num_shards: int) -> int:
        """Where ``client_id`` WOULD live under ``num_shards`` — the pure
        prospective mapping the drain uses before the new epoch is
        installed (moved iff route_with(c, S) != route_with(c, S'))."""
        return jump_hash(self.key_of(client_id), num_shards)

    def moved(self, client_id, old_shards: int, new_shards: int) -> bool:
        """Does ``client_id``'s owning shard change between the two shard
        counts?  The per-client drain predicate of a live reshard."""
        return (self.route_with(client_id, old_shards)
                != self.route_with(client_id, new_shards))

    def moved_fraction(self, old_shards: int, new_shards: int,
                       sample: int = 2048) -> float:
        """Measured fraction of a deterministic key sample that moves
        between the two shard counts — the jump hash bounds it by
        ~|S'-S|/max(S,S'); benches report the measured value."""
        if sample <= 0:
            raise ValueError("sample must be positive")
        moved = sum(
            1 for k in range(sample)
            if self.moved(f"moved-probe-{k}", old_shards, new_shards)
        )
        return moved / sample

    def reshard(self, num_shards: int, epoch: Optional[int] = None) -> dict:
        """Install a new shard count as a new epoch.

        ``epoch`` defaults to ``self.epoch + 1``; an orchestrator that
        burned numbers on aborted transitions passes its own (strictly
        greater) allocation.  Returns ``{"old": S, "new": S',
        "epoch": e}`` for the caller's log/journal.  The caller owns
        draining: requests already routed keep their old shard's dedup
        history, so a deployment shrinking S must quiesce the moved
        key-ranges first (exactly the Mir-BFT epoch-change dance); this
        object only guarantees the MAPPING moves minimally and stays
        queryable per epoch."""
        if num_shards <= 0:
            raise ValueError(f"num_shards must be positive, got {num_shards}")
        e = self.epoch + 1 if epoch is None else int(epoch)
        if e <= self.epoch:
            raise ValueError(
                f"epoch must exceed the installed {self.epoch}, got {e}"
            )
        old = self.num_shards
        self._epochs.append((e, num_shards))
        return {"old": old, "new": num_shards, "epoch": e}
