"""ShardSet: S independent consensus groups behind one front door.

The composition root of sharded mode (README "Sharded mode").  A shard is
an independent consensus group — its own membership, WAL directories, and
totally-ordered chain — and the ShardSet owns everything that spans them:

* the client-facing **front door**: ``submit`` routes by client id through
  a deterministic :class:`~smartbft_tpu.shard.router.ShardRouter` and
  forwards into the owning shard's request pool (per-shard backpressure
  applies; ``occupancy`` exposes the combined surface);
* the **delivery multiplexer**: ``poll_committed`` drains each shard's
  newly committed decisions into one :class:`~smartbft_tpu.shard.mux.
  DeliveryMux` stream, enforcing per-shard exactly-once/gapless;
* **metrics roll-up**: ``stats_block`` emits per-shard blocks (decisions,
  committed requests, pool occupancy, protocol-plane delta) plus the
  aggregate, including the shared verify plane's cross-shard wave
  attribution when a coalescer is attached.

The ShardSet is deliberately generic over a small shard-handle protocol
(duck-typed; see :class:`ShardHandle`) so the same front door drives the
in-process test harness (``testing.sharded.AppShard`` — n test Apps over
one group-namespaced network) and an embedder's production wiring (S
``Consensus`` facades over real transports).  What makes the set more
than S independent processes is the SHARED verify plane: every shard's
CryptoProvider is constructed over ONE ``AsyncBatchCoalescer`` /
``JaxVerifyEngine`` (each provider tagged with its shard id), so
prepare/commit verify waves from all shards coalesce into common device
launches — cross-shard fill is the throughput multiplier, and the fault
plane (deadline / retry / host-fallback breaker) degrades or recovers all
shards coherently because it IS one plane.
"""

from __future__ import annotations

import abc
from typing import Optional, Sequence

from .mux import DeliveryMux, ShardStreamViolation
from .router import ShardRouter

__all__ = ["ShardHandle", "ShardSet"]


class ShardHandle(abc.ABC):
    """What the ShardSet needs from one consensus group.

    ``testing.sharded.AppShard`` is the in-process implementation; a
    production embedder wraps its per-shard ``Consensus`` facade + ledger
    the same way.  Implementations are matched by duck typing — this ABC
    documents the protocol and provides the registration hook."""

    shard_id: int

    @abc.abstractmethod
    async def start(self) -> None: ...

    @abc.abstractmethod
    async def stop(self) -> None: ...

    @abc.abstractmethod
    async def submit(self, raw_request: bytes) -> None:
        """Forward one raw request into this shard's pool (its leader's
        submit path: blocks on a full pool, raises on closed/no-leader)."""

    @abc.abstractmethod
    def poll_committed(self, since: int) -> list:
        """Committed decisions from chain position ``since`` (0-based) on,
        each as ``(seq, request_ids, decision)``."""

    @abc.abstractmethod
    def pool_occupancy(self) -> dict: ...

    def stats_block(self) -> dict:
        """Optional per-shard extras merged into the roll-up."""
        return {}


class ShardSet:
    """S shard handles + router + delivery mux behind one surface."""

    def __init__(self, shards: Sequence, router: Optional[ShardRouter] = None,
                 coalescer=None):
        """``shards``: shard handles, one per group; their ``shard_id``
        must be 0..S-1 (the router's bucket space).  ``coalescer``: the
        SHARED AsyncBatchCoalescer all shards verify through — optional,
        but without it the set is just S processes glued together; with it
        ``stats_block`` reports the cross-shard wave mix and breaker
        state.  ``router`` defaults to a seed-0 ShardRouter over S."""
        self.shards = {int(s.shard_id): s for s in shards}
        if sorted(self.shards) != list(range(len(shards))):
            raise ValueError(
                f"shard ids must be 0..{len(shards) - 1}, "
                f"got {sorted(self.shards)}"
            )
        self.router = router or ShardRouter(len(shards))
        if self.router.num_shards != len(shards):
            raise ValueError(
                f"router covers {self.router.num_shards} shards, "
                f"set has {len(shards)}"
            )
        self.coalescer = coalescer
        self.mux = DeliveryMux(sorted(self.shards))
        #: per-shard chain cursor for poll_committed
        self._chain_pos: dict[int, int] = {s: 0 for s in self.shards}
        self.submitted = 0

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        for s in sorted(self.shards):
            await self.shards[s].start()

    async def stop(self) -> None:
        for s in sorted(self.shards):
            await self.shards[s].stop()

    # -- the front door ----------------------------------------------------

    def route(self, client_id) -> int:
        return self.router.route(client_id)

    async def submit(self, client_id, raw_request: bytes) -> int:
        """Route ``client_id``'s request to its owning shard and forward
        into that shard's pool.  Returns the shard id it landed on.

        Backpressure is PER SHARD and real: a full pool parks this
        submitter exactly as a single-group deployment would (Pool.submit
        waits up to submit_timeout, then raises), and other shards'
        intake is unaffected — one hot shard cannot stall the set."""
        sid = self.router.route(client_id)
        shard = self.shards.get(sid)
        if shard is None:
            raise ValueError(
                f"client {client_id!r} routes to shard {sid}, but this set "
                f"has shards 0..{self.num_shards - 1} — after router."
                f"reshard() the embedder must rebuild the ShardSet with the "
                f"new groups (and drain removed ones) before submitting"
            )
        await shard.submit(raw_request)
        self.submitted += 1
        return sid

    def occupancy(self) -> dict:
        """Combined submit/backpressure surface over the per-shard pools."""
        per = {s: self.shards[s].pool_occupancy() for s in sorted(self.shards)}
        live = [o for o in per.values() if o]
        return {
            "per_shard": per,
            "total_size": sum(o.get("size", 0) for o in live),
            "total_free": sum(o.get("free", 0) for o in live),
            "total_waiters": sum(o.get("waiters", 0) for o in live),
        }

    # -- the combined committed stream -------------------------------------

    def poll_committed(self) -> list:
        """Drain newly committed decisions from every shard into the mux.

        Returns the new :class:`~smartbft_tpu.shard.mux.CommittedEntry`
        list (combined arrival order).  Raises
        :class:`~smartbft_tpu.shard.mux.ShardStreamViolation` if any
        shard's feed broke gaplessness or exactly-once — the set fails
        loudly rather than applying a forked shard's entries."""
        start = self.mux.total()
        for sid in sorted(self.shards):
            pos = self._chain_pos[sid]
            fresh = self.shards[sid].poll_committed(pos)
            for seq, request_ids, decision in fresh:
                self.mux.ingest(sid, decision, seq=seq,
                                request_ids=request_ids)
            self._chain_pos[sid] = pos + len(fresh)
        return self.mux.since(start)

    def committed_requests(self, shard_id: Optional[int] = None) -> int:
        if shard_id is not None:
            return self.mux.requests_delivered(shard_id)
        return sum(self.mux.requests_delivered(s) for s in self.shards)

    # -- metrics roll-up ---------------------------------------------------

    def stats_block(self) -> dict:
        """Per-shard attribution + aggregate, JSON-able for bench rows."""
        per_shard = {}
        for sid in sorted(self.shards):
            shard = self.shards[sid]
            block = {
                "decisions": self.mux.height(sid),
                "committed_requests": self.mux.requests_delivered(sid),
                "pool": shard.pool_occupancy(),
            }
            block.update(shard.stats_block())
            per_shard[sid] = block
        agg = {
            "shards": self.num_shards,
            "decisions": self.mux.total(),
            "committed_requests": self.committed_requests(),
            "submitted": self.submitted,
        }
        if self.coalescer is not None:
            agg["coalescer"] = self.coalescer.shard_snapshot()
            agg["breaker"] = self.coalescer.fault_snapshot()
        return {"per_shard": per_shard, "aggregate": agg}
