"""ShardSet: S independent consensus groups behind one front door.

The composition root of sharded mode (README "Sharded mode").  A shard is
an independent consensus group — its own membership, WAL directories, and
totally-ordered chain — and the ShardSet owns everything that spans them:

* the client-facing **front door**: ``submit`` routes by client id through
  a deterministic :class:`~smartbft_tpu.shard.router.ShardRouter` and
  forwards into the owning shard's request pool (per-shard backpressure
  applies; ``occupancy`` exposes the combined surface);
* the **delivery multiplexer**: ``poll_committed`` drains each shard's
  newly committed decisions into one :class:`~smartbft_tpu.shard.mux.
  DeliveryMux` stream, enforcing per-shard exactly-once/gapless, and
  prunes applied entries automatically behind a bounded retention window;
* the **epoch state machine**: ``reshard`` grows or shrinks the set UNDER
  LIVE TRAFFIC — the resize decision commits through each old shard's own
  ordered stream as a barrier command, moved key-ranges drain behind the
  barrier, the router flips atomically to the new epoch, and the mux
  stays gapless/exactly-once across the transition.  Every transition
  edge is journaled (:class:`~smartbft_tpu.shard.epoch.EpochJournal`) so
  a coordinator crash mid-drain, mid-handoff, or mid-flip recovers into
  the correct epoch;
* **metrics roll-up**: ``stats_block`` emits per-shard blocks (decisions,
  committed requests, pool occupancy, protocol-plane delta) plus the
  aggregate, including the shared verify plane's cross-shard wave
  attribution when a coalescer is attached, and a ``reshard`` block
  (epoch, transition count, last transition's barriers/drain/pause).

The live-reshard contract at the front door: submits for UNMOVED clients
never notice a transition; submits for MOVED clients park until the flip
(they then route to their new shard) and raise the single loud
:class:`~smartbft_tpu.shard.epoch.ShardEpochError` only when the bounded
drain deadline expires first.  There are still NO cross-shard
transactions — resharding moves key-ranges between groups, it does not
order across them.

The ShardSet is deliberately generic over a small shard-handle protocol
(duck-typed; see :class:`ShardHandle`) so the same front door drives the
in-process test harness (``testing.sharded.AppShard`` — n test Apps over
one group-namespaced network) and an embedder's production wiring (S
``Consensus`` facades over real transports).  What makes the set more
than S independent processes is the SHARED verify plane: every shard's
CryptoProvider is constructed over ONE ``AsyncBatchCoalescer`` /
``JaxVerifyEngine`` (each provider tagged with its shard id), so
prepare/commit verify waves from all shards coalesce into common device
launches — cross-shard fill is the throughput multiplier, and the fault
plane (deadline / retry / host-fallback breaker) degrades or recovers all
shards coherently because it IS one plane.
"""

from __future__ import annotations

import abc
import asyncio
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from .epoch import (
    RESHARD_CLIENT,
    EpochJournal,
    ShardEpochError,
    barrier_marker,
    recover_epochs,
)
from .mux import DeliveryMux, ShardStreamViolation
from .router import ShardRouter
from ..core.pool import (
    AdmissionRejected,
    ReqAlreadyExistsError,
    ReqAlreadyProcessedError,
    SubmitTimeoutError,
)
from ..metrics import CommitLatencyTracker
from ..utils.tasks import create_logged_task

__all__ = ["ShardHandle", "ShardSet"]


class ShardHandle(abc.ABC):
    """What the ShardSet needs from one consensus group.

    ``testing.sharded.AppShard`` is the in-process implementation; a
    production embedder wraps its per-shard ``Consensus`` facade + ledger
    the same way.  Implementations are matched by duck typing — this ABC
    documents the protocol and provides the registration hook."""

    shard_id: int

    @abc.abstractmethod
    async def start(self) -> None: ...

    @abc.abstractmethod
    async def stop(self) -> None: ...

    @abc.abstractmethod
    async def submit(self, raw_request: bytes) -> None:
        """Forward one raw request into this shard's pool (its leader's
        submit path: blocks on a full pool, raises on closed/no-leader)."""

    @abc.abstractmethod
    def poll_committed(self, since: int) -> list:
        """Committed decisions from chain position ``since`` (0-based) on,
        each as ``(seq, request_ids, decision)``."""

    @abc.abstractmethod
    def pool_occupancy(self) -> dict: ...

    def stats_block(self) -> dict:
        """Optional per-shard extras merged into the roll-up."""
        return {}

    # -- live-reshard surface (optional; reshard() requires them) ----------

    async def submit_barrier(self, epoch: int, old_shards: int,
                             new_shards: int) -> None:
        """Submit epoch ``epoch``'s barrier command into this shard's
        ordered stream (client ``epoch.RESHARD_CLIENT``, request id
        ``epoch.barrier_request_id(epoch)``, payload
        ``epoch.reshard_command_payload(...)`` in the embedder's request
        envelope).  MUST swallow the embedder's already-exists /
        already-processed dedup errors: a recovered coordinator
        re-submits, and the pool's client dedup makes that exactly-once."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support live reshard"
        )

    def pending_client_ids(self) -> Optional[set]:
        """Client ids with requests still pooled (un-committed) anywhere
        in this shard — the drain predicate's input.  None means the
        handle cannot report, and the drain falls back to barrier-only."""
        return None

    def ready(self) -> bool:
        """Can this shard serve submits (e.g. a leader is elected)?  The
        flip waits for every NEW group's readiness so released moved-key
        submitters land on a shard that can actually order them."""
        return True

    def space_waiters(self) -> int:
        """Submitters blocked in this shard's pool space-wait (their
        requests are in NO pool yet, so ``pending_client_ids`` cannot see
        them).  The drain must wait these out too: a waiter admitted
        after the flip would commit its request on the OLD shard — the
        wrong side.  Default reads the occupancy block."""
        occ = self.pool_occupancy() or {}
        return int(occ.get("waiters", 0))

    # -- read plane surface (optional; ISSUE 19) ---------------------------

    def read_replies(self, key: str) -> Optional[list]:
        """Stamped committed-state read replies for ``key`` from this
        shard's replicas, as ``(sender, reply)`` pairs — the quorum
        fan-out's input (each reply exposes the ``core.readplane`` stamp
        fields).  None = this handle cannot serve reads."""
        return None

    def read_quorum_need(self) -> int:
        """Matching stamps that prove commitment for this shard's
        membership (``f+1``)."""
        return 1

    def note_read_outliers(self, outliers: list) -> None:
        """Attribute quorum-read outliers (``(sender, why)`` pairs that
        contradicted an accepted f+1 stamp) to the shard's misbehavior
        accounting — OBSERVED-only evidence, never a shun input (read
        replies are unsigned).  Default: unsupported, drop."""

    # -- snapshot handoff surface (optional; ISSUE 17) ---------------------

    def capture_snapshot(self) -> Optional[dict]:
        """Donor side of the scale-out handoff: a JSON-able application
        snapshot of this shard's committed state (chained digests,
        committed count, recent request ids).  None = unsupported — new
        groups then start fresh, the pre-snapshot behavior."""
        return None

    def install_snapshot(self, snapshot: dict) -> None:
        """Receiver side: seed this NOT-YET-STARTED group from a donor's
        :meth:`capture_snapshot` so scale-out is O(1) in the donor's
        history (dedup memory armed, digests chained)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not accept snapshot handoff"
        )


@dataclass
class _Transition:
    """One in-flight epoch transition (the reshard coordinator's state)."""

    epoch: int
    old_s: int
    new_s: int
    deadline: float                       # wall-clock (time.monotonic)
    phase: str = "prepare"                # prepare|barrier|drain|flip
    barriers: dict = field(default_factory=dict)   # shard -> barrier seq
    barrier_submitted_at: dict = field(default_factory=dict)  # shard -> mono
    flip_event: asyncio.Event = field(default_factory=asyncio.Event)
    failed: Optional[str] = None
    parked: int = 0
    parked_peak: int = 0
    moved_cache: dict = field(default_factory=dict)
    started: float = field(default_factory=time.monotonic)
    drain_ms: float = 0.0

    def moved(self, router: ShardRouter, client_id) -> bool:
        key = str(client_id)
        if key == RESHARD_CLIENT:
            return False
        hit = self.moved_cache.get(key)
        if hit is None:
            hit = router.moved(client_id, self.old_s, self.new_s)
            self.moved_cache[key] = hit
        return hit


class ShardSet:
    """S shard handles + router + delivery mux + epoch machine behind one
    surface."""

    def __init__(self, shards: Sequence, router: Optional[ShardRouter] = None,
                 coalescer=None, *, journal: Optional[EpochJournal] = None,
                 drain_deadline: float = 30.0, retention: int = 4096,
                 on_deliver: Optional[Callable] = None,
                 on_deliver_batch: Optional[Callable] = None,
                 clock: Optional[Callable[[], float]] = None,
                 recorder=None):
        """``shards``: shard handles, one per group; their ``shard_id``
        must be 0..S-1 (the router's bucket space).  ``coalescer``: the
        SHARED AsyncBatchCoalescer all shards verify through — optional,
        but without it the set is just S processes glued together; with it
        ``stats_block`` reports the cross-shard wave mix and breaker
        state.  ``router`` defaults to a seed-0 ShardRouter over S.

        ``journal``: the epoch journal (None = transitions are not
        durable; fine for tests, not for a deployment that reshards).
        ``drain_deadline``: wall-clock seconds a transition may spend
        waiting for barriers + moved-range drain before it aborts and
        parked submits raise ShardEpochError.  ``retention``: max
        combined entries the mux keeps after they have been handed to the
        embedder (the automatic prune watermark); <= 0 disables pruning.
        ``clock``: time source for the per-request commit-latency tracker
        (default wall ``time.monotonic``; deterministic tests inject the
        logical ``Scheduler.now``)."""
        self.shards = {int(s.shard_id): s for s in shards}
        if sorted(self.shards) != list(range(len(shards))):
            raise ValueError(
                f"shard ids must be 0..{len(shards) - 1}, "
                f"got {sorted(self.shards)}"
            )
        self.router = router or ShardRouter(len(shards))
        if self.router.num_shards != len(shards):
            raise ValueError(
                f"router covers {self.router.num_shards} shards, "
                f"set has {len(shards)}"
            )
        self.coalescer = coalescer
        self.journal = journal
        self.drain_deadline = drain_deadline
        self.retention = retention
        self.mux = DeliveryMux(sorted(self.shards), on_deliver=on_deliver,
                               on_deliver_batch=on_deliver_batch)
        #: per-shard chain cursor for poll_committed
        self._chain_pos: dict[int, int] = {s: 0 for s in self.shards}
        #: shards retired by scale-in flips (stopped, history in the mux)
        self.retired: dict[int, object] = {}
        self.submitted = 0
        #: submit→commit latency + shed accounting (README "Overload
        #: behavior"): ``submit(..., request_key=...)`` stamps arrivals,
        #: ``poll_committed`` resolves them against the combined stream
        self.latency = CommitLatencyTracker(clock=clock)
        #: flight recorder for control-plane transitions (reshard epochs);
        #: the nop singleton when tracing is off (obs.recorder contract)
        from ..obs.recorder import NOP_RECORDER

        self.recorder = recorder if recorder is not None else NOP_RECORDER
        self._epoch = self.router.epoch
        self._next_epoch = self._epoch + 1
        self._transition: Optional[_Transition] = None
        self.reshard_stats: dict = {"transitions": 0, "aborts": 0,
                                    "last": None}
        #: front-door read accounting (ISSUE 19): quorum reads routed
        #: through :meth:`read` — served/no-quorum/outlier counts for the
        #: ``read`` stats block (per-replica serving counters live on the
        #: handles' replicas)
        self.read_stats: dict = {"reads": 0, "served": 0, "no_quorum": 0,
                                 "unsupported": 0, "outliers": 0}
        self._recovered: Optional[dict] = None
        if journal is not None:
            self._recover(journal)

    # -- journal recovery --------------------------------------------------

    def _recover(self, journal: EpochJournal) -> None:
        """Fold a journal replay into this (re)constructed set.

        Completed epochs re-anchor the epoch counter.  An incomplete
        transition that already journaled its FLIP took effect — the
        caller must have rebuilt the set with the new epoch's handles (we
        verify the count) and we complete it with a ``done``.  One that
        never flipped is aborted (its barrier markers, if any committed,
        are inert history; its epoch number stays burned)."""
        facts = recover_epochs(journal.replay())
        self._recovered = facts
        epoch = facts["epoch"]
        self._next_epoch = max(self._next_epoch, facts["next_epoch"])
        inc = facts["incomplete"]
        if not (inc is not None and inc["flipped"]) and epoch > 0 \
                and facts["shards"] is not None \
                and len(self.shards) != facts["shards"]:
            # a COMPLETED epoch pins the shard count just as hard as a
            # flipped-incomplete one: rebuilding with a stale count would
            # install a mapping that never existed as this epoch, letting
            # a moved client's pre-crash commit recommit elsewhere.  A
            # trailing UNFLIPPED prepare does not relax this — it aborts
            # below and the completed epoch's count still governs.
            raise ShardEpochError(
                f"journal says epoch {epoch} completed with "
                f"{facts['shards']} shards but the set was rebuilt with "
                f"{len(self.shards)} — recover with that epoch's handles"
            )
        if inc is not None:
            if inc["flipped"]:
                epoch = max(epoch, inc["epoch"])
                if len(self.shards) != inc["new"]:
                    raise ShardEpochError(
                        f"journal says epoch {inc['epoch']} flipped to "
                        f"{inc['new']} shards but the set was rebuilt with "
                        f"{len(self.shards)} — recover with the new epoch's "
                        f"handles"
                    )
                journal.append({"t": "done", "epoch": inc["epoch"]})
            else:
                journal.append({
                    "t": "abort", "epoch": inc["epoch"],
                    "reason": "coordinator recovery before flip",
                })
                self.reshard_stats["aborts"] += 1
        if epoch > self._epoch:
            # re-install the recovered epoch so route(epoch=...) history
            # has the correct anchor (mapping = current handle count)
            self.router.reshard(len(self.shards), epoch=epoch)
            self._epoch = epoch
            self.mux.begin_epoch(epoch, sorted(self.shards))
        self._next_epoch = max(self._next_epoch, self._epoch + 1)

    # -- basics ------------------------------------------------------------

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    @property
    def epoch(self) -> int:
        """The ACTIVE epoch this set routes in (the router may know newer
        installed epochs only transiently, mid-flip)."""
        return self._epoch

    @property
    def reshard_in_progress(self) -> bool:
        return self._transition is not None

    @property
    def reshard_phase(self) -> Optional[str]:
        return self._transition.phase if self._transition else None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        for s in sorted(self.shards):
            await self.shards[s].start()

    async def stop(self) -> None:
        for s in sorted(self.shards):
            await self.shards[s].stop()
        if self.journal is not None:
            self.journal.close()

    # -- the front door ----------------------------------------------------

    def route(self, client_id) -> int:
        return self.router.route(client_id, epoch=self._epoch)

    async def submit(self, client_id, raw_request: bytes,
                     *, request_key: Optional[str] = None) -> int:
        """Route ``client_id``'s request to its owning shard (in the
        ACTIVE epoch) and forward into that shard's pool.  Returns the
        shard id it landed on.

        Backpressure is PER SHARD and real: a full pool parks this
        submitter exactly as a single-group deployment would (Pool.submit
        waits up to its TOTAL submit deadline, then sheds), and other
        shards' intake is unaffected — one hot shard cannot stall the
        set.  With ``admission_high_water`` configured on the shard's
        pool, an over-the-knee submit fails fast with
        :class:`~smartbft_tpu.core.pool.AdmissionRejected` (retry-after
        hint attached) instead of queueing — both shed shapes are counted
        in ``latency.shed`` and re-raised to the caller.

        ``request_key``: the committed-stream id of this request (the
        ``str(RequestInfo)`` form, ``"client:request"``).  When given,
        the front door stamps submit→commit latency for it — arrival is
        stamped HERE, before any admission/park wait, so the measured
        latency is what the client experiences.

        During a live reshard, a client whose key-range is MOVING parks
        here until the epoch flips (then lands on its new shard); if the
        bounded drain deadline expires first, it gets ShardEpochError.
        Unmoved clients submit straight through the whole transition.
        Parked-at-barrier submitters are COUNTED in :meth:`occupancy`
        (``total_waiters`` / ``parked_moved``) — the admission gate and
        the autoscaler must see the same pressure the clients feel."""
        # fresh=False on a retry of a still-pending request: the ORIGINAL
        # stamp keeps measuring from the first submit, and a failure of
        # THIS attempt must not erase it (the pending request still
        # commits) — dedup/shed handling below keys off `fresh`
        fresh = (self.latency.on_submitted(request_key)
                 if request_key is not None else False)
        try:
            tr = self._transition
            if tr is not None and tr.moved(self.router, client_id):
                tr.parked += 1
                tr.parked_peak = max(tr.parked_peak, tr.parked)
                try:
                    await self._wait_for_flip(tr)
                finally:
                    tr.parked -= 1
            sid = self.router.route(client_id, epoch=self._epoch)
            shard = self.shards.get(sid)
            if shard is None:
                raise ShardEpochError(
                    f"client {client_id!r} routes to shard {sid} in epoch "
                    f"{self._epoch}, but this set has shards "
                    f"{sorted(self.shards)} — the router was re-pointed "
                    f"outside ShardSet.reshard(); use the epoch protocol"
                )
            await shard.submit(raw_request)
        except ReqAlreadyExistsError:
            # a retry of a still-pending request: not a shed — the
            # original stamp stays and resolves when the request commits
            raise
        except ReqAlreadyProcessedError:
            # duplicate of an already-committed request: no commit is
            # coming for this stamp, and it was not shed either
            if fresh and request_key is not None:
                self.latency.discard(request_key)
            raise
        except AdmissionRejected:
            self.latency.on_shed(request_key if fresh else None, "admission")
            raise
        except SubmitTimeoutError:
            self.latency.on_shed(request_key if fresh else None, "timeout")
            raise
        except BaseException:
            self.latency.on_shed(request_key if fresh else None, "other")
            raise
        self.submitted += 1
        return sid

    async def _wait_for_flip(self, tr: _Transition) -> None:
        remaining = tr.deadline - time.monotonic()
        try:
            await asyncio.wait_for(
                tr.flip_event.wait(), timeout=max(remaining, 0.001)
            )
        except asyncio.TimeoutError:
            raise ShardEpochError(
                f"epoch {tr.epoch} is still draining its moved key-ranges "
                f"and the {self.drain_deadline:.1f}s drain deadline expired "
                f"(phase {tr.phase}, barriers {sorted(tr.barriers)})"
            ) from None
        if tr.failed is not None:
            raise ShardEpochError(
                f"epoch {tr.epoch} transition failed: {tr.failed}"
            )

    def occupancy(self) -> dict:
        """Combined submit/backpressure surface over the per-shard pools.

        Submitters parked at a reshard barrier (moved clients waiting for
        the flip) hold requests NO pool can see yet, but they are load
        all the same: they count into ``total_waiters`` (and separately
        as ``parked_moved``) so the admission gate's occupancy signal and
        the autoscaler's saturation signal agree with client-experienced
        pressure during a transition."""
        per = {s: self.shards[s].pool_occupancy() for s in sorted(self.shards)}
        live = [o for o in per.values() if o]
        total_size = sum(o.get("size", 0) for o in live)
        total_cap = sum(o.get("capacity", 0) for o in live)
        parked = self._transition.parked if self._transition else 0
        return {
            "per_shard": per,
            "total_size": total_size,
            "total_free": sum(o.get("free", 0) for o in live),
            "total_capacity": total_cap,
            "total_waiters": sum(o.get("waiters", 0) for o in live) + parked,
            "parked_moved": parked,
            "shed_admission": sum(o.get("shed_admission", 0) for o in live),
            "shed_timeout": sum(o.get("shed_timeout", 0) for o in live),
            # the autoscaler's saturation signal: filled fraction of the
            # combined pool capacity (0.0 when nothing is reporting)
            "fill": (total_size / total_cap) if total_cap else 0.0,
        }

    def health_signals(self) -> dict:
        """The front door's contribution to a
        :class:`~smartbft_tpu.obs.health.HealthMonitor` — the set-level
        roll-up of the same signals each replica reports for itself:
        combined pool fill (parked moved-client submitters included, the
        client-felt pressure), whether the gate shed (the monitor's
        latch turns the counter into a recent-window signal), and the
        live submit->commit p99 over the set's latency tracker."""
        occ = self.occupancy()
        cap = occ["total_capacity"]
        # client-FELT fill: pooled requests plus waiters (parked moved
        # submitters included) over capacity — the same definition the
        # per-replica pool_signal_source uses, NOT the autoscaler's
        # pooled-only 'fill' (a resharding front door with stalled
        # clients must not read healthy)
        out = {
            "pool.fill": ((occ["total_size"] + occ["total_waiters"]) / cap)
            if cap else 0.0,
            "pool.shed_total": float(occ["shed_admission"]
                                     + occ["shed_timeout"]),
        }
        if self.latency.aggregate.count:
            out["latency.commit_p99_ms"] = \
                self.latency.aggregate.quantile(0.99) * 1e3
        return out

    def health_source(self, *, clock=None):
        """A zero-arg HealthMonitor source over :meth:`health_signals`
        with the shed counter latched into ``pool.shed_recent`` (the
        rule's signal) — counters are monotone, verdicts need recency."""
        import time as _time

        from ..obs.health import EventLatch

        latch = EventLatch(5.0)
        clock = clock or _time.monotonic
        lat_state = {"buckets": None}

        def signals() -> dict:
            sig = self.health_signals()
            shed_total = sig.pop("pool.shed_total", 0.0)
            sig["pool.shed_recent"] = latch.update(
                shed_total, 1.0, clock()
            )
            # recency window over the latency signal (ISSUE 20): the
            # verdict judges the p99 of commits landed since the LAST
            # tick, not the lifetime aggregate — a cumulative p99 never
            # clears after one bad spell, so a controller acting on it
            # would remediate history (obs.health.latency_signal_source
            # applies the same rule per replica)
            hist = self.latency.aggregate
            sig.pop("latency.commit_p99_ms", None)
            if hist.count:
                if lat_state["buckets"] is None:
                    lat_state["buckets"] = list(hist.buckets)
                    sig["latency.commit_p99_ms"] = \
                        hist.quantile(0.99) * 1e3
                else:
                    p99 = hist.delta_quantile(0.99, lat_state["buckets"])
                    if p99 > 0.0:
                        lat_state["buckets"] = list(hist.buckets)
                        sig["latency.commit_p99_ms"] = p99 * 1e3
            return sig

        return signals

    # -- the combined committed stream -------------------------------------

    def poll_committed(self) -> list:
        """Drain newly committed decisions from every live shard into the
        mux.

        Returns the new :class:`~smartbft_tpu.shard.mux.CommittedEntry`
        list (combined arrival order).  Raises
        :class:`~smartbft_tpu.shard.mux.ShardStreamViolation` if any
        shard's feed broke gaplessness or exactly-once — the set fails
        loudly rather than applying a forked shard's entries.

        This is also where two pieces of epoch machinery live: barrier
        DETECTION (an in-flight transition scans fresh entries for its
        committed barrier commands and journals each shard's barrier
        sequence) and the automatic PRUNE (entries handed to the embedder
        by earlier polls are applied by contract; everything beyond the
        ``retention`` window below that watermark is dropped, so long
        soaks do not grow mux memory with history)."""
        start = self.mux.total()
        for sid in sorted(self.shards):
            pos = self._chain_pos[sid]
            fresh = self.shards[sid].poll_committed(pos)
            if fresh:
                # wave-batched hand-off: one mux call (and one application
                # callback) per shard per poll, not one per decision
                self.mux.ingest_batch(sid, fresh)
                self._chain_pos[sid] = pos + len(fresh)
        out = self.mux.since(start)
        self.latency.on_committed_batch(out)
        tr = self._transition
        if tr is not None and len(tr.barriers) < tr.old_s:
            marker = barrier_marker(tr.epoch)
            for e in out:
                if (e.shard_id < tr.old_s and e.shard_id not in tr.barriers
                        and marker in e.request_ids):
                    tr.barriers[e.shard_id] = e.seq
                    self._journal({"t": "barrier", "epoch": tr.epoch,
                                   "shard": e.shard_id, "seq": e.seq})
        if self.retention > 0:
            # never prune entries not yet returned: `start` IS the
            # delivered watermark (everything below it left poll_committed
            # in an earlier call)
            self.mux.prune(min(start, max(0, self.mux.total()
                                          - self.retention)))
        return out

    def read(self, client_id, *, max_lag_decisions: int = 0) -> dict:
        """Route a committed-state READ to ``client_id``'s owning shard
        and decide it with the ``f+1`` match rule (ISSUE 19) — no pool,
        no proposer, no verify launch, and never a consensus round.

        The owning shard fans the read across its replicas
        (``read_replies``), and :func:`~smartbft_tpu.core.readplane.
        quorum_read_decide` accepts when ``f+1`` bit-identical
        ``(found, value, height, digest)`` stamps agree.  Returns the
        decided stamp plus the fan-out accounting; ``ok`` False when no
        stamp reached quorum (partition/churn — retry) or the shard
        cannot serve reads."""
        from ..core.readplane import quorum_read_decide

        self.read_stats["reads"] += 1
        sid = self.router.route(client_id, epoch=self._epoch)
        shard = self.shards.get(sid)
        replies = (shard.read_replies(str(client_id))
                   if shard is not None else None)
        if replies is None:
            self.read_stats["unsupported"] += 1
            return {"ok": False, "shard": sid,
                    "error": "shard cannot serve reads"}
        need = shard.read_quorum_need()
        decision = quorum_read_decide(
            replies, need, max_lag_decisions=max_lag_decisions
        )
        self.read_stats["outliers"] += len(decision.outliers)
        if decision.outliers:
            # same attribution the socket plane's quorum edge performs:
            # observed-only `stale_read` evidence against the outlier
            shard.note_read_outliers(list(decision.outliers))
        out = {
            "ok": decision.winner is not None,
            "shard": sid,
            "need": need,
            "matches": decision.matches,
            "outliers": [(s, why) for s, why in decision.outliers],
        }
        w = decision.winner
        if w is None:
            self.read_stats["no_quorum"] += 1
            return out
        self.read_stats["served"] += 1
        out.update(
            found=bool(w.found), value=bytes(w.value),
            height=int(w.height), state_digest=bytes(w.state_digest),
        )
        return out

    def committed_requests(self, shard_id: Optional[int] = None) -> int:
        if shard_id is not None:
            return self.mux.requests_delivered(shard_id)
        # monotone across flips even when a retired id re-enters as a new
        # generation (the dead incarnation's count is preserved)
        return self.mux.requests_total()

    # -- live reshard ------------------------------------------------------

    def _journal(self, record: dict) -> None:
        if self.journal is not None:
            self.journal.append(record)

    async def reshard(self, new_shards: int, *,
                      make_shard: Optional[Callable] = None,
                      drain_deadline: Optional[float] = None,
                      poll_interval: float = 0.005) -> dict:
        """Grow or shrink the set to ``new_shards`` groups UNDER TRAFFIC.

        The epoch protocol, in order (each edge journaled):

        1. **prepare** — allocate the next epoch number (aborted epochs
           stay burned) and, for scale-out, build + start the new groups
           via ``make_shard(shard_id, epoch)`` (they receive no client
           traffic until the flip);
        2. **barrier** — submit the epoch's barrier command into every
           OLD shard's ordered stream (retrying through leader churn) and
           wait until each shard COMMITS it: that sequence is the shard's
           barrier.  From the moment this coroutine starts, moved-client
           submits park at the front door;
        3. **drain** — wait until no OLD shard still pools a moved
           client's request (retiring shards must drain completely —
           every key they own is moving) so nothing can commit on the
           wrong side of the flip;
        4. **flip** — atomically: install the new epoch in the router,
           open the new epoch in the mux (hand-off dedup snapshot +
           watermark), stop retiring shards, release parked submitters
           into their new shards.

        The whole wait (2+3) is bounded by ``drain_deadline`` wall-clock
        seconds; expiry aborts the transition (journaled), raises
        ShardEpochError here AND to every parked submitter, and leaves
        the set serving the OLD epoch.  Returns the transition summary
        also stored in ``reshard_stats['last']``."""
        if self._transition is not None:
            raise ShardEpochError(
                f"reshard to {new_shards} refused: epoch "
                f"{self._transition.epoch} transition already in progress"
            )
        s_old = len(self.shards)
        s_new = int(new_shards)
        if s_new <= 0:
            raise ValueError(f"new_shards must be positive, got {s_new}")
        if s_new == s_old:
            return {"epoch": self._epoch, "old": s_old, "new": s_new,
                    "noop": True}
        if s_new > s_old and make_shard is None:
            raise ValueError("scale-out needs make_shard(shard_id, epoch)")
        epoch = self._next_epoch
        self._next_epoch += 1
        deadline = time.monotonic() + (drain_deadline or self.drain_deadline)
        self._journal({"t": "prepare", "epoch": epoch,
                       "old": s_old, "new": s_new})
        if self.recorder.enabled:
            self.recorder.record("ctl.reshard_prepare", epoch=epoch,
                                 extra={"old": s_old, "new": s_new})
        tr = _Transition(epoch=epoch, old_s=s_old, new_s=s_new,
                         deadline=deadline)
        self._transition = tr
        new_handles: dict[int, object] = {}
        handoffs: dict[int, Optional[int]] = {}
        flipped = False
        try:
            for sid in range(s_old, s_new):
                h = make_shard(sid, epoch)
                # registered BEFORE start(): a partially started group
                # (start raised halfway) must still be stopped by the
                # abort cleanup, not leak its tasks/registrations
                new_handles[sid] = h
                # snapshot-based handoff (ISSUE 17): seed the new group
                # from a donor's application snapshot BEFORE it starts —
                # scale-out is then O(1) in the donor's history instead
                # of starting fresh.  Donor choice is deterministic
                # (sid % s_old); a handle pair that does not support the
                # surface (capture returns None) keeps the fresh start.
                donor_sid = sid % s_old
                donor = self.shards.get(donor_sid)
                snap = donor.capture_snapshot() if donor is not None \
                    else None
                if snap is not None:
                    h.install_snapshot(snap)
                    handoffs[sid] = donor_sid
                    if self.recorder.enabled:
                        self.recorder.record(
                            "ctl.reshard_handoff", epoch=epoch,
                            seq=int(snap.get("height", 0)),
                            extra={"to": sid, "from": donor_sid},
                        )
                else:
                    handoffs[sid] = None
                await h.start()
                # visible to polling immediately (it commits nothing until
                # the flip routes clients to it), so the flip itself stays
                # a pure metadata operation
                self.shards[sid] = h
                self._chain_pos[sid] = 0
            tr.phase = "barrier"
            await self._drive(tr, lambda: self._barrier_step(tr),
                              poll_interval)
            tr.phase = "drain"
            drain_t0 = time.monotonic()
            retiring = list(range(s_new, s_old))
            await self._drive(tr, lambda: self._drain_step(tr, retiring),
                              poll_interval)
            tr.drain_ms = (time.monotonic() - drain_t0) * 1e3
            # -- flip ------------------------------------------------------
            # journaled first, then applied SYNCHRONOUSLY (no awaits) so a
            # cancellation/crash can only land before the flip exists or
            # after it is fully effective — never in between
            tr.phase = "flip"
            self._journal({"t": "flip", "epoch": epoch,
                           "shards": list(range(s_new))})
            flipped = True
            self.router.reshard(s_new, epoch=epoch)
            self.mux.begin_epoch(epoch, list(range(s_new)),
                                 retire=retiring, barriers=tr.barriers)
            stopping = []
            for sid in retiring:
                h = self.shards.pop(sid)
                self._chain_pos.pop(sid, None)
                self.retired[sid] = h
                stopping.append(h)
            self._epoch = epoch
            if self.recorder.enabled:
                self.recorder.record(
                    "ctl.reshard_flip", epoch=epoch,
                    dur=time.monotonic() - tr.started,
                    extra={"old": s_old, "new": s_new,
                           "drain_ms": round(tr.drain_ms, 2)},
                )
            tr.flip_event.set()
            try:
                self._journal({"t": "done", "epoch": epoch})
            except OSError:
                # the flip edge is durable; recovery completes an
                # unrecorded done identically
                pass
            summary = {
                "epoch": epoch,
                "old": s_old,
                "new": s_new,
                "barriers": dict(sorted(tr.barriers.items())),
                "moved_fraction": round(
                    self.router.moved_fraction(s_old, s_new), 4
                ),
                "drain_ms": round(tr.drain_ms, 2),
                # how long moved-key submits could not land (barrier start
                # to flip) — the "paused submit window" of the bench block
                "paused_submit_ms": round(
                    (time.monotonic() - tr.started) * 1e3, 2
                ),
                "parked_submits_peak": tr.parked_peak,
                # scale-out handoff provenance: new shard -> donor shard
                # (None = fresh start; {} on scale-in)
                "handoffs": handoffs,
            }
            self.reshard_stats["transitions"] += 1
            self.reshard_stats["last"] = summary
            self._transition = None
            # teardown of drained, retired groups happens AFTER the
            # transition is fully committed; noisy stops must not unwind it
            for h in stopping:
                try:
                    await h.stop()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    pass
            return summary
        except BaseException as exc:
            if flipped:
                # the transition is journaled and effective — a post-flip
                # failure (cancelled teardown, done-edge IO error) must
                # neither journal an abort nor un-flip live state
                raise
            tr.failed = f"{type(exc).__name__}: {exc}"
            if self.recorder.enabled:
                self.recorder.record("ctl.reshard_abort", epoch=epoch,
                                     extra={"reason": tr.failed})
            try:
                self._journal({"t": "abort", "epoch": epoch,
                               "reason": tr.failed})
            except OSError:
                # a torn-down coordinator (cancelled mid-transition, journal
                # dir already gone) must surface the ORIGINAL failure, not
                # an abort-bookkeeping IO error; recovery treats a missing
                # abort edge identically (unflipped prepare => abort)
                pass
            self.reshard_stats["aborts"] += 1
            # tear down never-flipped new groups; the old epoch keeps
            # serving exactly as before
            for sid, h in new_handles.items():
                self.shards.pop(sid, None)
                self._chain_pos.pop(sid, None)
                try:
                    await h.stop()
                except Exception:
                    pass
            self._transition = None
            tr.flip_event.set()  # parked submitters wake and see `failed`
            raise

    async def _drive(self, tr: _Transition, step: Callable[[], bool],
                     poll_interval: float) -> None:
        """Run one transition phase: call ``step`` (True = phase done)
        until done or the drain deadline expires."""
        while True:
            if step():
                return
            if time.monotonic() > tr.deadline:
                raise ShardEpochError(
                    f"epoch {tr.epoch} drain deadline expired in phase "
                    f"{tr.phase!r}: barriers={sorted(tr.barriers)}, "
                    f"needed {tr.old_s}"
                )
            await asyncio.sleep(poll_interval)

    #: wall-clock seconds after which an uncommitted barrier is submitted
    #: AGAIN — a replica crash can lose the pooled command entirely (it
    #: lived only in that pool), and re-submission is free under client
    #: dedup, so the barrier phase must keep re-ordering until it COMMITS
    BARRIER_RESUBMIT_INTERVAL = 0.5

    def _barrier_step(self, tr: _Transition) -> bool:
        """(Re)submit barrier commands and poll for their commits."""
        now = time.monotonic()
        for sid in range(tr.old_s):
            if sid in tr.barriers:
                continue
            last = tr.barrier_submitted_at.get(sid)
            if last is not None \
                    and now - last < self.BARRIER_RESUBMIT_INTERVAL:
                continue
            h = self.shards.get(sid)
            if h is None:
                continue
            # fire-and-account: _submit_barrier stamps the attempt time and
            # swallows transient no-leader/full-pool errors so the next
            # step retries — leader churn mid-reshard is normal, and an
            # attempt that LANDED but died with its replica re-submits
            # after the interval above
            create_logged_task(
                self._submit_barrier(h, sid, tr),
                name=f"reshard-barrier-e{tr.epoch}-s{sid}",
            )
        self.poll_committed()
        return len(tr.barriers) >= tr.old_s

    async def _submit_barrier(self, handle, sid: int, tr: _Transition) -> None:
        if sid in tr.barriers:
            return
        tr.barrier_submitted_at[sid] = time.monotonic()
        try:
            await handle.submit_barrier(tr.epoch, tr.old_s, tr.new_s)
        except Exception:
            # transient (no leader yet / pool full / view change): retry
            # on a later step immediately.  Embedder dedup errors are
            # swallowed by submit_barrier itself per the ShardHandle
            # contract.
            tr.barrier_submitted_at.pop(sid, None)

    def _drain_step(self, tr: _Transition, retiring: list[int]) -> bool:
        self.poll_committed()
        for sid in range(tr.old_s, tr.new_s):
            if not self.shards[sid].ready():
                return False
        # submitters parked in a pool's SPACE wait hold requests no pool
        # (and no pending_client_ids) can see yet; one admitted after the
        # flip would commit on the old shard — wait them out (conservative:
        # any old shard's waiter blocks the drain, attribution is unknown)
        for sid in range(tr.old_s):
            h = self.shards.get(sid)
            if h is not None and h.space_waiters():
                return False
        for sid in retiring:
            pend = self.shards[sid].pending_client_ids()
            if pend:
                pend = {c for c in pend if c != RESHARD_CLIENT}
                if pend:  # every key a retiring shard owns is moving
                    return False
        for sid in range(min(tr.old_s, tr.new_s)):
            pend = self.shards[sid].pending_client_ids()
            if not pend:
                continue
            for c in pend:
                if tr.moved(self.router, c):
                    return False
        return True

    # -- metrics roll-up ---------------------------------------------------

    def stats_block(self) -> dict:
        """Per-shard attribution + aggregate, JSON-able for bench rows."""
        per_shard = {}
        for sid in sorted(self.shards):
            shard = self.shards[sid]
            block = {
                "decisions": self.mux.height(sid),
                "committed_requests": self.mux.requests_delivered(sid),
                "pool": shard.pool_occupancy(),
            }
            block.update(shard.stats_block())
            per_shard[sid] = block
        agg = {
            "shards": self.num_shards,
            "epoch": self._epoch,
            "decisions": self.mux.total(),
            "committed_requests": self.committed_requests(),
            "submitted": self.submitted,
        }
        if self.coalescer is not None:
            agg["coalescer"] = self.coalescer.shard_snapshot()
            agg["breaker"] = self.coalescer.fault_snapshot()
            agg["mesh"] = self.coalescer.mesh_snapshot()
        reshard = dict(self.reshard_stats)
        reshard["epoch"] = self._epoch
        reshard["in_progress"] = self.reshard_phase
        reshard["watermarks"] = self.mux.snapshot()["watermarks"]
        return {"per_shard": per_shard, "aggregate": agg, "reshard": reshard,
                "latency": self.latency.snapshot(),
                "read": dict(self.read_stats)}
