"""Snapshot state transfer + log compaction (ISSUE 17).

The catch-up story before this package was full chain replay: a
SIGKILL'd replica re-pulled every committed decision it missed, a
scale-out shard started fresh, and ledgers/WALs grew forever.  This
package is the PBFT stable-checkpoint half the reference survey names
(StateCollector + state transfer): application state is periodically
captured ANCHORED at a committed decision's certificate, written
crash-safely, verified against that certificate on install, and used to
answer "you are too far behind" with snapshot + tail instead of the
whole chain — which is what makes rejoin O(1) in history depth and lets
the pre-horizon ledger/WAL prefix be deleted.
"""

from .store import (
    CHAIN_SEED,
    RECENT_IDS_CAP,
    AppState,
    Snapshot,
    SnapshotError,
    SnapshotManifest,
    SnapshotStore,
    chain_update,
    encode_snapshot_blob,
    fold_ids,
    make_manifest,
    parse_snapshot_blob,
    plan_catchup,
    state_digest,
    verify_anchor,
    verify_manifest_state,
    verify_snapshot,
    verify_tail,
)

__all__ = [
    "CHAIN_SEED",
    "RECENT_IDS_CAP",
    "AppState",
    "Snapshot",
    "SnapshotError",
    "SnapshotManifest",
    "SnapshotStore",
    "chain_update",
    "encode_snapshot_blob",
    "fold_ids",
    "make_manifest",
    "parse_snapshot_blob",
    "plan_catchup",
    "state_digest",
    "verify_anchor",
    "verify_manifest_state",
    "verify_snapshot",
    "verify_tail",
]
