"""Crash-safe snapshot store + the pure verification/planning functions.

On-disk contract (the ``EpochJournal`` idiom, hardened for binary blobs):

* one snapshot is ONE file ``snapshot-%016x.snap`` (hex height), written
  as temp file + flush + fsync + atomic rename + directory fsync — a
  crash at ANY instant leaves either the complete previous snapshot or
  the complete new one, never a half-visible file;
* the file is ``MAGIC | u32 manifest_len | manifest | state_blob``; the
  manifest carries the state blob's size and blake2b digest, so a torn
  or tampered file is DETECTED on open (short header, undecodable
  manifest, size/digest mismatch) and skipped instead of installed;
* the manifest also carries the ANCHOR: the committed decision
  (proposal + signatures) at exactly ``height`` — PBFT's stable
  checkpoint certificate.  ``verify_snapshot`` re-checks the anchor
  against cluster membership and quorum size on every install, so a
  snapshot is never trusted because of where it came from, only because
  of what it proves.

Chain digests: the pre-snapshot ledger prefix is deleted by compaction,
so fork detection can no longer re-hash the whole prefix.  The chained
digest ``d_{i+1} = sha256(d_i || payload_i || metadata_i)`` folds each
decision into a running 32-byte value whose final state is independent
of whether the prefix is still on disk — the manifest pins the chain
value at ``height`` and recovery extends it from there, arriving at a
bit-identical digest to a replica that replayed everything.

Everything in this module is synchronous, lock-free, and pure except
:class:`SnapshotStore`'s file I/O — callers own their locking.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

from ..codec import decode, encode, wiremsg
from ..messages import Proposal, Signature, ViewMetadata

#: snapshot file magic — versioned separately from the manifest's
#: format_version so a reader can reject a foreign file before decoding
SNAP_MAGIC = b"sbftsnp1"

SNAP_SUFFIX = ".snap"
_HDR_LEN = len(SNAP_MAGIC) + 4

#: the chain-digest seed (height 0: nothing folded in yet)
CHAIN_SEED = b"\x00" * 32

#: bounded dedup window carried in AppState: enough ids for the pool to
#: purge in-flight duplicates after an install, without making snapshot
#: size O(history) — which would defeat the whole flat-rejoin point
RECENT_IDS_CAP = 1024


class SnapshotError(Exception):
    """A snapshot failed verification — never install it."""


def chain_update(digest: bytes, payload: bytes, metadata: bytes) -> bytes:
    """Fold one committed decision into the chained ledger digest."""
    h = hashlib.sha256(digest)
    h.update(payload)
    h.update(metadata)
    return h.digest()


def fold_ids(digest: bytes, ids: Iterable[str]) -> bytes:
    """Fold committed request ids ("client:rid") into a chained digest —
    the exactly-once oracle that survives compaction (equality across
    replicas proves identical delivered-request sequences without either
    side holding the full id list)."""
    for rid in ids:
        h = hashlib.sha256(digest)
        h.update(rid.encode())
        digest = h.digest()
    return digest


@wiremsg
class AppState:
    """The bounded application state a snapshot carries for the test
    embedders (socket ``ReplicaApp`` and in-process ``testing.app.App``):
    delivered-request count, the chained ids digest, and a bounded recent
    window for pool dedup/purge after install.  Real embedders supply
    their own state blob; the manifest/digest machinery is agnostic."""

    request_count: int = 0
    ids_digest: bytes = b""
    recent_ids: list[str] = None  # type: ignore[assignment]
    #: the committed KV view the read plane serves (ISSUE 19): key ->
    #: latest committed payload, as parallel lists (the codec's untagged
    #: encoding has no dict shape).  Must ride the snapshot or a
    #: compaction would silently forget every key behind the horizon —
    #: O(distinct keys), which the test embedders bound by client count.
    kv_keys: list[str] = None  # type: ignore[assignment]
    kv_values: list[bytes] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.recent_ids is None:
            object.__setattr__(self, "recent_ids", [])
        if self.kv_keys is None:
            object.__setattr__(self, "kv_keys", [])
        if self.kv_values is None:
            object.__setattr__(self, "kv_values", [])


@wiremsg
class SnapshotManifest:
    """Everything needed to verify + install one snapshot (untagged
    canonical encoding, like every control-plane message)."""

    format_version: int = 1
    #: decisions folded in: ledger[0:height] — the snapshot horizon
    height: int = 0
    #: chained ledger digest at ``height`` (chain_update from CHAIN_SEED)
    chain_digest: bytes = b""
    #: blake2b-32 of the state blob (torn/tamper detection)
    state_digest: bytes = b""
    state_bytes: int = 0
    #: the anchoring certificate: the committed decision at seq ``height``
    anchor_proposal: Proposal = None  # type: ignore[assignment]
    anchor_signatures: list[Signature] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.anchor_proposal is None:
            object.__setattr__(self, "anchor_proposal", Proposal())
        if self.anchor_signatures is None:
            object.__setattr__(self, "anchor_signatures", [])


@dataclass(frozen=True)
class Snapshot:
    """One verified-on-open snapshot: manifest + state blob + file path."""

    manifest: SnapshotManifest
    state: bytes
    path: str = ""


def state_digest(state: bytes) -> bytes:
    return hashlib.blake2b(state, digest_size=32).digest()


def make_manifest(height: int, chain: bytes, state: bytes,
                  anchor_proposal: Proposal,
                  anchor_signatures: Sequence[Signature]) -> SnapshotManifest:
    return SnapshotManifest(
        height=height,
        chain_digest=chain,
        state_digest=state_digest(state),
        state_bytes=len(state),
        anchor_proposal=anchor_proposal,
        anchor_signatures=list(anchor_signatures),
    )


# ---------------------------------------------------------------------------
# pure verification — the sync-poisoning guard's teeth
# ---------------------------------------------------------------------------


def verify_manifest_state(manifest: SnapshotManifest,
                          state: bytes) -> Optional[str]:
    """Blob-integrity half of installation: size + digest must match the
    manifest.  Returns the failure reason, None when clean."""
    if manifest.height <= 0:
        return f"non-positive snapshot height {manifest.height}"
    if len(state) != manifest.state_bytes:
        return (f"state size mismatch: manifest says {manifest.state_bytes}, "
                f"got {len(state)}")
    if state_digest(state) != manifest.state_digest:
        return "state digest mismatch (torn or tampered blob)"
    return None


def verify_anchor(manifest: SnapshotManifest, quorum: int,
                  members: Optional[frozenset] = None) -> Optional[str]:
    """Certificate half of installation: the anchoring decision must sit
    at exactly ``height`` and carry >= quorum distinct signers from the
    known membership.  (Crypto is the embedder's Verifier SPI — the test
    embedders use trivial signatures, so the checks here are structural;
    a production Verifier additionally checks the signature bytes.)"""
    proposal = manifest.anchor_proposal
    if not proposal.metadata:
        return "anchor proposal carries no metadata"
    try:
        md = decode(ViewMetadata, proposal.metadata)
    except Exception as e:  # noqa: BLE001 — hostile input path
        return f"anchor metadata undecodable: {e!r}"
    if md.latest_sequence != manifest.height:
        return (f"anchor sequence {md.latest_sequence} != snapshot height "
                f"{manifest.height}")
    signers = {s.signer for s in manifest.anchor_signatures}
    if members is not None:
        unknown = signers - set(members)
        if unknown:
            return f"anchor signed by unknown nodes {sorted(unknown)}"
    if len(signers) < quorum:
        return (f"anchor certificate has {len(signers)} distinct signers, "
                f"quorum is {quorum}")
    return None


def verify_snapshot(manifest: SnapshotManifest, state: bytes, quorum: int,
                    members: Optional[frozenset] = None) -> Optional[str]:
    """Full install-time verification: blob integrity AND anchor
    certificate.  None means safe to install."""
    return (verify_manifest_state(manifest, state)
            or verify_anchor(manifest, quorum, members))


def verify_tail(decisions: Sequence, from_height: int,
                quorum: int = 0,
                members: Optional[frozenset] = None) -> Optional[str]:
    """Verify a sync tail BEFORE applying it: each decision must sit at
    the exactly-next sequence and (when quorum > 0) carry a plausible
    commit certificate.  ``decisions`` are WireDecision-shaped (a
    ``proposal`` and ``signatures``).  Returns the first failure reason,
    None when the whole tail is consistent."""
    expect = from_height + 1
    for i, wd in enumerate(decisions):
        md_raw = wd.proposal.metadata
        if not md_raw:
            return f"tail[{i}] carries no metadata"
        try:
            md = decode(ViewMetadata, md_raw)
        except Exception as e:  # noqa: BLE001 — hostile input path
            return f"tail[{i}] metadata undecodable: {e!r}"
        if md.latest_sequence != expect:
            return (f"tail[{i}] sequence {md.latest_sequence}, "
                    f"expected {expect}")
        if quorum > 0:
            signers = {s.signer for s in wd.signatures}
            if members is not None:
                unknown = signers - set(members)
                if unknown:
                    return (f"tail[{i}] signed by unknown nodes "
                            f"{sorted(unknown)}")
            if len(signers) < quorum:
                return (f"tail[{i}] has {len(signers)} distinct signers, "
                        f"quorum is {quorum}")
        expect += 1
    return None


def plan_catchup(my_height: int, peer_total: int,
                 peer_snapshot_height: int) -> str:
    """Catch-up planning for a lagging replica: ``"snapshot"`` when the
    peer's snapshot horizon is past our height (the peer compacted the
    prefix away — or fetching it would be O(history) anyway),
    ``"tail"`` when plain decision paging reaches it, ``"none"`` when we
    are already caught up."""
    if peer_total <= my_height:
        return "none"
    if peer_snapshot_height > my_height:
        return "snapshot"
    return "tail"


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------


def _snap_name(height: int) -> str:
    return f"snapshot-{height:016x}{SNAP_SUFFIX}"


def _parse_snap_name(name: str) -> Optional[int]:
    if not (name.startswith("snapshot-") and name.endswith(SNAP_SUFFIX)):
        return None
    stem = name[len("snapshot-"):-len(SNAP_SUFFIX)]
    if len(stem) != 16:
        return None
    try:
        return int(stem, 16)
    except ValueError:
        return None


def encode_snapshot_blob(manifest: SnapshotManifest, state: bytes) -> bytes:
    """The on-disk/on-wire snapshot file image (what SnapshotStore.save
    writes and the chunked FT_SNAP transfer ships)."""
    blob = encode(manifest)
    return SNAP_MAGIC + len(blob).to_bytes(4, "big") + blob + state


def parse_snapshot_blob(data: bytes) -> Optional[tuple[SnapshotManifest, bytes]]:
    """Parse a transferred snapshot file image; None on any structural
    damage (short header, foreign magic, undecodable manifest, blob
    size/digest mismatch) — the receiver treats that as a failed
    transfer, never installs it."""
    if len(data) < _HDR_LEN or data[:len(SNAP_MAGIC)] != SNAP_MAGIC:
        return None
    mlen = int.from_bytes(data[len(SNAP_MAGIC):_HDR_LEN], "big")
    if len(data) < _HDR_LEN + mlen:
        return None
    try:
        manifest = decode(SnapshotManifest, data[_HDR_LEN:_HDR_LEN + mlen])
    except Exception:  # noqa: BLE001 — hostile input path
        return None
    state = data[_HDR_LEN + mlen:]
    if verify_manifest_state(manifest, state) is not None:
        return None
    return manifest, state


def _fsync_dir(dir_path: str) -> None:
    fd = os.open(dir_path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class SnapshotStore:
    """Directory of at most ``keep`` verified snapshots, newest wins.

    ``save`` is atomic (temp + fsync + rename + dir fsync) and prunes
    older snapshots AFTER the new one is durable — a crash between the
    two leaves both, and ``latest`` picks the newer.  ``latest`` verifies
    blob integrity on open and SKIPS torn/tampered files (counted in
    ``rejected_files``) instead of raising: a corrupt snapshot is
    equivalent to no snapshot, the replica falls back to chain sync."""

    def __init__(self, dir_path: str, keep: int = 1):
        self.dir = os.path.normpath(dir_path)
        self.keep = max(1, keep)
        self.rejected_files = 0
        os.makedirs(self.dir, mode=0o700, exist_ok=True)

    def _heights(self) -> list[int]:
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return []
        hs = [h for h in (_parse_snap_name(n) for n in names) if h is not None]
        hs.sort()
        return hs

    def save(self, manifest: SnapshotManifest, state: bytes) -> str:
        """Write one snapshot crash-safely; returns the final path."""
        err = verify_manifest_state(manifest, state)
        if err:
            raise SnapshotError(f"refusing to save inconsistent snapshot: {err}")
        final = os.path.join(self.dir, _snap_name(manifest.height))
        tmp = final + ".tmp"
        blob = encode(manifest)
        with open(tmp, "wb") as fh:
            fh.write(SNAP_MAGIC)
            fh.write(len(blob).to_bytes(4, "big"))
            fh.write(blob)
            fh.write(state)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, final)
        _fsync_dir(self.dir)
        self._gc()
        return final

    def _gc(self) -> None:
        heights = self._heights()
        for h in heights[:-self.keep]:
            try:
                os.remove(os.path.join(self.dir, _snap_name(h)))
            except OSError:
                pass
        # stray temp files from a crash mid-save are garbage by contract
        try:
            for name in os.listdir(self.dir):
                if name.endswith(".tmp"):
                    os.remove(os.path.join(self.dir, name))
        except OSError:
            pass

    def load(self, height: int) -> Optional[Snapshot]:
        return self._read(os.path.join(self.dir, _snap_name(height)))

    def read_range(self, height: int, offset: int,
                   max_bytes: int) -> tuple[int, bytes, bool]:
        """One bounded byte slice of the snapshot FILE at ``height`` —
        ``(total_bytes, data, last)`` with ``total_bytes == 0`` when the
        file is gone (superseded/pruned: the chunked-transfer requester
        restarts against the current offer).  This is the single
        file-open surface both the FT_SNAP chunk server and the
        read-plane's read-at-base path go through; integrity of the
        WHOLE file is the caller's side of the contract (`load` for the
        verified-object path, the transfer receiver's parse for the
        chunked path)."""
        path = os.path.join(self.dir, _snap_name(height))
        try:
            total = os.path.getsize(path)
            with open(path, "rb") as fh:
                fh.seek(offset)
                data = fh.read(max(0, max_bytes))
        except OSError:
            return 0, b"", False
        return total, data, offset + len(data) >= total

    def latest(self) -> Optional[Snapshot]:
        """The newest snapshot that passes blob verification, or None."""
        for h in reversed(self._heights()):
            snap = self._read(os.path.join(self.dir, _snap_name(h)))
            if snap is not None:
                return snap
        return None

    def _read(self, path: str) -> Optional[Snapshot]:
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError:
            return None
        if len(data) < _HDR_LEN or data[:len(SNAP_MAGIC)] != SNAP_MAGIC:
            self.rejected_files += 1
            return None
        mlen = int.from_bytes(data[len(SNAP_MAGIC):_HDR_LEN], "big")
        if len(data) < _HDR_LEN + mlen:
            self.rejected_files += 1
            return None
        try:
            manifest = decode(SnapshotManifest, data[_HDR_LEN:_HDR_LEN + mlen])
        except Exception:  # noqa: BLE001 — torn/foreign manifest
            self.rejected_files += 1
            return None
        state = data[_HDR_LEN + mlen:]
        if verify_manifest_state(manifest, state) is not None:
            self.rejected_files += 1
            return None
        return Snapshot(manifest=manifest, state=state, path=path)

    def disk_bytes(self) -> int:
        total = 0
        for h in self._heights():
            try:
                total += os.path.getsize(os.path.join(self.dir, _snap_name(h)))
            except OSError:
                pass
        return total
