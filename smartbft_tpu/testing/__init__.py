from .network import Network, Node
from .app import App, TestRequest, fast_config

__all__ = ["Network", "Node", "App", "TestRequest", "fast_config"]
