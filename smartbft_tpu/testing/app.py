"""Test application: implements every SPI interface in-process.

Re-design of /root/reference/test/test_app.go:28-494.  Crypto is trivial by
default (signature = node id, verification always succeeds, auxiliary data
passes through) but a real provider (smartbft_tpu.crypto.provider.
P256CryptoProvider) can be injected via ``crypto=`` — then every commit vote
carries a real P-256 signature and verification can genuinely fail.  Plus: a
shared in-memory ledger that doubles as the Synchronizer source,
fault-injection hooks, restart with real per-node WAL dirs, and the fast
test configuration.
"""

from __future__ import annotations

import asyncio
import threading
from dataclasses import dataclass, field
from typing import Optional

from .. import wal as walmod
from ..api import (
    Application,
    Assembler,
    Comm,
    MembershipNotifier,
    RequestInspector,
    Signer,
    Synchronizer,
    Verifier,
)
from ..codec import decode, encode, wiremsg
from ..config import Configuration
from ..consensus import Consensus
from ..messages import Proposal, Signature, ViewMetadata
from ..metrics import InMemoryProvider, MetricsBundle
from ..types import Decision, Reconfig, RequestInfo, SyncResponse
from ..utils.clock import Scheduler
from ..utils.memo import BoundedMemo
from ..utils.logging import RecordingLogger
from .network import Network


@wiremsg
class TestRequest:
    """Mirrors the reference test Request{ClientID, ID} (test/test_app.go)."""

    client_id: str = ""
    request_id: str = ""
    payload: bytes = b""


@wiremsg
class BatchPayload:
    requests: list[bytes] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.requests is None:
            object.__setattr__(self, "requests", [])


def barrier_request_bytes(epoch: int, old_shards: int,
                          new_shards: int) -> bytes:
    """Epoch ``epoch``'s reshard barrier command in the TestRequest
    envelope — the ONE construction both the in-process shard harness
    (AppShard.submit_barrier) and the socket control plane (ControlServer
    cmd=reshard) order through their streams, so the marker the mux scan
    and ReplicaApp.barrier_seq look for can never drift between them."""
    from ..shard.epoch import (
        RESHARD_CLIENT,
        barrier_request_id,
        reshard_command_payload,
    )

    return encode(TestRequest(
        client_id=RESHARD_CLIENT,
        request_id=barrier_request_id(epoch),
        payload=reshard_command_payload(epoch, old_shards, new_shards),
    ))


async def submit_barrier_request(consensus, epoch: int, old_shards: int,
                                 new_shards: int) -> None:
    """Order the barrier command through ``consensus``, treating the
    pool's already-exists/already-processed dedup as success (a recovered
    coordinator re-submits; client dedup makes that exactly-once).
    ``internal=True``: the barrier must not be shed by the client-facing
    admission gate — a reshard is how an over-the-knee deployment scales
    OUT, so the gate refusing its own remediation would lock the cluster
    into shedding forever."""
    from ..core.pool import ReqAlreadyExistsError, ReqAlreadyProcessedError

    try:
        await consensus.submit_request(
            barrier_request_bytes(epoch, old_shards, new_shards),
            internal=True,
        )
    except (ReqAlreadyExistsError, ReqAlreadyProcessedError):
        pass


def fast_config(self_id: int) -> Configuration:
    """test_app.go:28-46 — tight timeouts for tests."""
    return Configuration(
        self_id=self_id,
        request_batch_max_count=10,
        request_batch_max_bytes=10 * 1024 * 1024,
        request_batch_max_interval=0.05,
        incoming_message_buffer_size=200,
        request_pool_size=400,
        request_forward_timeout=1.0,
        request_complain_timeout=2.0,
        request_auto_remove_timeout=30.0,
        view_change_resend_interval=1.0,
        view_change_timeout=10.0,
        leader_heartbeat_timeout=15.0,
        leader_heartbeat_count=10,
        num_of_ticks_behind_before_syncing=10,
        # blocking saves keep the logical clock honest: an awaited fsync
        # wave spans real executor round-trips during which wait_for-driven
        # tests advance timers the protocol never earned (Configuration
        # docstring has the full rationale); production keeps the default ON
        wal_group_commit=False,
        collect_timeout=0.5,
        sync_on_start=False,
        speed_up_view_change=False,
        leader_rotation=False,
        decisions_per_leader=0,
    )


class SharedLedgers:
    """Shared view over every node's committed decisions — the Synchronizer
    source (test_app.go:327-371)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.ledgers: dict[int, list[Decision]] = {}
        # decode memos shared by every in-process replica: the SAME frozen
        # bytes reach all n nodes, so a per-App cache decodes each request
        # (and each proposal payload) once PER REPLICA — at open-loop rates
        # that redundant decode is a top-5 profile line.  Values are
        # immutable (RequestInfo / tuple), so cross-node sharing is safe.
        self.request_infos: BoundedMemo[bytes, "RequestInfo"] = BoundedMemo()
        self.proposal_infos: BoundedMemo[bytes, tuple] = BoundedMemo(512)

    def register(self, node_id: int) -> None:
        with self.lock:
            self.ledgers.setdefault(node_id, [])

    def append(self, node_id: int, decision: Decision) -> None:
        with self.lock:
            self.ledgers.setdefault(node_id, []).append(decision)

    def height(self, node_id: int) -> int:
        with self.lock:
            return len(self.ledgers.get(node_id, []))

    def longest(self, exclude: int) -> list[Decision]:
        with self.lock:
            best: list[Decision] = []
            for nid, ledger in self.ledgers.items():
                if nid == exclude:
                    continue
                if len(ledger) > len(best):
                    best = list(ledger)
            return best

    def get(self, node_id: int) -> list[Decision]:
        with self.lock:
            return list(self.ledgers.get(node_id, []))


class App(Application, Assembler, Comm, Signer, Verifier, RequestInspector,
          Synchronizer, MembershipNotifier):
    """One test node: SPI implementation + fault injection + lifecycle."""

    def __init__(
        self,
        node_id: int,
        network: Optional[Network],
        shared: SharedLedgers,
        scheduler: Scheduler,
        wal_dir: Optional[str] = None,
        config: Optional[Configuration] = None,
        use_metrics: bool = False,
        crypto=None,
        wal_file_size_bytes: Optional[int] = None,
        comm=None,
        recorder=None,
    ):
        self.id = node_id
        self.network = network
        self.shared = shared
        self.scheduler = scheduler
        self.wal_dir = wal_dir
        # tiny segments force frequent rotation — the WAL-growth soak tests
        # use this to observe truncation-driven segment deletion quickly
        self.wal_file_size_bytes = wal_file_size_bytes
        self.config = config or fast_config(node_id)
        self.logger = RecordingLogger(f"app-{node_id}")
        self.lock = threading.Lock()
        # shared across the in-process replica set (see SharedLedgers) —
        # one decode per unique bytes for the WHOLE cluster, not per node
        self._request_id_cache = shared.request_infos
        self._proposal_infos_cache = shared.proposal_infos
        self.verification_seq = 0
        self.delay_sync_by: float = 0.0
        self.membership_changed = False
        # snapshot handoff provenance (ISSUE 17): a node seeded from a
        # donor shard's snapshot starts with the donor's chained digests
        # and committed-request count instead of replaying its history
        self.base_height = 0
        self.base_digest = ""
        self.base_ids_digest = ""
        self.base_request_count = 0
        self.base_recent_ids: list[str] = []
        self.base_kv: dict[str, bytes] = {}
        # read plane (ISSUE 19): the committed KV view (key = client id,
        # value = latest committed payload), folded LAZILY from the
        # shared ledger on each read — O(new decisions) per read via the
        # scan cursor, so the view needs no hook in deliver.  The chain
        # digest is folded alongside so read stamps carry it without an
        # O(ledger) capture per read.  Reads get their own token-bucket
        # gate (off by default) and stats block, same as the socket
        # embedder.
        from ..core.readplane import ReadStats, TokenBucket

        self._kv: dict[str, bytes] = {}
        self._read_scan = 0
        self._read_chain: Optional[bytes] = None
        self._read_gate = TokenBucket(self.config.read_gate_rate,
                                      self.config.read_gate_burst,
                                      clock=scheduler.now if scheduler
                                      is not None else None)
        self.read_stats = ReadStats()
        self.consensus: Optional[Consensus] = None
        self._wal = None
        # transport seam: either the in-process Network (default) or a real
        # socket transport (smartbft_tpu.net.SocketComm) — the SAME App runs
        # over both, which is how the socket tests/bench pair against the
        # in-process rows without touching the protocol stack
        self.comm = comm
        if comm is not None:
            self.node = None
            comm.attach(self)
        else:
            if network is None:
                raise ValueError("App needs a Network or an explicit comm=")
            self.node = network.add_node(node_id)
            self.node.consensus = self
        shared.register(node_id)
        self.metrics = MetricsBundle(InMemoryProvider()) if use_metrics else None
        #: flight recorder handed to this node's Consensus (None = nop):
        #: the chaos/sharded harnesses wire one per replica when tracing
        self.recorder = recorder
        self.clock = scheduler
        # optional real-crypto provider (smartbft_tpu.crypto.provider.
        # P256CryptoProvider); when set, Signer/Verifier crypto methods
        # delegate to it and the View's async batch path is enabled
        self.crypto = crypto
        if crypto is not None and hasattr(crypto, "verify_consenter_sigs_batch_async"):
            self.verify_consenter_sigs_batch_async = (
                crypto.verify_consenter_sigs_batch_async
            )
        if crypto is not None and hasattr(crypto, "configure_fault_policy"):
            # expose the verify-plane wiring seam so Consensus.start can
            # arm launch deadlines / retry / breaker from the Configuration
            self.configure_fault_policy = crypto.configure_fault_policy
        if crypto is not None and hasattr(crypto, "configure_verify_mesh"):
            # mesh-graduation seam: Configuration.verify_mesh_devices
            # reaches the shared coalescer through the same facade wiring
            self.configure_verify_mesh = crypto.configure_verify_mesh
        if crypto is not None and hasattr(crypto, "configure_flush_hold"):
            # occupancy-gating seam: Configuration.verify_flush_hold
            # reaches the shared coalescer the same way
            self.configure_flush_hold = crypto.configure_flush_hold
        if crypto is not None and hasattr(crypto, "configure_misbehavior"):
            # per-sender attribution seam (ISSUE 18): Consensus hands its
            # MisbehaviorTable to the provider so failed verify verdicts
            # are charged to the signer instead of the aggregate counter
            self.configure_misbehavior = crypto.configure_misbehavior

    # ------------------------------------------------------------------ app

    #: in-memory ledger append — never blocks, so the controller may run
    #: deliver inline instead of paying an executor round-trip per decision
    blocking_deliver = False

    def deliver(self, proposal: Proposal, signatures) -> Reconfig:
        decision = Decision(proposal=proposal, signatures=tuple(signatures))
        self.shared.append(self.id, decision)
        return self._reconfig_in(proposal)

    def _reconfig_in(self, proposal: Proposal) -> Reconfig:
        """Scan a committed batch for a reconfiguration transaction
        (test/reconfig.go; the last one in the batch wins)."""
        from .reconfig import RECONFIG_MAGIC, detect_reconfig

        found = Reconfig(in_latest_decision=False)
        if not proposal.payload:
            return found
        # fast path: no request in this batch can be a reconfig unless the
        # magic marker appears somewhere in the raw payload — one memchr
        # scan instead of 500 per-request decodes on every deliver
        if RECONFIG_MAGIC not in proposal.payload:
            return found
        try:
            batch = decode(BatchPayload, proposal.payload)
        except Exception:
            return found
        for raw in batch.requests:
            try:
                req = decode(TestRequest, raw)
            except Exception:
                continue
            reconfig = detect_reconfig(req.payload)
            if reconfig is not None:
                found = reconfig
        return found

    # -- Assembler ---------------------------------------------------------

    def assemble_proposal(self, metadata: bytes, requests) -> Proposal:
        return Proposal(
            header=b"",
            payload=encode(BatchPayload(requests=list(requests))),
            metadata=metadata,
            verification_sequence=self.verification_seq,
        )

    # -- Comm --------------------------------------------------------------

    def send_consensus(self, target_id: int, msg) -> None:
        if self.comm is not None:
            self.comm.send_consensus(target_id, msg)
            return
        self.network.send_consensus(self.id, target_id, msg)

    def broadcast_consensus(self, msg, targets=None) -> None:
        # encode-once fan-out: the transport marshals once and shares the
        # wire bytes (and, in-process, the interned decoded object) across
        # recipients
        if self.comm is not None:
            self.comm.broadcast_consensus(msg, targets)
            return
        self.network.broadcast_consensus(self.id, msg, targets)

    def send_transaction(self, target_id: int, request: bytes) -> None:
        if self.comm is not None:
            self.comm.send_transaction(target_id, request)
            return
        self.network.send_transaction(self.id, target_id, request)

    def nodes(self) -> list[int]:
        if self.comm is not None:
            return self.comm.nodes()
        return self.network.node_ids()

    # -- Signer ------------------------------------------------------------

    def sign(self, data: bytes) -> bytes:
        if self.crypto is not None:
            return self.crypto.sign(data)
        return b"sig-%d" % self.id

    def sign_proposal(self, proposal: Proposal, auxiliary_input: bytes) -> Signature:
        if self.crypto is not None:
            return self.crypto.sign_proposal(proposal, auxiliary_input)
        return Signature(signer=self.id, value=b"sig-%d" % self.id, msg=auxiliary_input)

    # -- Verifier (trivial crypto, test_app.go:237-267) --------------------

    def verify_proposal(self, proposal: Proposal) -> list[RequestInfo]:
        return self.requests_from_proposal(proposal)

    def verify_request(self, raw_request: bytes) -> RequestInfo:
        return self.request_id(raw_request)

    def verify_consenter_sig(self, signature: Signature, proposal: Proposal) -> bytes:
        if self.crypto is not None:
            return self.crypto.verify_consenter_sig(signature, proposal)
        return signature.msg

    def verify_consenter_sigs_batch(self, signatures, proposal: Proposal):
        if self.crypto is not None and hasattr(self.crypto, "verify_consenter_sigs_batch"):
            return self.crypto.verify_consenter_sigs_batch(signatures, proposal)
        # SPI default: sequential loop over verify_consenter_sig
        return super().verify_consenter_sigs_batch(signatures, proposal)

    def verify_signature(self, signature: Signature) -> None:
        if self.crypto is not None:
            return self.crypto.verify_signature(signature)
        return None

    def verification_sequence(self) -> int:
        return self.verification_seq

    def requests_from_proposal(self, proposal: Proposal) -> list[RequestInfo]:
        if not proposal.payload:
            return []
        # memoized per payload: verification, delivery, and sync all
        # re-extract infos from the same (frozen) proposal bytes.  Cached
        # as a tuple (immutable, shared across replicas); callers get a
        # fresh list since some mutate the result.
        infos = self._proposal_infos_cache.get(proposal.payload)
        if infos is None:
            batch = decode(BatchPayload, proposal.payload)
            infos = tuple(self.request_id(r) for r in batch.requests)
            self._proposal_infos_cache.put(proposal.payload, infos)
        return list(infos)

    def auxiliary_data(self, msg: bytes) -> bytes:
        if self.crypto is not None:
            return self.crypto.auxiliary_data(msg)
        return msg

    # -- RequestInspector --------------------------------------------------

    def request_id(self, raw_request: bytes) -> RequestInfo:
        # bounded memo: the same raw bytes are inspected at submit, forward,
        # proposal verification, and removal — and by EVERY replica, since
        # the memo lives on SharedLedgers.  Open-coded get/put keeps the
        # hit path free of per-call closure allocation.
        info = self._request_id_cache.get(raw_request)
        if info is None:
            req = decode(TestRequest, raw_request)
            info = RequestInfo(client_id=req.client_id,
                               request_id=req.request_id)
            self._request_id_cache.put(raw_request, info)
        return info

    # -- MembershipNotifier ------------------------------------------------

    def membership_change(self) -> bool:
        return self.membership_changed

    # -- Synchronizer (test_app.go:327-371) --------------------------------

    def sync(self) -> SyncResponse:
        import time as _time

        if self.delay_sync_by:
            _time.sleep(self.delay_sync_by)
        best = self.shared.longest(exclude=self.id)
        mine = self.shared.get(self.id)
        for decision in best[len(mine):]:
            self.deliver(decision.proposal, list(decision.signatures))
            self._drop_synced_from_pool(decision.proposal)
        mine = self.shared.get(self.id)
        latest = mine[-1] if mine else Decision(proposal=Proposal())
        # a reconfig in the latest synced decision must surface so the facade
        # rebuilds with the new membership (consensus.go:86-100)
        reconfig = (
            self._reconfig_in(latest.proposal) if mine else Reconfig(in_latest_decision=False)
        )
        return SyncResponse(latest=latest, reconfig=reconfig)

    def _drop_synced_from_pool(self, proposal: Proposal) -> None:
        """The socket replicas' wire-sync rule (PR 6), applied to the
        in-process path: a decision this node learned by SYNC (not by its
        own consensus deliver) must still leave the request pool.  A
        pooled copy that survives the sync is re-proposed the moment this
        node becomes leader — measured as duplicate delivery (mux
        ShardStreamViolation) under adaptive-timer view-change churn at
        deep overload, where a deposed-and-synced node retakes leadership
        within milliseconds."""
        consensus = getattr(self, "consensus", None)
        pool = getattr(consensus, "pool", None)
        if pool is None:
            return
        from ..core.pool import remove_delivered_requests

        try:
            infos = self.requests_from_proposal(proposal)
        except Exception:  # noqa: BLE001 — foreign payload: nothing pooled
            return
        remove_delivered_requests(pool, infos, self.logger)

    # ------------------------------------------------------------------ lifecycle

    def _read_wal(self) -> list[bytes]:
        if self.wal_dir is None:
            # in-memory WAL stub: no durability, restart loses protocol state
            class _NopWAL:
                def append(self, entry: bytes, truncate_to: bool) -> None:
                    pass

            self._wal = _NopWAL()
            return []
        kw = {}
        if self.wal_file_size_bytes is not None:
            kw["file_size_bytes"] = self.wal_file_size_bytes
        self._wal, entries = walmod.initialize_and_read_all(
            self.wal_dir, self.logger, **kw
        )
        return entries

    def _latest_metadata(self) -> tuple[ViewMetadata, Proposal, list[Signature]]:
        mine = self.shared.get(self.id)
        if not mine:
            return ViewMetadata(), Proposal(), []
        last = mine[-1]
        md = decode(ViewMetadata, last.proposal.metadata)
        return md, last.proposal, list(last.signatures)

    async def start(self) -> None:
        entries = self._read_wal()
        md, last_proposal, last_sigs = self._latest_metadata()
        self.consensus = Consensus(
            config=self.config,
            application=self,
            assembler=self,
            wal=self._wal,
            wal_initial_content=entries,
            comm=self,
            signer=self,
            verifier=self,
            membership_notifier=self,
            request_inspector=self,
            synchronizer=self,
            logger=self.logger,
            metadata=md,
            last_proposal=last_proposal,
            last_signatures=last_sigs,
            scheduler=self.scheduler,
            metrics=self.metrics,
            viewchanger_tick_interval=0.2,
            heartbeat_tick_interval=0.2,
            recorder=self.recorder,
        )
        # read plane (ISSUE 19): committed-state reads through the facade
        self.consensus.read_hook = self.read_committed
        if self.comm is not None:
            # real transport: point ingest at the fresh Consensus and open
            # the sockets; frames enqueued by consensus.start() (heartbeats,
            # sync) sit in the bounded outboxes until the listener is up
            self.comm.attach(self.consensus)
            await self.comm.start()
            await self.consensus.start()
            self._seed_pool_dedup()
            return
        self.node.consensus = self.consensus
        self.node.start()
        await self.consensus.start()
        self._seed_pool_dedup()

    async def stop(self) -> None:
        if self.consensus is not None:
            await self.consensus.stop()
        if self.comm is not None:
            await self.comm.close()
        else:
            await self.node.stop()
        if self._wal is not None and hasattr(self._wal, "close"):
            self._wal.close()

    async def restart(self) -> None:
        """Crash-restart with WAL recovery (test_app.go:129-143)."""
        await self.stop()
        await self.start()

    async def submit(self, client_id: str, request_id: str, payload: bytes = b"",
                     *, internal: bool = False) -> None:
        req = encode(TestRequest(client_id=client_id, request_id=request_id, payload=payload))
        await self.consensus.submit_request(req, internal=internal)

    async def submit_reconfig(
        self, request_id: str, nodes: list[int], config=None
    ) -> None:
        """Order a reconfiguration transaction (test/reconfig.go pattern).
        internal=True: a reconfig is control plane — the one that raises
        pool capacity or disarms the admission gate must not be shed by
        the very gate it remediates (Consensus.submit_request rationale)."""
        from .reconfig import reconfig_request_payload

        await self.submit("reconfig", request_id,
                          reconfig_request_payload(nodes, config),
                          internal=True)

    def pool_occupancy(self) -> dict:
        """Backpressure snapshot of this node's request pool — the shard
        front door's per-shard surface ({} while stopped)."""
        if self.consensus is None:
            return {}
        return self.consensus.pool_occupancy()

    # -- fault injection convenience --------------------------------------

    def disconnect(self) -> None:
        self.node.disconnect()

    def connect(self) -> None:
        self.node.connect()

    # -- queries -----------------------------------------------------------

    def ledger(self) -> list[Decision]:
        return self.shared.get(self.id)

    def height(self) -> int:
        return self.shared.height(self.id)

    # -- read plane (ISSUE 19) ---------------------------------------------

    def _read_view(self, key: str) -> tuple[int, bytes, Optional[bytes]]:
        """Fold the shared ledger's NEW decisions into the committed KV
        view and running chain digest, then answer ``key`` — all under
        one lock hold so the ``(value, height, digest)`` stamp is a
        consistent cut (never a value newer than its stamped height)."""
        from ..snapshot import CHAIN_SEED, chain_update

        ledger = self.shared.get(self.id)
        with self.lock:
            if self._read_scan > len(ledger) or self._read_chain is None:
                # first read, or a fresh shared view: (re)build from the
                # installed base
                self._read_scan = 0
                self._kv = dict(self.base_kv)
                self._read_chain = (bytes.fromhex(self.base_digest)
                                    if self.base_digest else CHAIN_SEED)
            for d in ledger[self._read_scan:]:
                self._read_chain = chain_update(self._read_chain,
                                                d.proposal.payload,
                                                d.proposal.metadata)
                if not d.proposal.payload:
                    continue
                try:
                    batch = decode(BatchPayload, d.proposal.payload)
                except Exception:  # noqa: BLE001 — foreign payload
                    continue
                for raw in batch.requests:
                    try:
                        req = decode(TestRequest, raw)
                    except Exception:  # noqa: BLE001 — foreign request
                        continue
                    self._kv[req.client_id] = bytes(req.payload)
            self._read_scan = len(ledger)
            return (self.base_height + len(ledger), self._read_chain,
                    self._kv.get(key))

    def serve_read(self, key: str):
        """One keyed read from committed state, stamped — the in-process
        twin of ``ReplicaApp._serve_read`` (same gate, same reply shape),
        which is what lets the shard front door, the chaos oracle, and
        the bench apply the client-side rules of ``core.readplane``
        unchanged across both embedders."""
        from ..net.framing import ReadResponse

        if not self._read_gate.allow():
            self.read_stats.sheds += 1
            spent, burst = self._read_gate.occupancy()
            return ReadResponse(
                key=key, shed=True, shed_kind="read_gate",
                retry_after_ms=int(self._read_gate.retry_after() * 1000),
                occupancy=spent, high_water=burst,
            )
        height, chain, value = self._read_view(key)
        found = value is not None
        self.read_stats.note_served(at_base=False, found=found)
        return ReadResponse(
            key=key, found=found, value=value if found else b"",
            height=height, state_digest=chain,
            anchor_height=self.base_height, at_base=False,
        )

    def read_committed(self, key: str):
        """The facade ``read_hook`` shape: ``(value, height,
        state_digest, anchor_height)`` or None when never written."""
        height, chain, value = self._read_view(key)
        if value is None:
            return None
        return value, height, chain, self.base_height

    # -- snapshot handoff (ISSUE 17) ---------------------------------------

    def capture_snapshot(self) -> dict:
        """Chained application snapshot of this node's committed state —
        the in-process twin of ``smartbft_tpu.snapshot``'s capture: the
        chain digest and request-id digest fold over any installed base
        first, so snapshots CHAIN across repeated handoffs and two nodes
        with the same committed history produce identical digests no
        matter how many snapshot installs either went through."""
        from ..snapshot import (
            CHAIN_SEED,
            RECENT_IDS_CAP,
            chain_update,
            fold_ids,
        )

        chain = (bytes.fromhex(self.base_digest)
                 if self.base_digest else CHAIN_SEED)
        ids_digest = (bytes.fromhex(self.base_ids_digest)
                      if self.base_ids_digest else CHAIN_SEED)
        count = self.base_request_count
        recent = list(self.base_recent_ids)
        kv = dict(self.base_kv)
        ledger = self.ledger()
        for d in ledger:
            chain = chain_update(chain, d.proposal.payload,
                                 d.proposal.metadata)
            try:
                ids = [str(i) for i in
                       self.requests_from_proposal(d.proposal)]
            except Exception:  # noqa: BLE001 — foreign payload shape
                ids = []
            ids_digest = fold_ids(ids_digest, ids)
            count += len(ids)
            recent.extend(ids)
            if not d.proposal.payload:
                continue
            try:
                batch = decode(BatchPayload, d.proposal.payload)
            except Exception:  # noqa: BLE001 — foreign payload
                continue
            for raw in batch.requests:
                try:
                    req = decode(TestRequest, raw)
                except Exception:  # noqa: BLE001 — foreign request
                    continue
                kv[req.client_id] = bytes(req.payload)
        return {
            "height": self.base_height + len(ledger),
            "chain_digest": chain.hex(),
            "ids_digest": ids_digest.hex(),
            "request_count": count,
            "recent_ids": recent[-RECENT_IDS_CAP:],
            # the committed KV view rides the handoff so a seeded node's
            # read stamps match a full-history node's bit-for-bit (ISSUE
            # 19: keys whose last write predates the base must not
            # vanish from quorum reads after a scale-out)
            "kv": {k: v.hex() for k, v in kv.items()},
        }

    def install_base_state(self, snapshot: dict) -> None:
        """Seed this NOT-YET-STARTED node from a donor's
        :meth:`capture_snapshot` — the receiver half of the scale-out
        handoff.  The donor's recent request ids arm the pool's dedup
        memory at :meth:`start`, so a client resubmitting a request the
        donor already committed is refused instead of double-delivered."""
        if self.consensus is not None:
            raise RuntimeError(
                f"node {self.id}: install_base_state on a started node"
            )
        self.base_height = int(snapshot.get("height", 0))
        self.base_digest = str(snapshot.get("chain_digest", ""))
        self.base_ids_digest = str(snapshot.get("ids_digest", ""))
        self.base_request_count = int(snapshot.get("request_count", 0))
        self.base_recent_ids = [str(r) for r in
                                snapshot.get("recent_ids", [])]
        self.base_kv = {str(k): bytes.fromhex(v) for k, v in
                        (snapshot.get("kv") or {}).items()}

    def _seed_pool_dedup(self) -> None:
        pool = getattr(self.consensus, "pool", None)
        if pool is None or not self.base_recent_ids \
                or not hasattr(pool, "seed_processed"):
            return
        infos = []
        for rid in self.base_recent_ids:
            client, sep, req = rid.partition(":")
            if sep:
                infos.append(RequestInfo(client_id=client, request_id=req))
        pool.seed_processed(infos)


async def wait_for(predicate, scheduler: Scheduler, timeout: float = 30.0, step: float = 0.05):
    """Advance logical+real time until predicate() or timeout.

    Drives the shared scheduler in lockstep with the asyncio loop so
    tick-driven timers fire while tasks make progress.
    """
    elapsed = 0.0
    while elapsed < timeout:
        if predicate():
            return
        await asyncio.sleep(0)  # let tasks run
        scheduler.advance_by(step)
        await asyncio.sleep(0.001)
        elapsed += step
    raise TimeoutError(f"condition not met within {timeout}s of logical time")
