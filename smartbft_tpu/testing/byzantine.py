"""Byzantine actor harness (ISSUE 18): misbehave ON THE WIRE, assert the
honest majority stays safe AND live.

Every fault the chaos harness injected before this module was *omissive*
(crash, mute, partition) or *accidental* (bit corruption, device faults).
A Byzantine replica is neither: it runs the real stack and uses the
protocol's own seams against it.  :class:`ByzantineActor` wraps ONE
replica of an in-process cluster (``testing.network`` + ``testing.app``)
and arms attack modes at the replica's transport boundary, so everything
past the wire — intake, vote registration, the verify plane, blacklist
recomputation — is the production code path under attack:

- **equivocation** (``equivocate()``): as leader, send a DIFFERENT
  proposal to every follower at the same (view, seq), with matching
  per-target Prepare digests and genuinely re-signed per-target Commits
  (the actor owns its signing key — the signatures verify; the lie is the
  content).  With per-target-unique variants no digest can reach a
  prepare quorum, so honest replicas stall, complain, and view-change the
  liar out; the send log feeds the equivocation oracle
  (``chaos.Invariants.no_equivocation_commit``).
- **vote forgery** (``forge_votes()``): flood honest replicas with
  well-formed Commits whose ConsenterSigMsg binds the REAL in-flight
  proposal digest (spied off the leader's PrePrepare) but whose signature
  value is garbage.  Each forged vote passes the binding check and costs
  a verify-plane verdict — the resource the attack aims at — until the
  per-sender invalid-vote accounting (``core.misbehavior``) shuns the
  forger and intake sheds its votes for free.  Unique aux bytes per
  forgery make every message wire-unique, churning the bounded intern /
  sig-msg memos (the PR 4 ``LruMemo``s) instead of growing them.
- **stale-view replay** (``stale_replay()`` + ``replay_stale()``):
  re-broadcast recorded votes from superseded views.  Honest intake
  counts them observationally per sender (``stale_view`` is an OBSERVED
  cause — honest replicas racing a view change emit the same shape, so
  it never shuns) and the view's own gating drops them pre-verification.
- **leader censorship** (``censor()``): as leader, silently drop
  forwarded client requests from selected clients.  The followers'
  forward/complain machinery must detect the suppression and vote the
  censor out; the new leader orders the victims' requests from the
  followers' pools.

The fifth attack class — **sync poisoning under load** — happens at the
socket replica's state-transfer plane, not the in-process wire, so it
ships as a self-contained scenario (:func:`sync_poison_round`) over
``net.launch.ReplicaApp`` with scripted donors: one liar serving
forged tails and a garbage snapshot offer while honest donors keep
extending their ledgers mid-sync.  Asserts the certificate checks reject
every lie, ``sync_poisoned`` counts the liar (and ONLY the liar), and
the donor-shun threshold stops even asking it.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from dataclasses import dataclass
from typing import Iterable, Optional

from ..codec import decode, encode
from ..crypto.provider import ConsenterSigMsg
from ..messages import Commit, Message, PrePrepare, Prepare, Signature
from ..types import proposal_digest
from .app import App, BatchPayload, TestRequest

__all__ = [
    "ByzantineActor",
    "SendRecord",
    "sync_poison_round",
]


@dataclass
class SendRecord:
    """One equivocation-relevant outbound consensus send — the oracle's
    evidence: which digest this actor told which follower at (view, seq)."""

    target: int
    view: int
    seq: int
    kind: str  # "preprepare" | "prepare" | "commit"
    digest: str
    mutated: bool = False


class ByzantineActor:
    """Arms attack modes on one replica's wire seams.

    Construct over a started (or about-to-start) :class:`testing.app.App`
    and arm any combination of modes.  The actor never touches consensus
    internals — only ``Node.mutate_send`` (outbound), ``Node.filters``
    (inbound spy; always returns True, never vetoes), the network's
    broadcast injection point, and the facade's ``handle_request`` (the
    censorship seam the transport routes forwarded requests through).
    """

    #: bound on retained send-log / spy-history entries — a soak must not
    #: grow oracle evidence without bound
    LOG_CAP = 4096

    def __init__(self, app: App, network) -> None:
        self.app = app
        self.id = app.id
        self.network = network
        self.node = network.nodes[app.id]
        #: oracle evidence: every (mutated or not) PrePrepare/Prepare/
        #: Commit this actor sent while equivocation was armed
        self.send_log: deque[SendRecord] = deque(maxlen=self.LOG_CAP)
        #: (view, seq) -> {target -> variant digest} for armed equivocation
        self._variants: dict[tuple[int, int], dict[int, str]] = {}
        #: (view, seq, digest) of inbound PrePrepares, newest last — the
        #: forgery flood binds REAL digests so forged votes reach the
        #: verify plane instead of dying at the digest-match gate
        self.spied: deque[tuple[int, int, str]] = deque(maxlen=self.LOG_CAP)
        #: recorded inbound votes for stale replay
        self._history: deque[Message] = deque(maxlen=256)
        # armed-mode flags / counters
        self._equivocating = False
        self._flood_per_preprepare = 0
        self._max_forged: Optional[int] = None
        self._record_history = False
        self._censored_clients: frozenset[str] = frozenset()
        self._spy_installed = False
        self.forged = 0
        self.forged_prepares = 0
        self.replayed = 0
        self.censored = 0

    # -- mode arming -------------------------------------------------------

    def equivocate(self) -> "ByzantineActor":
        """As leader, tell every follower a different story per (view,
        seq): per-target proposal variants, matching Prepare digests, and
        re-signed per-target Commits."""
        self._equivocating = True
        self._install_mutator()
        return self

    def forge_votes(self, per_preprepare: int = 3,
                    max_forged: Optional[int] = None) -> "ByzantineActor":
        """Flood ``per_preprepare`` forged Commits at every spied
        PrePrepare (bounded by ``max_forged`` total when given)."""
        self._flood_per_preprepare = per_preprepare
        self._max_forged = max_forged
        self._install_spy()
        return self

    def stale_replay(self, keep: int = 256) -> "ByzantineActor":
        """Start recording inbound votes so :meth:`replay_stale` can
        re-broadcast them after the cluster moves past their view."""
        self._history = deque(maxlen=keep)
        self._record_history = True
        self._install_spy()
        return self

    def censor(self, clients: Iterable[str]) -> "ByzantineActor":
        """As leader, silently drop forwarded requests from ``clients``.
        Direct submissions at honest replicas still pool there — the
        complain machinery must detect the suppression and rotate this
        actor out, at which point the new leader orders them."""
        self._censored_clients = frozenset(clients)
        consensus = self.app.consensus
        orig = consensus.handle_request

        async def censored(sender: int, raw: bytes):
            try:
                cid = self.app.request_id(raw).client_id
            except Exception:  # noqa: BLE001 — undecodable: not a victim
                cid = None
            if cid in self._censored_clients:
                self.censored += 1
                return None
            return await orig(sender, raw)

        consensus.handle_request = censored
        return self

    # -- live injection ----------------------------------------------------

    async def flood_unique_prepares(self, count: int, *,
                                    burst: int = 500) -> None:
        """Broadcast ``count`` wire-unique (unsigned) forged Prepares —
        pure decode-plane pressure: every one churns the bounded intern
        memo; none carries a signature, so none reaches the verify plane.
        The LruMemo flood-bound satellite pins memory stays flat.

        Paced in ``burst``-sized waves with a drain wait between them:
        the in-process inboxes are themselves bounded (INCOMING_BUFFER),
        so a synchronous mega-burst would mostly be dropped at the door —
        that is the OTHER flood defense, not the decode-plane one this
        attack targets."""
        import asyncio

        view, seq = 0, 1
        if self.spied:
            view, seq, _ = self.spied[-1]
        peers = [n for n in self.network._gmap(self.node.group).values()
                 if n.id != self.id]
        sent = 0
        while sent < count:
            for _ in range(min(burst, count - sent)):
                sent += 1
                self.forged_prepares += 1
                p = Prepare(
                    view=view, seq=seq,
                    digest=f"byz-forged-{self.id}-{self.forged_prepares}",
                )
                self.network.broadcast_consensus(self.id, p,
                                                 group=self.node.group)
            while any(n._inbox.qsize() > 0 for n in peers):
                await asyncio.sleep(0)

    def replay_stale(self, current_view: Optional[int] = None) -> int:
        """Re-broadcast every recorded vote from a view strictly below
        ``current_view`` (default: the highest view ever recorded —
        replays everything the cluster has moved past).  Returns how many
        went out."""
        if current_view is None:
            current_view = max(
                (m.view for m in self._history), default=0
            )
        n = 0
        for m in list(self._history):
            if m.view < current_view:
                self.network.broadcast_consensus(self.id, m,
                                                 group=self.node.group)
                n += 1
        self.replayed += n
        return n

    # -- oracle surface ----------------------------------------------------

    def equivocated_slots(self) -> list[tuple[int, int]]:
        """(view, seq) pairs where per-target variants went out."""
        return sorted(self._variants)

    def variant_digests(self, view: int, seq: int) -> dict[int, str]:
        return dict(self._variants.get((view, seq), {}))

    def snapshot(self) -> dict:
        return {
            "id": self.id,
            "equivocated_slots": self.equivocated_slots(),
            "sends_logged": len(self.send_log),
            "forged": self.forged,
            "forged_prepares": self.forged_prepares,
            "replayed": self.replayed,
            "censored": self.censored,
            "spied": len(self.spied),
        }

    # -- seams -------------------------------------------------------------

    def _install_mutator(self) -> None:
        if self.node.mutate_send is not None \
                and self.node.mutate_send is not self._mutate:
            raise RuntimeError(
                f"node {self.id} already has a mutate_send hook installed"
            )
        self.node.mutate_send = self._mutate

    def _install_spy(self) -> None:
        if not self._spy_installed:
            self.node.add_filter(self._spy)
            self._spy_installed = True

    def _log(self, target: int, view: int, seq: int, kind: str,
             digest: str, mutated: bool) -> None:
        self.send_log.append(SendRecord(
            target=target, view=view, seq=seq, kind=kind, digest=digest,
            mutated=mutated,
        ))

    def _mutate(self, target: int, msg: Message) -> Optional[Message]:
        """Outbound hook (the network hands a deep copy — mutating here
        can never leak into another recipient's ingest)."""
        if not self._equivocating:
            return msg
        if isinstance(msg, PrePrepare):
            msg = self._variant_preprepare(target, msg)
            self._log(target, msg.view, msg.seq, "preprepare",
                      proposal_digest(msg.proposal), True)
            return msg
        if isinstance(msg, Prepare):
            d = self._variants.get((msg.view, msg.seq), {}).get(target)
            if d is not None:
                msg = dataclasses.replace(msg, digest=d)
            self._log(target, msg.view, msg.seq, "prepare", msg.digest,
                      d is not None)
            return msg
        if isinstance(msg, Commit):
            d = self._variants.get((msg.view, msg.seq), {}).get(target)
            if d is not None:
                msg = self._resign_commit(msg, d)
            self._log(target, msg.view, msg.seq, "commit", msg.digest,
                      d is not None)
            return msg
        return msg

    def _variant_preprepare(self, target: int, msg: PrePrepare) -> PrePrepare:
        """A per-target proposal variant: the original batch plus one
        forged request unique to this target, so every follower computes
        a different digest for the same (view, seq)."""
        proposal = msg.proposal
        try:
            batch = decode(BatchPayload, proposal.payload)
            requests = list(batch.requests)
        except Exception:  # noqa: BLE001 — unexpected payload: leave it
            return msg
        requests.append(encode(TestRequest(
            client_id=f"byz-{self.id}",
            request_id=f"equiv-{msg.view}-{msg.seq}-{target}",
        )))
        variant = dataclasses.replace(
            proposal, payload=encode(BatchPayload(requests=requests))
        )
        self._variants.setdefault((msg.view, msg.seq), {})[target] = \
            proposal_digest(variant)
        return dataclasses.replace(msg, proposal=variant)

    def _resign_commit(self, commit: Commit, digest: str) -> Commit:
        """Re-sign the per-target digest with the actor's REAL key: the
        signature verifies — equivocation is a content lie, not a crypto
        forgery — so safety must come from quorum intersection, not from
        signature rejection."""
        try:
            aux = decode(ConsenterSigMsg, commit.signature.msg).aux
        except Exception:  # noqa: BLE001 — trivial-crypto cluster
            aux = b""
        msg_bytes = encode(ConsenterSigMsg(proposal_digest=digest, aux=aux))
        sig = Signature(signer=self.id, value=self.app.sign(msg_bytes),
                        msg=msg_bytes)
        return dataclasses.replace(commit, digest=digest, signature=sig)

    def _spy(self, msg: Message, sender: int) -> bool:
        """Inbound filter: record, optionally flood; NEVER vetoes."""
        if isinstance(msg, PrePrepare):
            digest = proposal_digest(msg.proposal)
            self.spied.append((msg.view, msg.seq, digest))
            if self._flood_per_preprepare > 0:
                self._flood(msg.view, msg.seq, digest)
        elif self._record_history and isinstance(msg, (Prepare, Commit)):
            self._history.append(msg)
        return True

    def _flood(self, view: int, seq: int, digest: str) -> None:
        """Broadcast forged Commits binding the real in-flight digest:
        each passes the binding check (the spied digest is genuine) and
        costs the verify plane a verdict; the garbage signature value
        then fails, attributed per-signer to THIS actor.  Unique aux per
        forgery keeps every message wire-unique (memo-churn pressure)."""
        for _ in range(self._flood_per_preprepare):
            if self._max_forged is not None \
                    and self.forged >= self._max_forged:
                return
            self.forged += 1
            aux = b"byz-forged-%d-%d" % (self.id, self.forged)
            msg_bytes = encode(ConsenterSigMsg(
                proposal_digest=digest, aux=aux
            ))
            sig = Signature(signer=self.id, value=b"\x00" * 16,
                            msg=msg_bytes)
            commit = Commit(view=view, seq=seq, digest=digest,
                            signature=sig)
            self.network.broadcast_consensus(self.id, commit,
                                             group=self.node.group)


# ---------------------------------------------------------------- sync poison


def _thin_decision(seq: int, signers=(1, 2)):
    """A decision whose certificate is BELOW quorum — the forged-tail
    material a lying donor serves (continuity is correct, so only the
    certificate check can catch it)."""
    from ..messages import Proposal, ViewMetadata

    raw = encode(TestRequest(client_id="byz", request_id=f"forged-{seq}",
                             payload=b"x"))
    md = ViewMetadata(view_id=1, latest_sequence=seq)
    prop = Proposal(header=b"", payload=encode(BatchPayload(requests=[raw])),
                    metadata=encode(md), verification_sequence=0)
    sigs = [Signature(signer=i, value=b"sig-%d" % i, msg=b"")
            for i in signers]
    return prop, sigs


def _committed_history(depth: int, members=(1, 2, 3, 4)):
    """Full-quorum committed decisions 1..depth (the honest donors'
    ledger) — same wire shapes a live cluster commits."""
    from ..messages import Proposal, ViewMetadata
    from ..types import Decision

    out = []
    for seq in range(1, depth + 1):
        raw = encode(TestRequest(client_id="cli", request_id=f"r-{seq}",
                                 payload=b"p"))
        md = ViewMetadata(view_id=1, latest_sequence=seq)
        prop = Proposal(header=b"",
                        payload=encode(BatchPayload(requests=[raw])),
                        metadata=encode(md), verification_sequence=0)
        sigs = tuple(Signature(signer=i, value=b"sig-%d" % i, msg=b"")
                     for i in members)
        out.append(Decision(proposal=prop, signatures=sigs))
    return out


async def sync_poison_round(root: str, *, depth: int = 8, extra: int = 4,
                            liar: int = 2) -> dict:
    """One sync-poisoning-under-load scenario against a real
    ``net.launch.ReplicaApp`` rejoiner (height 0):

    - donor ``liar`` serves forged tails (thin certificates) on its first
      two answers, then an empty tail with a garbage snapshot offer —
      three distinct poisoning shapes;
    - the honest donors keep APPENDING while the rejoiner syncs (each
      answer serves a longer tail than the last — the open-load race);
    - a second sync pass (after the cluster commits ``extra`` more
      decisions) must not even ask the liar: its poisoning streak crossed
      ``SYNC_DONOR_SHUN_THRESHOLD``.

    Returns the observation dict the tier-1 test and the ``--byzantine``
    matrix both assert on.  Wall clock, bounded by the scripted donors —
    cheap to await from a soak round or a test body.
    """
    import os
    from types import SimpleNamespace

    from ..net.framing import WireDecision
    from ..net.launch import SYNC_DONOR_SHUN_THRESHOLD, ReplicaApp

    members = (1, 2, 3, 4)
    base = str(root)
    spec = {
        "node_id": 1,
        "peers": {i: f"uds:{base}/n{i}.sock" for i in members if i != 1},
        "listen": f"uds:{base}/n1.sock",
        "ledger_path": os.path.join(base, "ledger-1.bin"),
        "wal_dir": os.path.join(base, "wal-1"),
    }
    history = _committed_history(depth + extra, members)
    calls = {p: 0 for p in members if p != 1}
    liar_calls = {"sync": 0}
    # the donors' visible height: honest answers keep extending it — the
    # rejoiner races live commits exactly like a real rejoin under load
    served = {"h": depth}

    def _wire(ds):
        return [WireDecision(proposal=d.proposal,
                             signatures=list(d.signatures)) for d in ds]

    async def fake_sync(peer, from_height, timeout=1.0):
        calls[peer] += 1
        if peer == liar:
            liar_calls["sync"] += 1
            if liar_calls["sync"] <= 2:
                # forged tail: correct continuity, thin certificates
                tail = []
                for seq in range(from_height + 1, from_height + 4):
                    prop, sigs = _thin_decision(seq)
                    tail.append(WireDecision(proposal=prop,
                                             signatures=sigs))
                return SimpleNamespace(decisions=tail, snapshot_height=0,
                                       snapshot_bytes=0)
            # then: nothing to serve but a (garbage) snapshot offer
            return SimpleNamespace(decisions=[],
                                   snapshot_height=from_height + 5,
                                   snapshot_bytes=1000)
        h = served["h"]
        tail = _wire(history[from_height:h])
        served["h"] = min(len(history), h + 2)
        return SimpleNamespace(decisions=tail, snapshot_height=0,
                               snapshot_bytes=0)

    async def fake_fetch(peer, height, chunk_bytes=0):
        return b"not a snapshot"  # fails blob integrity -> poisoned

    r = ReplicaApp(spec)
    r._recover_local_state()
    r.transport.request_sync = fake_sync
    r.transport.fetch_snapshot = fake_fetch
    try:
        await r._sync_over_wire()
        height_pass1 = r.height()
        liar_asks_pass1 = calls[liar]
        # the cluster keeps committing; the rejoiner syncs again — the
        # liar's streak crossed the threshold, so it is not even asked
        served["h"] = len(history)
        await r._sync_over_wire()
        return {
            "height_pass1": height_pass1,
            "height": r.height(),
            "target_height": len(history),
            "sync_poisoned": dict(r.sync_poisoned),
            "metrics_poisoned": r.transport.metrics.sync_poisoned,
            "liar": liar,
            "liar_asks_pass1": liar_asks_pass1,
            "liar_asks_total": calls[liar],
            "honest_asks": {p: c for p, c in calls.items() if p != liar},
            "shun_threshold": SYNC_DONOR_SHUN_THRESHOLD,
        }
    finally:
        r.ledger_file.close()
