"""Scripted fault-schedule chaos harness over the in-process network.

Layered on :mod:`smartbft_tpu.testing.network`'s fault primitives, this
module turns ad-hoc fault tests into DECLARATIVE timelines: a schedule is a
list of :class:`ChaosEvent` (leader-mute, crash, restart, partition, heal,
message-corruption, ...) pinned to logical-clock offsets, executed by
:class:`ChaosCluster` while a request pump keeps the protocol under load.
After the run, :class:`Invariants` checks the four properties every
schedule must preserve:

* **fork-free** — pairwise identical ledger prefixes;
* **exactly-once** — no request delivered twice on any ledger, sequences
  gapless from 1;
* **eventual blacklist** — a deposed faulty leader appears in the
  blacklist carried by committed checkpoint metadata (rotation mode);
* **bounded liveness** — once the last fault heals, draining the
  outstanding requests takes at most the batch-count they need plus a
  small fixed slack, measured in WINDOWS (decisions / pipeline_depth).

The harness is mode-agnostic: the same schedule runs single-slot
(pipeline_depth=1, per-decision rotation) and pipelined
(pipeline_depth>1, window-granular rotation) clusters, which is exactly
the parametrization the scenario tests sweep.

Soak entry point (CI, behind ``-m slow``)::

    python -m smartbft_tpu.testing.chaos --soak [--rounds N] [--depth K]

runs randomized schedules against a rotation-on pipelined cluster and
fails loudly on any invariant violation.  ``--sockets`` re-proves the
fault matrix at the SOCKET level: one OS process per replica over the
real ``smartbft_tpu.net`` transport, with SIGKILL-and-rejoin and
slow-link rounds driven by the same :class:`ChaosEvent` vocabulary
(see ``net.cluster.run_socket_schedule``).  ``--shards S`` (with
``--engine-faults``) runs the engine-fault soak against S consensus
groups sharing ONE coalescer/engine — the sharded deployment shape — and
asserts the breaker open/close cycle affects all shards coherently:
every shard keeps committing through the outage on the host fallback,
every shard's traffic shows in the shared plane's per-tag attribution,
and the post-heal close restores them together.
"""

from __future__ import annotations

import asyncio
import dataclasses
import math
import random
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..codec import decode
from ..config import Configuration
from ..core.pool import AdmissionRejected, SubmitTimeoutError
from ..messages import Commit, Prepare, ViewMetadata
from ..metrics import CommitLatencyTracker
from ..utils.clock import Scheduler
from ..utils.tasks import create_logged_task
from .app import App, SharedLedgers, fast_config, wait_for
from .load import OpenLoopPump, ZipfClients
from .network import Network


def chaos_config(
    i: int,
    *,
    depth: int = 1,
    rotation: bool = True,
    decisions_per_leader: int = 1,
    **overrides,
) -> Configuration:
    """Tight-timeout configuration for fault scenarios, pipelined or not.

    ``decisions_per_leader`` is in the configured granularity's units:
    windows when ``depth > 1`` (rotation_granularity='window'), decisions
    otherwise."""
    base = dict(
        leader_rotation=rotation,
        decisions_per_leader=decisions_per_leader if rotation else 0,
        rotation_granularity="window" if depth > 1 else "decision",
        pipeline_depth=depth,
        request_batch_max_count=2,
        request_batch_max_interval=0.05,
        leader_heartbeat_timeout=2.0,
        leader_heartbeat_count=10,
        view_change_timeout=8.0,
        view_change_resend_interval=2.0,
    )
    base.update(overrides)
    return dataclasses.replace(fast_config(i), **base)


# ---------------------------------------------------------------------- events

@dataclass(frozen=True)
class ChaosEvent:
    """One timeline entry: ``action`` applied at logical offset ``at``.

    ``node`` (and ``groups`` members) may be a concrete node id or one of
    two dynamic targets, resolved when the event FIRES:

    - ``"leader"``: whatever node the live cluster currently follows —
      under rotation the leader at schedule-authoring time is meaningless;
    - ``"faulty"``: the node the run's first ``"leader"`` resolution
      picked, so multi-event schedules (mute -> crash -> restart) stay
      aimed at one victim while the cluster rotates around it.

    Actions:

    - ``mute`` / ``unmute``: outbound-only silence (alive but not sending)
    - ``disconnect`` / ``reconnect``: full isolation both ways
    - ``crash`` / ``restart``: stop the consensus process / start it again
      with WAL recovery (a crash-restart pair with downtime in between)
    - ``partition`` / ``heal``: split the mesh into ``groups`` / undo it
    - ``corrupt`` / ``uncorrupt``: mutate a ``fraction`` of the node's
      outbound prepare/commit digests (message corruption)

    Device-plane actions (require ``ChaosCluster(engine_faults=True)``;
    ``node`` is ignored — the engine is shared by every replica, which is
    exactly the blast radius under test):

    - ``engine_hang``: verify launches block until released (the coalescer
      deadline abandons them); ``engine_fail`` (× ``count``): transient
      tunnel-class errors; ``engine_slow`` (``fraction`` seconds of added
      latency); ``engine_permanent``: compile-class error, trips the
      breaker immediately; ``engine_heal``: clear all device faults.
    - ``engine_device_down`` / ``engine_device_restore`` (``count`` =
      mesh device index): MESH-scoped faults — losing one device of an
      N-device verify mesh fails every launch (one logical launch spans
      the whole mesh), so the breaker degrades ALL shards to host
      together and the canary recovers them back onto the mesh.

    Overload actions (the open-loop pump as a schedulable fault — README
    "Overload behavior"):

    - ``load_spike``: start an OPEN-loop Poisson arrival pump at
      ``fraction`` arrivals per logical second over a Zipf-skewed client
      universe of ``count`` ids (``count`` <= 1 means the default 64 —
      the field's dataclass default is 1); arrivals spawn background
      submits that ack, shed (admission / space-wait timeout), or fail,
      all counted in the report, with submit→commit latency stamped per
      request into the cluster's ``latency`` tracker;
    - ``load_stop``: stop the pump (outstanding submits finish or shed).
      A pump still running when the schedule's last event has fired AND
      the baseline submissions are done gets an implicit stop — the run
      must drain, not pump to the hard cap.

    Elastic-shard actions (consumed by :func:`run_reshard_schedule`
    against a ``ShardedCluster``; ``shard`` scopes node-shaped actions to
    one consensus group):

    - ``reshard`` (``count`` = target S): start a live epoch transition
      (split or merge) under the pump's traffic; held until any earlier
      transition completes — epochs are serial by design;
    - ``crash_during_reshard`` (``shard`` + ``node``): crash that replica
      INSIDE the handoff window — the event holds until a transition is
      actually in flight, so the crash always lands mid-drain/mid-flip;
    - ``crash`` / ``restart`` with ``shard`` set: the plain pair, scoped
      to one group.

    Snapshot actions (socket-level only — consumed by
    :func:`~smartbft_tpu.net.cluster.run_socket_schedule` against a
    ``SocketCluster`` built with ``snapshot_interval_decisions > 0``):

    - ``crash_during_snapshot``: wait (bounded by ``fraction`` seconds,
      default 10) for the node's NEXT snapshot capture to land, then
      SIGKILL immediately — the process dies with the fresh snapshot on
      disk and the ledger-compaction/offer plumbing interrupted at an
      arbitrary point; recovery must reconcile.  The deterministic crash
      points (between snapshot write and ledger truncate, torn files,
      mid-chunk) are pinned by the ``tests/test_snapshot.py`` unit tests;
      :func:`~smartbft_tpu.net.cluster.run_snapshot_rejoin` is the
      snapshot-safe end-to-end runner (``run_socket_schedule``'s
      ``committed_ids`` resubmission oracle sees only the post-horizon
      suffix once a replica compacts).
    """

    at: float
    action: str
    node: Optional[object] = None  # int | "leader" | "faulty"
    groups: tuple = ()
    fraction: float = 1.0
    count: int = 1  # engine_fail: consecutive failures; reshard: target S
    shard: Optional[int] = None  # sharded runs: which group a node action hits


def mute_leader_schedule(*, mute_at=2.0, heal_at=14.0) -> list[ChaosEvent]:
    """The canonical faulty-leader schedule: the CURRENT leader goes mute
    (alive, receiving, silent), the cluster deposes it, then it heals."""
    return [
        ChaosEvent(at=mute_at, action="mute", node="leader"),
        ChaosEvent(at=heal_at, action="unmute", node="faulty"),
    ]


def faulty_leader_full_schedule(
    *, mute_at=2.0, crash_at=12.0, restart_at=20.0
) -> list[ChaosEvent]:
    """The acceptance schedule: mute -> crash-restart -> rejoin.  The
    current leader first goes mute (deposed + blacklisted by the remaining
    quorum), then crashes outright, then restarts from its WAL and
    rejoins as a follower."""
    return [
        ChaosEvent(at=mute_at, action="mute", node="leader"),
        ChaosEvent(at=crash_at, action="crash", node="faulty"),
        ChaosEvent(at=restart_at, action="restart", node="faulty"),
        ChaosEvent(at=restart_at, action="unmute", node="faulty"),
    ]


def engine_fault_schedule(
    *, hang_at=2.0, fail_at=60.0, fail_every=20.0, fail_count=20,
    heal_at=120.0,
) -> list[ChaosEvent]:
    """The verify-plane acceptance schedule: the device engine HANGS (the
    launch deadline abandons waves, retries, and the breaker degrades to
    host verify), then un-hangs into three bursts of transient failures
    (the recovery probe keeps failing, so the breaker stays open and
    consensus keeps committing on the host engine), then HEALS — the next
    probe succeeds, the breaker closes, and waves return to the device.

    ``fail_count`` per burst is sized so the probe cannot drain a burst
    before the next one lands (probes are wall-clock; the schedule is
    logical) — recovery is therefore strictly tied to ``engine_heal``."""
    return [
        ChaosEvent(at=hang_at, action="engine_hang"),
        ChaosEvent(at=fail_at, action="engine_fail", count=fail_count),
        ChaosEvent(at=fail_at + fail_every, action="engine_fail", count=fail_count),
        ChaosEvent(at=fail_at + 2 * fail_every, action="engine_fail", count=fail_count),
        ChaosEvent(at=heal_at, action="engine_heal"),
    ]


# ---------------------------------------------------------------------- report

@dataclass
class ChaosReport:
    submitted: int = 0
    committed_at_heal: int = 0
    decisions_at_heal: int = 0
    final_committed: int = 0
    final_decisions: int = 0
    heal_at: float = 0.0
    leaders_seen: set = field(default_factory=set)
    events_fired: list = field(default_factory=list)
    #: (logical t, status, [breaching slo names]) — one entry per
    #: CLUSTER-verdict change from the continuous SLO evaluation
    verdicts: list = field(default_factory=list)
    #: (first fired event t, last fired event t), logical offsets
    fault_span: Optional[tuple] = None
    final_health: Optional[dict] = None
    # open-loop spike accounting (load_spike / load_stop actions)
    spike_offered: int = 0
    spike_acked: int = 0
    spike_shed_admission: int = 0
    spike_shed_timeout: int = 0
    spike_failed: int = 0
    spike_peak_occupancy: int = 0   # max (pooled + parked) on any live node

    @property
    def decisions_after_heal(self) -> int:
        return self.final_decisions - self.decisions_at_heal

    @property
    def spike_shed(self) -> int:
        return self.spike_shed_admission + self.spike_shed_timeout


def assert_health_verdicts(verdicts: list, fault_span: Optional[tuple],
                           final_health: Optional[dict], *,
                           recovery_s: float = 30.0) -> None:
    """The soak health gate (ISSUE 14), shared by the logical-clock and
    socket runners: a ``critical`` verdict is only acceptable inside the
    injected-fault window plus a bounded recovery, and the run must not
    END critical.  With NO fault window (no event ever fired) there is
    no excuse: EVERY critical sample fails — a default window would
    blanket-pass exactly the unexplained criticals the gate exists to
    catch."""
    if fault_span is None:
        stray = [(t, names) for t, status, names in verdicts
                 if status == "critical"]
        lo = hi = 0.0
    else:
        lo, hi = fault_span
        hi += recovery_s
        stray = [
            (t, names) for t, status, names in verdicts
            if status == "critical" and not (lo <= t <= hi)
        ]
    assert not stray, (
        f"critical verdict outside the injected-fault window "
        f"[{lo:.1f}s, {hi:.1f}s]: {stray}"
    )
    if final_health is not None:
        assert final_health.get("status") != "critical", (
            f"cluster still critical after the run drained: {final_health}"
        )


# ---------------------------------------------------------------------- cluster

class ChaosCluster:
    """n apps over one logical clock + fault-injection network, driven by a
    declarative fault schedule under continuous request load."""

    def __init__(
        self,
        wal_root,
        *,
        n: int = 4,
        depth: int = 1,
        rotation: bool = True,
        seed: int = 101,
        config_fn: Optional[Callable[[int], Configuration]] = None,
        engine_faults: bool = False,
        byzantine: bool = False,
        trace: bool = False,
        trace_capacity: int = 4096,
        health: bool = True,
        slo_spec=None,
    ):
        self.wal_root = str(wal_root)
        self.n = n
        self.depth = depth
        self.rotation = rotation
        self.scheduler = Scheduler()
        self.network = Network(seed=seed)
        self.shared = SharedLedgers()
        self.rng = random.Random(seed)
        #: engine_faults=True: every replica routes quorum verification
        #: through ONE shared FaultyEngine-wrapped coalescer (the
        #: single-chip deployment shape) so engine_* timeline actions can
        #: hang/fail the device plane under a full fault policy — launch
        #: deadline, retry/backoff, host-fallback breaker, canary probe
        self.engine: Optional[object] = None
        self.coalescer = None
        self.verify_metrics = None  # InMemoryProvider backing the breaker counters
        crypto_fn: Callable[[int], Optional[object]] = lambda i: None
        if engine_faults:
            from ..crypto.provider import AsyncBatchCoalescer, VerifyFaultPolicy
            from ..metrics import InMemoryProvider, TPUCryptoMetrics
            from .engine_faults import (
                CoalescedTrivialCrypto,
                FaultyEngine,
                always_valid_engine,
            )

            self.engine = FaultyEngine(always_valid_engine())
            self.verify_metrics = InMemoryProvider()
            # the fault knobs are WALL-CLOCK: tight values keep the
            # deadline→retry→breaker cycle well inside the real seconds a
            # logical-clock schedule takes to play out
            self.coalescer = AsyncBatchCoalescer(
                self.engine, window=0.001, max_batch=4096,
                policy=VerifyFaultPolicy(
                    launch_timeout=0.15, launch_retries=2,
                    backoff_base=0.02, backoff_max=0.08, backoff_jitter=0.25,
                    breaker_threshold=3, probe_interval=0.05,
                    probe_backoff_max=0.2,
                ),
                fallback_engine=always_valid_engine(),
                metrics=TPUCryptoMetrics(self.verify_metrics),
            )
            crypto_fn = lambda i: CoalescedTrivialCrypto(i, self.coalescer)
            if config_fn is None:
                # device-plane outages stall verification for wall-clock
                # spans the logical clock races past — keep request
                # complaints and heartbeat escalation out of the picture so
                # the scenario exercises the DEVICE plane, not deposition
                config_fn = lambda i: chaos_config(
                    i, depth=depth, rotation=rotation,
                    request_forward_timeout=120.0,
                    request_complain_timeout=240.0,
                    request_auto_remove_timeout=480.0,
                    leader_heartbeat_timeout=30.0,
                    view_change_resend_interval=15.0,
                    view_change_timeout=60.0,
                    verify_launch_timeout=0.15, verify_launch_retries=2,
                    verify_breaker_threshold=3, verify_probe_interval=0.05,
                )
        elif byzantine:
            # byzantine=True (ISSUE 18): a FORGERY-REJECTING crypto plane.
            # The engine-fault clusters run always-valid trivial crypto —
            # useless against an adversary, whose whole attack is invalid
            # signatures.  Every replica gets a real CryptoProvider over
            # the deterministic toy scheme (millisecond kernel, real
            # binding checks, real per-signer verdicts) sharing one
            # coalescer — the shared verify plane the forgery flood aims
            # at.  Shun threshold is lowered so a vote forger (at most ONE
            # registered vote per sender per decision) crosses it within a
            # few decisions; decay is pushed past the round so the
            # post-run oracles still see the shun.
            from ..crypto.provider import (
                AsyncBatchCoalescer,
                HostVerifyEngine,
                Keyring,
            )
            from . import toy_scheme

            self.engine = HostVerifyEngine(scheme=toy_scheme)
            self.coalescer = AsyncBatchCoalescer(
                self.engine, window=0.001, max_batch=4096, dedupe=True,
            )
            rings = Keyring.generate(
                list(range(1, n + 1)), seed=b"byzantine-chaos",
                scheme=toy_scheme,
            )
            crypto_fn = lambda i: toy_scheme.ToyCryptoProvider(
                rings[i], coalescer=self.coalescer
            )
            if config_fn is None:
                config_fn = lambda i: chaos_config(
                    i, depth=depth, rotation=rotation,
                    misbehavior_shun_threshold=3,
                    misbehavior_decay_interval=600.0,
                )
        #: the installed Byzantine actor, when a schedule arms one
        self.actor = None
        cfg = config_fn or (lambda i: chaos_config(i, depth=depth, rotation=rotation))
        #: per-replica flight recorders (ISSUE 12): armed with trace=True,
        #: dumped to the run dir on any invariant failure so a failed soak
        #: leaves a timeline, not just an assertion message
        self.trace = trace
        self.recorders: dict[int, object] = {}
        if trace:
            from ..obs import TraceRecorder

            self.recorders = {
                i: TraceRecorder(clock=self.scheduler.now, node=f"n{i}",
                                 capacity=trace_capacity)
                for i in range(1, n + 1)
            }
            if self.coalescer is not None:
                self.recorders[0] = TraceRecorder(
                    clock=self.scheduler.now, node="verify",
                    capacity=trace_capacity,
                )
                self.coalescer.attach_recorder(self.recorders[0])
        self.apps = [
            App(i, self.network, self.shared, self.scheduler,
                wal_dir=f"{self.wal_root}/wal-{i}", config=cfg(i),
                crypto=crypto_fn(i), recorder=self.recorders.get(i))
            for i in range(1, n + 1)
        ]
        self.down: set[int] = set()
        #: nodes under an active injected fault (mute/corrupt/disconnect):
        #: the request pump skips them, like a client avoiding a dead peer
        self.faulted: set[int] = set()
        #: members of partition groups below quorum size (pump skips too)
        self.partition_minority: set[int] = set()
        #: the node the run's first dynamic "leader" target resolved to
        self.faulty_node: Optional[int] = None
        #: active open-loop spike (load_spike action), None when stopped
        self.spike: Optional[dict] = None
        #: request-id sequence shared by EVERY spike of a run — a second
        #: load_spike must not re-issue the first one's ids (pool dedup
        #: would reject its whole burst as duplicates)
        self._spike_seq = 0
        self._spike_pending = 0
        #: per-request submit→commit latency on the LOGICAL clock — fed by
        #: the spike pump, resolved by the run loop's ledger scan, read by
        #: overload scenarios (phase p99s via begin_phase)
        self.latency = CommitLatencyTracker(clock=self.scheduler.now)
        self._latency_scan_pos = 0
        #: continuous SLO evaluation (ISSUE 14): one HealthMonitor per
        #: node on the LOGICAL clock, ticked by the run loop; sources
        #: rebind across crash-restarts (each restart builds a fresh
        #: Consensus + VC tracker).  slo_spec defaults to the production
        #: default spec — the point is judging chaos runs against the
        #: same objectives an operator would.
        self.health_monitors: dict[int, object] = {}
        if health:
            from ..obs.health import HealthMonitor

            for i in range(1, n + 1):
                mon = HealthMonitor(
                    slo_spec, clock=self.scheduler.now, node=f"n{i}",
                    recorder=self.recorders.get(i),
                )
                mon.add_source(self._node_signal_source(i))
                if self.coalescer is not None:
                    from ..obs.health import coalescer_signal_source

                    mon.add_source(coalescer_signal_source(self.coalescer))
                self.health_monitors[i] = mon
        self._last_cluster_status: Optional[str] = None

    def _node_signal_source(self, node_id: int) -> Callable:
        """A source that follows the node's CURRENT Consensus: restarts
        rebuild consensus (and its VC tracker), so the bound vc/pool
        sources are rebuilt whenever the underlying object changes."""
        from ..obs.health import pool_signal_source, vc_signal_source

        state = {"consensus": None, "sources": []}

        def signals() -> dict:
            app = self.app(node_id)
            c = app.consensus if node_id not in self.down else None
            if c is None:
                state["consensus"], state["sources"] = None, []
                return {}
            if c is not state["consensus"]:
                state["consensus"] = c
                state["sources"] = [
                    vc_signal_source(c.vc_phases, clock=self.scheduler.now),
                    pool_signal_source(c.pool_occupancy,
                                       clock=self.scheduler.now),
                ]
            out: dict = {}
            for fn in state["sources"]:
                out.update(fn())
            return out

        return signals

    def tick_health(self, report: Optional[ChaosReport] = None) -> dict:
        """Tick every live node's monitor, aggregate the cluster verdict,
        and (when ``report`` is given) record verdict CHANGES.  Down
        nodes count as unreachable — exactly the control-channel sweep
        semantics of SocketCluster.cluster_health."""
        from ..obs.health import aggregate_cluster_verdict

        verdicts = {}
        unreachable = []
        for i, mon in self.health_monitors.items():
            if i in self.down:
                unreachable.append(f"n{i}")
                continue
            verdicts[f"n{i}"] = mon.tick()
        agg = aggregate_cluster_verdict(verdicts, unreachable=unreachable)
        if report is not None:
            report.final_health = agg
            if agg["status"] != self._last_cluster_status:
                self._last_cluster_status = agg["status"]
                report.verdicts.append((
                    round(self.scheduler.now(), 2), agg["status"],
                    sorted({r.get("slo", "?") for r in agg["reasons"]}),
                ))
        return agg

    async def wait_healthy(self, timeout: float = 30.0,
                           step: float = 0.05) -> float:
        """Advance logical time until the cluster verdict returns to
        ``healthy``; returns the logical seconds it took.  The
        recovery-bound invariant (ISSUE 14) asserts through this."""
        start = self.scheduler.now()
        elapsed = 0.0
        while elapsed < timeout:
            if self.tick_health()["status"] == "healthy":
                return self.scheduler.now() - start
            await asyncio.sleep(0)
            self.scheduler.advance_by(step)
            await asyncio.sleep(0.001)
            elapsed += step
        raise TimeoutError(
            f"cluster verdict did not return to healthy within {timeout}s: "
            f"{self.tick_health()}"
        )

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        for a in self.apps:
            await a.start()

    async def stop(self) -> None:
        if self.engine is not None and hasattr(self.engine, "heal"):
            self.engine.heal()  # release any verify calls parked in a hang
        for a in self.apps:
            if a.id not in self.down:
                await a.stop()

    def app(self, node_id: int) -> App:
        return self.apps[node_id - 1]

    def install_actor(self, node_id: int):
        """Wrap ``node_id`` in a :class:`testing.byzantine.ByzantineActor`
        (arm modes on the returned actor).  The actor's replica is NOT
        marked faulted: it stays a pump target and must keep committing —
        a Byzantine node is indistinguishable from an honest one except
        where it chooses to lie."""
        from .byzantine import ByzantineActor

        self.actor = ByzantineActor(self.app(node_id), self.network)
        return self.actor

    # -- queries -----------------------------------------------------------

    def committed(self, app: App) -> int:
        return sum(len(app.requests_from_proposal(d.proposal)) for d in app.ledger())

    def live_apps(self) -> list[App]:
        return [a for a in self.apps if a.id not in self.down]

    def leader_of(self) -> int:
        for a in self.live_apps():
            if a.consensus is not None:
                lead = a.consensus.get_leader_id()
                if lead:
                    return lead
        return 0

    def healthy_apps(self) -> list[App]:
        """Live apps with no active injected fault — pump targets."""
        bad = self.down | self.faulted | self.partition_minority
        return [a for a in self.apps if a.id not in bad]

    # -- event execution ---------------------------------------------------

    def _resolve(self, spec) -> Optional[int]:
        """Resolve a dynamic target ("leader" / "faulty") to a node id."""
        if spec == "leader":
            node = self.leader_of()
            if not node:
                raise RuntimeError("no live leader to resolve a dynamic target")
            if self.faulty_node is None:
                self.faulty_node = node
            return node
        if spec == "faulty":
            if self.faulty_node is None:
                raise RuntimeError('"faulty" target used before any "leader" resolution')
            return self.faulty_node
        return spec

    async def _fire(self, evt: ChaosEvent) -> ChaosEvent:
        target = self._resolve(evt.node) if evt.node is not None else None
        groups = tuple(
            tuple(self._resolve(m) for m in g) for g in evt.groups
        )
        evt = dataclasses.replace(evt, node=target, groups=groups)
        node = self.network.nodes.get(evt.node) if evt.node else None
        if evt.action == "mute":
            node.mute()
            self.faulted.add(evt.node)
        elif evt.action == "unmute":
            node.unmute()
            self.faulted.discard(evt.node)
        elif evt.action == "disconnect":
            node.disconnect()
            self.faulted.add(evt.node)
        elif evt.action == "reconnect":
            node.connect()
            self.faulted.discard(evt.node)
        elif evt.action == "crash":
            self.down.add(evt.node)
            self.faulted.add(evt.node)
            await self.app(evt.node).stop()
        elif evt.action == "restart":
            await self.app(evt.node).start()
            self.down.discard(evt.node)
            self.faulted.discard(evt.node)
        elif evt.action == "partition":
            from ..core.util import compute_quorum

            self.network.partition(*[list(g) for g in evt.groups])
            named = {m for g in evt.groups for m in g}
            rest = [i for i in range(1, self.n + 1) if i not in named]
            q, _ = compute_quorum(self.n)
            for g in [list(g) for g in evt.groups] + ([rest] if rest else []):
                if len(g) < q:
                    self.partition_minority.update(g)
        elif evt.action == "heal":
            self.network.heal()
            self.partition_minority.clear()
        elif evt.action == "corrupt":
            node.mutate_send = self._corruptor(evt.fraction)
            self.faulted.add(evt.node)
        elif evt.action == "uncorrupt":
            node.mutate_send = None
            self.faulted.discard(evt.node)
        # device-plane actions: the engine is shared, so no node is marked
        # faulted — the pump keeps submitting everywhere, which is the
        # point (consensus must keep committing through the outage)
        elif evt.action == "engine_hang":
            self._require_engine().hang()
        elif evt.action == "engine_fail":
            self._require_engine().fail_next(max(1, int(evt.count)))
        elif evt.action == "engine_slow":
            self._require_engine().slow(evt.fraction)
        elif evt.action == "engine_permanent":
            self._require_engine().permanent_error()
        elif evt.action == "engine_device_down":
            self._require_engine().lose_device(max(0, int(evt.count)))
        elif evt.action == "engine_device_restore":
            self._require_engine().restore_device(max(0, int(evt.count)))
        elif evt.action == "engine_heal":
            self._require_engine().heal()
        # overload actions: the open-loop pump is a fault like any other —
        # no node is marked faulted, the point is precisely that honest
        # traffic keeps arriving at nodes that must now shed
        elif evt.action == "load_spike":
            rate = evt.fraction if evt.fraction > 0 else 50.0
            # count is the Zipf client universe; the ChaosEvent default
            # (1, shared with engine_fail/reshard semantics) means
            # "unspecified" — a 1-client spike is a degenerate hammer
            # nobody schedules deliberately, so <= 1 takes the default 64
            n_clients = int(evt.count) if int(evt.count) > 1 else 64
            self.spike = {
                "pump": OpenLoopPump(rate, self.rng,
                                     start=self.scheduler.now()),
                "zipf": ZipfClients(n_clients, prefix="spike"),
            }
        elif evt.action == "load_stop":
            self.spike = None
        # Byzantine actions (require install_actor; the actor's armed
        # modes run continuously — only the replay needs a timeline hook,
        # since stale votes only EXIST after the cluster moved past them)
        elif evt.action == "byz_replay":
            if self.actor is None:
                raise RuntimeError(
                    "byz_replay needs ChaosCluster.install_actor first"
                )
            # staleness is judged against the CLUSTER's view, not the
            # actor's recording horizon: after a quiet view change the
            # actor holds only pre-change votes, all of them stale now
            view = max(
                (a.consensus.controller.curr_view_number
                 for a in self.live_apps()
                 if a.consensus is not None
                 and a.consensus.controller is not None),
                default=0,
            )
            self.actor.replay_stale(view)
        else:
            raise ValueError(f"unknown chaos action: {evt.action}")
        return evt

    def _require_engine(self):
        if self.engine is None:
            raise RuntimeError(
                "engine_* chaos actions need ChaosCluster(engine_faults=True)"
            )
        return self.engine

    def scan_latency_commits(self) -> None:
        """Resolve latency stamps against the longest live ledger
        (prefix-consistent, so already-scanned positions are stable).
        Called every run-loop tick; tests that submit stamped requests
        AFTER a schedule call it again to resolve the tail."""
        live = self.live_apps()
        if not live:
            return
        probe = max(live, key=lambda a: a.height())
        ledger = probe.ledger()
        for d in ledger[self._latency_scan_pos:]:
            for info in probe.requests_from_proposal(d.proposal):
                self.latency.on_committed(str(info), 0)
        self._latency_scan_pos = len(ledger)

    def _dump_on_failure(self) -> None:
        """Best-effort artifact dump on an invariant/liveness failure —
        must never mask the failure it documents."""
        try:
            paths = self.dump_flight_recorders()
            if paths:
                print(f"flight-recorder dumps written: {paths}")
        except Exception:  # noqa: BLE001
            pass

    def dump_flight_recorders(self, out_dir: Optional[str] = None) -> list:
        """Write each replica's last spans to ``out_dir`` (default: the
        SIBLING dir ``<wal_root>-flight`` — soaks run under a
        TemporaryDirectory whose cleanup would delete an in-tree dump
        while the failure propagates) as ``flight-<node>.json`` — the
        dump shape ``python -m smartbft_tpu.obs.report`` renders.
        No-op (returns []) unless the cluster was built with
        ``trace=True``."""
        if not self.recorders:
            return []
        import os

        out_dir = out_dir or (self.wal_root.rstrip("/") + "-flight")
        os.makedirs(out_dir, exist_ok=True)
        return [
            rec.dump_to(os.path.join(out_dir, f"flight-{rec.node}.json"))
            for rec in self.recorders.values()
        ]

    def _corruptor(self, fraction: float):
        """Per-target message corruption.

        Copy-on-write contract: broadcasts share ONE frozen decoded message
        object across all recipients (the encode-once plane), so a mutation
        hook must never touch the routed original — the network enforces
        this by handing every mutate_send hook a deep copy
        (``messages.deep_copy_message``), making it impossible for the
        corruption of one recipient's message to leak into another
        replica's ingest (regression-pinned in tests/test_message_plane.py).
        """
        rng = self.rng

        def mutate(_target, msg):
            if isinstance(msg, (Prepare, Commit)) and rng.random() < fraction:
                return dataclasses.replace(msg, digest="corrupted-" + msg.digest[:8])
            return msg

        return mutate

    # -- the run loop ------------------------------------------------------

    async def run_schedule(
        self,
        schedule: list[ChaosEvent],
        *,
        requests: int = 20,
        submit_via: int = 0,
        submit_every: float = 0.3,
        settle_timeout: float = 300.0,
        step: float = 0.05,
        on_tick: Optional[Callable[[float], None]] = None,
    ) -> ChaosReport:
        """Execute the schedule under load and drain to quiescence.

        Requests ``chaos-0..requests-1`` are submitted one per
        ``submit_every`` logical seconds through the ``submit_via`` node
        (0 = rotate over live non-faulted nodes), interleaved with the
        timeline's events.  An active ``load_spike`` additionally pumps
        open-loop Poisson arrivals as background submit tasks (they ack,
        shed, or fail — all counted; ACKED spike requests join the drain
        target, shed ones never will).  After the last event AND last
        submission, the run continues until every live node committed
        every request (or ``settle_timeout`` logical seconds pass, which
        raises)."""
        report = ChaosReport()
        pending = sorted(schedule, key=lambda e: e.at)
        now = 0.0
        submitted = 0
        next_submit = 0.0
        next_health = 0.0
        heal_seen = False
        self._spike_pending = 0

        def target_app() -> Optional[App]:
            if submit_via:
                return self.app(submit_via) if submit_via not in self.down else None
            healthy = self.healthy_apps()
            return healthy[submitted % len(healthy)] if healthy else None

        async def spike_submit(key: str, cid: str, rid: str) -> None:
            healthy = self.healthy_apps()
            app = healthy[report.spike_offered % len(healthy)] \
                if healthy else None
            self.latency.on_submitted(key)
            if app is None or app.consensus is None:
                self.latency.on_shed(key, "other")
                report.spike_failed += 1
                return
            try:
                await app.submit(cid, rid)
                report.spike_acked += 1
            except AdmissionRejected:
                self.latency.on_shed(key, "admission")
                report.spike_shed_admission += 1
            except SubmitTimeoutError:
                self.latency.on_shed(key, "timeout")
                report.spike_shed_timeout += 1
            except Exception:  # noqa: BLE001 — counted, never kills the run
                self.latency.on_shed(key, "other")
                report.spike_failed += 1

        def pump_spike() -> None:
            sp = self.spike
            if sp is None:
                return
            for _ in range(sp["pump"].due(self.scheduler.now())):
                cid = sp["zipf"].sample(self.rng)
                rid = f"spike-{self._spike_seq}"
                self._spike_seq += 1
                report.spike_offered += 1
                # a done-callback counter, not a retained task list: the
                # drain check must not rescan O(offered) tasks per tick
                self._spike_pending += 1
                task = create_logged_task(
                    spike_submit(f"{cid}:{rid}", cid, rid),
                    name=f"chaos-{rid}",
                )
                task.add_done_callback(
                    lambda _t: setattr(self, "_spike_pending",
                                       self._spike_pending - 1)
                )

        def sample_occupancy() -> None:
            for a in self.live_apps():
                occ = a.pool_occupancy()
                pressure = occ.get("size", 0) + occ.get("waiters", 0)
                if pressure > report.spike_peak_occupancy:
                    report.spike_peak_occupancy = pressure

        def all_drained() -> bool:
            live = self.live_apps()
            # spike requests that were ACKED are pooled somewhere and must
            # commit; the count is final once every spike task finished
            need = requests + report.spike_acked
            return bool(live) and all(
                self.committed(a) >= need for a in live
            ) and self._spike_pending == 0

        deadline = None
        while True:
            # 1. fire due events
            while pending and pending[0].at <= now:
                evt = pending.pop(0)
                report.events_fired.append(await self._fire(evt))
                lo, hi = report.fault_span or (now, now)
                report.fault_span = (min(lo, now), max(hi, now))
            # 2. pump load
            if submitted < requests and now >= next_submit:
                app = target_app()
                if app is not None and app.consensus is not None:
                    try:
                        await app.submit("chaos", f"chaos-{submitted}")
                        submitted += 1
                        next_submit = now + submit_every
                    except Exception:
                        next_submit = now + submit_every  # pool full / no leader: retry later
                else:
                    next_submit = now + submit_every
            report.submitted = submitted
            # 2b. open-loop spike arrivals (when a load_spike is active)
            pump_spike()
            # 2c. caller-driven side traffic (ISSUE 19: read probes that
            # must land DURING faults, not after the drain)
            if on_tick is not None:
                on_tick(now)
            # 3. bookkeeping (latency/occupancy scans only when an
            # overload measurement is live — schedules without a spike
            # must not pay per-tick ledger decoding for an empty tracker)
            if self.spike is not None or self.latency.pending():
                self.scan_latency_commits()
                sample_occupancy()
            # 3b. continuous SLO evaluation (every 0.25 logical s — the
            # burn windows need cadence, not per-step granularity)
            if self.health_monitors and now >= next_health:
                self.tick_health(report)
                next_health = now + 0.25
            lead = self.leader_of()
            if lead:
                report.leaders_seen.add(lead)
            if not heal_seen and not pending and submitted >= requests:
                # schedule end is an implicit load_stop: every event has
                # fired so no load_stop can arrive, and an unstopped pump
                # would push the run to the 1h hard cap instead of
                # draining (a spike meant to outlive the baseline pump
                # schedules its load_stop explicitly)
                self.spike = None
                heal_seen = True
                report.heal_at = now
                live = self.live_apps()
                probe = live[0] if live else self.apps[0]
                report.committed_at_heal = self.committed(probe)
                report.decisions_at_heal = len(probe.ledger())
                deadline = now + settle_timeout
            # 4. exit condition
            if heal_seen and all_drained():
                break
            if deadline is not None and now > deadline:
                live = self.live_apps()
                self._dump_on_failure()  # liveness timeout: keep the trace
                raise TimeoutError(
                    f"chaos run did not drain within {settle_timeout}s of the "
                    f"last event: committed="
                    f"{[self.committed(a) for a in live]} of {requests}"
                )
            if now > 3600.0:
                self._dump_on_failure()
                raise TimeoutError("chaos run exceeded the hard 1h logical cap")
            # 5. advance logical time in lockstep with the loop
            await asyncio.sleep(0)
            self.scheduler.advance_by(step)
            await asyncio.sleep(0.001)
            now += step

        probe = self.live_apps()[0]
        report.final_committed = self.committed(probe)
        report.final_decisions = len(probe.ledger())
        return report


# ---------------------------------------------------------------------- invariants

class Invariants:
    """Post-run safety/liveness checks; every method raises AssertionError
    with a diagnostic on violation."""

    @staticmethod
    def fork_free(cluster: ChaosCluster) -> None:
        apps = cluster.live_apps()
        ref = [(d.proposal.payload, d.proposal.metadata) for d in apps[0].ledger()]
        for a in apps[1:]:
            other = [(d.proposal.payload, d.proposal.metadata) for d in a.ledger()]
            m = min(len(ref), len(other))
            assert ref[:m] == other[:m], (
                f"ledger fork between node {apps[0].id} and node {a.id}"
            )

    @staticmethod
    def exactly_once(cluster: ChaosCluster, expected: Optional[int] = None) -> None:
        for a in cluster.live_apps():
            infos = [
                str(i)
                for d in a.ledger()
                for i in a.requests_from_proposal(d.proposal)
            ]
            dupes = {i for i in infos if infos.count(i) > 1}
            assert not dupes, f"node {a.id} delivered duplicates: {sorted(dupes)}"
            if expected is not None:
                assert len(infos) >= expected, (
                    f"node {a.id} delivered {len(infos)} of {expected} requests"
                )
            seqs = [
                decode(ViewMetadata, d.proposal.metadata).latest_sequence
                for d in a.ledger()
                if d.proposal.metadata
            ]
            assert seqs == list(range(1, len(seqs) + 1)), (
                f"node {a.id} has a sequence gap: {seqs}"
            )

    @staticmethod
    def reads_linearizable(cluster: ChaosCluster, observations: list) -> int:
        """Every stamped read matches the committed state AT ITS HEIGHT.

        ``observations`` are ``(key, found, value, height)`` stamps a
        client collected during the run (any mode — local, follower, or
        the f+1 winner).  The oracle replays a live replica's committed
        prefix into an independent per-height KV timeline (the same
        last-write-per-client fold the serving plane uses, rebuilt from
        scratch here) and asserts each stamp against the state at its
        height — a read that returned a value its stamped height had not
        committed, or missed one it had, is a linearizability violation
        no matter what the cluster was doing when it was served.

        Returns the number of stamps checked.  Stamps below the
        replayer's snapshot base are uncheckable (their prefix was
        compacted away) and skipped."""
        from .app import BatchPayload, TestRequest

        apps = cluster.live_apps()
        assert apps, "no live replica to replay against"
        app = min(apps, key=lambda a: a.base_height)
        kv = dict(app.base_kv)
        timeline = [dict(kv)]  # timeline[i] = state at base_height + i
        for d in app.ledger():
            if d.proposal.payload:
                try:
                    batch = decode(BatchPayload, d.proposal.payload)
                except Exception:  # noqa: BLE001 — foreign payload
                    batch = None
                if batch is not None:
                    for raw in batch.requests:
                        try:
                            req = decode(TestRequest, raw)
                        except Exception:  # noqa: BLE001
                            continue
                        kv[req.client_id] = bytes(req.payload)
            timeline.append(dict(kv))
        base = app.base_height
        checked = 0
        for key, found, value, height in observations:
            idx = int(height) - base
            if idx < 0:
                continue  # pre-base stamp: prefix compacted, uncheckable
            assert idx < len(timeline), (
                f"read of {key!r} stamped height {height} beyond the "
                f"committed frontier {base + len(timeline) - 1}"
            )
            expect = timeline[idx].get(str(key))
            if found:
                assert expect is not None, (
                    f"read of {key!r} at height {height} returned a value "
                    f"but nothing was committed for it by then"
                )
                assert bytes(value) == expect, (
                    f"read of {key!r} at height {height} returned "
                    f"{bytes(value)!r}, committed state says {expect!r}"
                )
            else:
                assert expect is None, (
                    f"read of {key!r} at height {height} found nothing, "
                    f"but {expect!r} was committed by then"
                )
            checked += 1
        return checked

    @staticmethod
    def ever_blacklisted(cluster: ChaosCluster, node_id: int) -> None:
        """The faulty node must appear in the blacklist of SOME committed
        decision's metadata (it may later be redeemed once it rejoins and
        is witnessed alive — util.go:502-541 — so 'currently blacklisted'
        is deliberately not the assertion)."""
        app = cluster.live_apps()[0]
        seen = [
            list(decode(ViewMetadata, d.proposal.metadata).black_list)
            for d in app.ledger()
            if d.proposal.metadata
        ]
        assert any(node_id in bl for bl in seen), (
            f"node {node_id} never entered the committed blacklist; "
            f"blacklists seen: {seen}"
        )

    @staticmethod
    def no_equivocation_commit(cluster: ChaosCluster, actor,
                               max_blacklist_decisions: Optional[int] = None
                               ) -> None:
        """The equivocation oracle (ISSUE 18 satellite): judged against
        the actor's OWN send log.  (a) No two honest replicas committed
        different proposals at any (view, seq) — quorum intersection held
        against a leader telling every follower a different story.
        (b) None of the per-target variant digests the actor fabricated
        was ever committed (each variant reached exactly one follower, so
        no variant can gather a prepare quorum).  (c) The actor entered
        the committed blacklist within a bounded number of decisions of
        its first equivocation — the deposition machinery converged."""
        from ..types import proposal_digest as _pdigest

        apps = [a for a in cluster.live_apps() if a.id != actor.id]
        assert apps, "no honest replicas to check"
        slots = actor.equivocated_slots()
        assert slots, "actor never equivocated — the oracle is vacuous"
        committed: dict = {}
        for a in apps:
            for d in a.ledger():
                if not d.proposal.metadata:
                    continue
                md = decode(ViewMetadata, d.proposal.metadata)
                key = (md.view_id, md.latest_sequence)
                dig = _pdigest(d.proposal)
                got = committed.setdefault(key, dig)
                assert got == dig, (
                    f"equivocation committed: node {a.id} holds "
                    f"{dig[:12]}.. at (view, seq) {key} while another "
                    f"honest replica holds {got[:12]}.."
                )
        variant_digests = {
            dg
            for (v, s) in slots
            for dg in actor.variant_digests(v, s).values()
        }
        leaked = {k: dg for k, dg in committed.items()
                  if dg in variant_digests}
        assert not leaked, (
            f"a per-target variant digest gathered a quorum and "
            f"committed: {leaked}"
        )
        first_eq = min(s for _, s in slots)
        bl_seqs = [
            decode(ViewMetadata, d.proposal.metadata).latest_sequence
            for d in apps[0].ledger()
            if d.proposal.metadata
            and actor.id in decode(ViewMetadata,
                                   d.proposal.metadata).black_list
        ]
        assert bl_seqs, (
            f"equivocator {actor.id} never entered the committed "
            f"blacklist; slots equivocated: {slots}"
        )
        bound = max_blacklist_decisions if max_blacklist_decisions \
            is not None else 6 * max(cluster.depth, 1) + 8
        assert min(bl_seqs) - first_eq <= bound, (
            f"equivocator blacklisted only at seq {min(bl_seqs)}, "
            f"{min(bl_seqs) - first_eq} decisions after its first "
            f"equivocation at seq {first_eq} (bound {bound})"
        )

    @staticmethod
    def forger_shunned_and_shed(cluster: ChaosCluster, actor) -> None:
        """The vote-forgery oracle (ISSUE 18): every honest replica's
        per-sender accounting attributed the forged verdicts to the actor
        (and ONLY to provable causes from the actor), at least one
        crossed its shun threshold, and intake sheds followed — the flood
        stopped costing verify-plane launches."""
        assert actor.forged > 0, "actor never forged — oracle is vacuous"
        shun_events = 0
        sheds = 0
        for a in cluster.live_apps():
            if a.id == actor.id or a.consensus is None:
                continue
            snap = a.consensus.misbehavior_snapshot()
            by = snap["by_sender"].get(actor.id, {})
            assert by.get("invalid_sig", 0) > 0, (
                f"node {a.id} never attributed an invalid signature to "
                f"forger {actor.id}: {snap['by_sender']}"
            )
            for sender, causes in snap["by_sender"].items():
                if sender != actor.id:
                    assert causes.get("invalid_sig", 0) == 0, (
                        f"node {a.id} misattributed invalid signatures "
                        f"to honest sender {sender}: {causes}"
                    )
            shun_events += snap["shun_events"]
            sheds += sum(snap["shed_votes"].values())
        assert shun_events > 0, (
            f"no honest replica ever shunned forger {actor.id} "
            f"despite {actor.forged} forged votes"
        )
        assert sheds > 0, (
            "no forged vote was ever shed at intake — the accounting "
            "never turned into enforcement"
        )

    @staticmethod
    def stale_replay_observed(cluster: ChaosCluster, actor) -> None:
        """The stale-replay oracle (ISSUE 18): honest replicas COUNTED
        the actor's replayed old-view votes per sender, and none shunned
        it for them — stale views are an observed cause (honest replicas
        racing a view change emit the same shape), never a provable
        one."""
        assert actor.replayed > 0, "actor never replayed — oracle vacuous"
        observed = 0
        for a in cluster.live_apps():
            if a.id == actor.id or a.consensus is None:
                continue
            snap = a.consensus.misbehavior_snapshot()
            observed += snap["by_sender"].get(actor.id, {}) \
                .get("stale_view", 0)
            assert actor.id not in snap["shunned"], (
                f"node {a.id} shunned {actor.id} over stale-view replays "
                f"— an observed cause must never shun: {snap}"
            )
            assert snap["scores"].get(actor.id, 0) == 0, (
                f"stale-view replays moved {actor.id}'s provable score "
                f"on node {a.id}: {snap['scores']}"
            )
        assert observed > 0, (
            f"{actor.replayed} replayed stale votes were never counted "
            f"by any honest replica"
        )

    @staticmethod
    def liveness_within_windows(
        cluster: ChaosCluster, report: ChaosReport, slack_windows: int = 4
    ) -> None:
        """Bounded post-heal liveness: draining the requests outstanding at
        heal time must take at most the decisions they need (batches) plus
        ``slack_windows`` windows of protocol slack (view changes,
        redeliveries)."""
        batch = cluster.apps[0].config.request_batch_max_count
        outstanding = report.submitted - report.committed_at_heal
        need = math.ceil(outstanding / max(batch, 1))
        depth = max(cluster.depth, 1)
        bound = need + slack_windows * depth
        assert report.decisions_after_heal <= bound, (
            f"liveness took {report.decisions_after_heal} decisions "
            f"(~{math.ceil(report.decisions_after_heal / depth)} windows) to "
            f"drain {outstanding} requests; bound was {bound} decisions "
            f"(~{math.ceil(bound / depth)} windows)"
        )

    @staticmethod
    async def breaker_recovered(cluster: ChaosCluster, timeout: float = 8.0) -> None:
        """Engine-fault runs: after the schedule's final heal, the
        host-fallback breaker must return to CLOSED (the canary probe runs
        on wall-clock time and may lag the logical drain — poll briefly),
        with every open matched by a close."""
        co = cluster.coalescer
        if co is None:
            return
        import time as _time

        deadline = _time.monotonic() + timeout
        while co.breaker_open and _time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        snap = co.fault_snapshot()
        assert not co.breaker_open, (
            f"verify breaker still open after heal: {snap}"
        )
        assert snap["opens"] == snap["closes"], (
            f"unbalanced breaker transitions after heal: {snap}"
        )

    @staticmethod
    def remediation_quiet(
        decisions, windows, grace: float = 0.0
    ) -> None:
        """Self-driving runs (ISSUE 20): every controller ACTION fell
        inside an injected-fault window (``grace`` extends each window's
        tail for the recovery it triggered).  ``decisions`` is the
        policy's acted-only log ``[(t, action, reason)]``; a controller
        that acts on a healthy, unfaulted cluster is hallucinating
        work — the steady state must be silence."""
        stray = [
            (round(t, 2), action, why)
            for (t, action, why) in decisions
            if not any(a <= t <= b + grace for (a, b) in windows)
        ]
        assert not stray, (
            f"controller acted outside every fault window "
            f"{[(round(a, 1), round(b, 1)) for (a, b) in windows]}: {stray}"
        )

    @staticmethod
    def no_flip_flop(decisions, window: float) -> None:
        """No A→B→A scale oscillation inside the hysteresis window —
        the Mir-BFT thrash lesson, counted by the SAME pure function the
        bench row reports so the invariant and the baseline guard cannot
        drift apart."""
        from ..control.policy import count_reversals

        flips = count_reversals(list(decisions), window)
        assert flips == 0, (
            f"{flips} scale reversal(s) within {window}s hysteresis: "
            f"{[(round(t, 2), a) for (t, a, _r) in decisions]}"
        )

    @classmethod
    def check_all(
        cls,
        cluster: ChaosCluster,
        report: ChaosReport,
        *,
        expected: Optional[int] = None,
        blacklisted: Optional[int] = None,
        slack_windows: int = 4,
    ) -> None:
        cls.fork_free(cluster)
        cls.exactly_once(cluster, expected)
        if blacklisted is not None:
            cls.ever_blacklisted(cluster, blacklisted)
        cls.liveness_within_windows(cluster, report, slack_windows)


def check_with_flight_dump(cluster: ChaosCluster, check: Callable[[], None],
                           out_dir: Optional[str] = None) -> None:
    """Run an invariant ``check``; on failure (AssertionError or
    TimeoutError) dump every replica's flight recorder to the run dir
    first, then re-raise — a failed soak leaves a timeline the
    ``obs.report`` tool can render, not just an assertion message."""
    try:
        check()
    except (AssertionError, TimeoutError):
        try:
            paths = cluster.dump_flight_recorders(out_dir)
            if paths:
                print(f"flight-recorder dumps written: {paths}")
        except Exception:  # noqa: BLE001 — never mask the real failure
            pass
        raise


# ---------------------------------------------------------------------- soak

def random_schedule(
    rng: random.Random, n: int, *, engine_faults: bool = False
) -> list[ChaosEvent]:
    """A randomized but always-heal-by-the-end schedule for soak runs.
    Leader-shaped faults use dynamic targets so they hit the node actually
    leading when the fault fires.  With ``engine_faults`` a device-plane
    fault shape is always present, with a 50% chance of ALSO running a
    protocol fault — device and protocol faults composing is exactly what
    production would see."""
    events: list[ChaosEvent] = []
    if engine_faults:
        t = rng.uniform(1.0, 4.0)
        shape = rng.choice(["hang", "fail", "slow", "permanent"])
        if shape == "hang":
            events.append(ChaosEvent(at=t, action="engine_hang"))
        elif shape == "fail":
            events.append(ChaosEvent(
                at=t, action="engine_fail", count=rng.randrange(1, 8)
            ))
        elif shape == "slow":
            events.append(ChaosEvent(
                at=t, action="engine_slow", fraction=rng.uniform(0.02, 0.1)
            ))
        else:
            events.append(ChaosEvent(at=t, action="engine_permanent"))
        events.append(ChaosEvent(
            at=t + rng.uniform(6.0, 14.0), action="engine_heal"
        ))
        if rng.random() < 0.5:
            return events
    t = rng.uniform(1.0, 3.0)
    shape = rng.choice(["mute", "crash", "partition", "corrupt"])
    if shape == "mute":
        events.append(ChaosEvent(at=t, action="mute", node="leader"))
        events.append(ChaosEvent(at=t + rng.uniform(8.0, 14.0), action="unmute", node="faulty"))
    elif shape == "crash":
        events.append(ChaosEvent(at=t, action="crash", node="leader"))
        events.append(ChaosEvent(at=t + rng.uniform(6.0, 12.0), action="restart", node="faulty"))
    elif shape == "partition":
        events.append(ChaosEvent(at=t, action="partition", groups=(("leader",),)))
        events.append(ChaosEvent(at=t + rng.uniform(6.0, 12.0), action="heal"))
    else:
        victim = rng.randrange(1, n + 1)
        events.append(
            ChaosEvent(at=t, action="corrupt", node=victim, fraction=rng.uniform(0.2, 0.8))
        )
        events.append(
            ChaosEvent(at=t + rng.uniform(6.0, 12.0), action="uncorrupt", node=victim)
        )
    return events


async def soak(
    *, rounds: int = 5, depth: int = 16, rotation: bool = True, seed: int = 1,
    n: int = 4, requests: int = 24, verbose: bool = True,
    engine_faults: bool = False,
) -> None:
    """Run ``rounds`` randomized schedules, checking every invariant.
    ``engine_faults`` adds randomized device-plane faults (hang / transient
    fail / slow / permanent) against a cluster whose verify plane runs
    through a shared FaultyEngine + fault-policy coalescer."""
    import tempfile

    rng = random.Random(seed)
    for r in range(rounds):
        with tempfile.TemporaryDirectory(prefix="chaos-soak-") as wal_root:
            cluster = ChaosCluster(
                wal_root, n=n, depth=depth, rotation=rotation, seed=seed + r,
                engine_faults=engine_faults, trace=True,
            )
            schedule = random_schedule(rng, n, engine_faults=engine_faults)
            await cluster.start()
            try:
                report = await cluster.run_schedule(
                    schedule, requests=requests, settle_timeout=600.0
                )

                def checks() -> None:
                    Invariants.fork_free(cluster)
                    Invariants.exactly_once(cluster, expected=requests)
                    Invariants.liveness_within_windows(
                        cluster, report, slack_windows=8
                    )

                # invariant failures leave per-replica flight-recorder
                # dumps in a SIBLING dir (rendered by obs.report) — the
                # temp run dir itself is deleted on the way out
                check_with_flight_dump(cluster, checks,
                                       out_dir=wal_root + "-flight")
                if engine_faults:
                    await Invariants.breaker_recovered(cluster)
                # ISSUE 14 invariants: no critical verdict the injected
                # faults don't explain, and the verdict RETURNS to
                # healthy within a bounded window of the heal (the
                # breaker-trip and forced-VC shapes both ride this)
                assert_health_verdicts(report.verdicts, report.fault_span,
                                       None)
                # the engine-faults soak deliberately configures heartbeat
                # escalation OUT of the picture (its config comment above)
                # — the detection judgment applies to protocol-fault rounds
                muted_leader = not engine_faults and any(
                    e.action == "mute" for e in schedule
                )
                if muted_leader:
                    # ISSUE 15 satellite: a mute-leader round must be
                    # JUDGED as a detection failure — some verdict
                    # transition (cluster log or per-node monitor) names
                    # the viewchange.detection_seconds SLO while
                    # non-healthy.  A soak where the leader dies and the
                    # detection objective never trips means the
                    # instrument, not the cluster, is broken.
                    named = [
                        names
                        for _, status, names in report.verdicts
                        if status != "healthy"
                    ] + [
                        names
                        for mon in cluster.health_monitors.values()
                        for _, status, names in mon.transitions
                        if status != "healthy"
                    ]
                    assert any(
                        "viewchange.detection_seconds" in names
                        for names in named
                    ), (
                        f"mute round never breached "
                        f"viewchange.detection_seconds: {named}"
                    )
                    # ...and recovery is BOUNDED by the detection SLO
                    # machinery, not just "eventually": the detection
                    # sample is latched after it fired, ages out of the
                    # fast burn window, and the bound itself passes —
                    # past latch + fast-window + bound (+2 s of tick
                    # slack) a still-degraded verdict means detection
                    # keeps RE-firing, i.e. leadership is thrashing.
                    # Derived from the live defaults so tuning them
                    # can't silently misalign this judgment.
                    import inspect

                    from ..obs.health import vc_signal_source
                    from ..obs.slo import default_slo_spec
                    det_rule = next(
                        r for r in default_slo_spec().rules
                        if r.name == "viewchange.detection_seconds"
                    )
                    latch_s = inspect.signature(
                        vc_signal_source).parameters["latch_s"].default
                    recovery_bound = (latch_s + det_rule.fast_window_s
                                      + det_rule.bound + 2.0)
                else:
                    recovery_bound = 30.0
                recovery_s = await cluster.wait_healthy(
                    timeout=recovery_bound)
            finally:
                await cluster.stop()
            if verbose:
                kinds = [e.action for e in report.events_fired]
                extra = ""
                if engine_faults and cluster.coalescer is not None:
                    snap = cluster.coalescer.fault_snapshot()
                    extra = (
                        f" breaker opens={snap['opens']}"
                        f" fallback_batches={snap['host_fallback_batches']}"
                    )
                print(
                    f"round {r}: events={kinds} decisions={report.final_decisions} "
                    f"committed={report.final_committed} leaders={sorted(report.leaders_seen)} "
                    f"post-heal decisions={report.decisions_after_heal}{extra} "
                    f"verdicts={report.verdicts} healthy_in={recovery_s:.1f}s — OK"
                )


async def sharded_soak(
    *, rounds: int = 3, shards: int = 2, n: int = 4, depth: int = 4,
    seed: int = 1, requests: int = 8, verbose: bool = True,
) -> None:
    """Engine-fault soak against the SHARED verify plane of a sharded
    cluster: every round rides hang -> transient fail-burst -> heal while
    all S shards stay under load.  Asserts the breaker cycle is coherent
    across shards — one plane means one open, every shard degrades to the
    host fallback together (and keeps committing), every shard's items
    show in the per-tag wave attribution, and one close restores them all.
    Per-shard fork-free/exactly-once/gapless invariants are checked
    through the delivery mux."""
    import tempfile
    import time as _time

    from .sharded import ShardedCluster, sharded_config

    rng = random.Random(seed)
    for r in range(rounds):
        with tempfile.TemporaryDirectory(prefix="chaos-shard-soak-") as root:
            cfg = lambda s, i: sharded_config(
                i, depth=depth,
                request_forward_timeout=120.0,
                request_complain_timeout=240.0,
                request_auto_remove_timeout=480.0,
                leader_heartbeat_timeout=30.0,
                view_change_resend_interval=15.0,
                view_change_timeout=60.0,
                verify_launch_timeout=0.15, verify_launch_retries=2,
                verify_breaker_threshold=3, verify_probe_interval=0.05,
            )
            cluster = ShardedCluster(
                root, shards=shards, n=n, depth=depth, engine_faults=True,
                config_fn=cfg, seed=seed + r,
            )
            await cluster.start()
            try:
                # warm-up decision per shard on the healthy device
                for s in range(shards):
                    await cluster.submit(cluster.client_for_shard(s), f"w{r}-{s}a")
                    await cluster.submit(cluster.client_for_shard(s, 1), f"w{r}-{s}b")
                from .app import wait_for

                await wait_for(
                    lambda: all(sh.committed() >= 2 for sh in cluster.shard_list),
                    cluster.scheduler, 90.0,
                )
                # outage: hang, then a transient fail-burst (the un-wedged
                # but still-sick device), under load on every shard
                cluster.engine.hang()
                for s in range(shards):
                    for j in range(requests):
                        await cluster.submit(
                            cluster.client_for_shard(s, j % 2), f"o{r}-{s}-{j}"
                        )
                cluster.engine.fail_next(rng.randrange(4, 12))
                await wait_for(
                    lambda: all(sh.committed() >= 2 + requests
                                for sh in cluster.shard_list),
                    cluster.scheduler, 240.0,
                )
                snap = cluster.coalescer.fault_snapshot()
                assert snap["opens"] >= 1, snap
                assert snap["host_fallback_batches"] >= 1, snap
                tag_snap = cluster.coalescer.shard_snapshot()
                assert set(tag_snap["per_tag"]) == {
                    str(s) for s in range(shards)
                }, tag_snap
                # heal: the canary probe closes the breaker for everyone
                cluster.engine.heal()
                deadline = _time.monotonic() + 10.0
                while cluster.coalescer.breaker_open \
                        and _time.monotonic() < deadline:
                    await asyncio.sleep(0.02)
                snap = cluster.coalescer.fault_snapshot()
                assert not cluster.coalescer.breaker_open, snap
                assert snap["opens"] == snap["closes"], snap
                cluster.check_invariants()
            finally:
                await cluster.stop()
            if verbose:
                print(
                    f"sharded round {r}: shards={shards} "
                    f"committed={[sh.committed() for sh in cluster.shard_list]} "
                    f"breaker opens={snap['opens']} closes={snap['closes']} "
                    f"mixed_waves={tag_snap['mixed_waves']} — OK"
                )


async def openloop_soak(
    *, rounds: int = 3, shards: int = 2, n: int = 4, depth: int = 2,
    seed: int = 1, rate: float = 600.0, duration: float = 4.0,
    verbose: bool = True,
) -> None:
    """Overload soak: every round drives OPEN-loop Poisson/Zipf arrivals
    far past the knee of a small-pool sharded cluster with admission
    control armed, then drops to a trickle.  Asserts the overload
    contract (README "Overload behavior"): shedding engages, combined
    pool occupancy stays bounded by capacity (no unbounded queue growth),
    committed goodput stays positive THROUGH the spike, and the recovery
    phase's p99 returns under the spike phase's — all on the logical
    clock, so a round costs real milliseconds per offered second."""
    import dataclasses as _dc
    import tempfile

    from .load import run_open_loop
    from .sharded import ShardedCluster, sharded_config

    for r in range(rounds):
        with tempfile.TemporaryDirectory(prefix="chaos-openloop-") as root:
            pool_size = 24
            cfg = lambda s, i: _dc.replace(
                sharded_config(i, depth=depth),
                request_pool_size=pool_size,
                admission_high_water=0.75,
                request_pool_submit_timeout=1.0,
                request_batch_max_count=8,
            )
            cluster = ShardedCluster(
                root, shards=shards, n=n, depth=depth, config_fn=cfg,
                seed=seed + r,
            )
            await cluster.start()
            try:
                capacity = shards * pool_size
                cluster.set.latency.begin_phase("spike")
                # drain=1.0: let the hot shard's admitted backlog commit
                # before the trickle phase starts, or its first arrivals
                # hit a gate still holding the spike's tail
                spike = await run_open_loop(
                    cluster, rate=rate, duration=duration, seed=seed + r,
                    drain=1.0,
                )
                cluster.set.latency.begin_phase("recovery")
                calm = await run_open_loop(
                    cluster, rate=rate / 40.0, duration=duration,
                    drain=6.0, seed=seed + r + 1000,
                    request_prefix="calm",
                )
                cluster.set.latency.end_phase()
                snap = cluster.set.latency.snapshot()
                phases = snap["phases"]
                assert spike.shed > 0, (
                    f"round {r}: a {rate}/s spike at capacity {capacity} "
                    f"must shed, got {spike.block()}"
                )
                assert spike.acked > 0 and phases["spike"]["count"] > 0, (
                    f"round {r}: goodput collapsed under the spike: "
                    f"{spike.block()}"
                )
                assert spike.peak_occupancy <= capacity, (
                    f"round {r}: occupancy {spike.peak_occupancy} exceeded "
                    f"combined capacity {capacity} — admission failed to "
                    f"bound the queue"
                )
                assert calm.shed == 0, (
                    f"round {r}: the trickle phase must not shed: "
                    f"{calm.block()}"
                )
                # "recovers" = not worse than the spike beyond measurement
                # resolution: admission keeps ADMITTED-request latency near
                # baseline even mid-spike, so the two phases can be equal —
                # allow one √2 histogram bucket of quantization slack
                assert phases["recovery"]["p99_ms"] <= \
                    max(phases["spike"]["p99_ms"] * 1.5, 1.0), (
                    f"round {r}: p99 did not recover after the spike: "
                    f"{phases}"
                )
                cluster.check_invariants()
            finally:
                await cluster.stop()
            if verbose:
                print(
                    f"openloop round {r}: offered={spike.offered} "
                    f"acked={spike.acked} shed={spike.shed} "
                    f"peak_occ={spike.peak_occupancy}/{capacity} "
                    f"spike_p99={phases['spike']['p99_ms']}ms "
                    f"recovery_p99={phases['recovery']['p99_ms']}ms — OK"
                )


# ---------------------------------------------------------------------- selfdrive

async def _advance_clock(cluster, seconds: float, step: float = 0.05) -> None:
    """Advance the logical clock (polling commits) without offering load
    or ticking the controller."""
    t_end = cluster.scheduler.now() + seconds
    while cluster.scheduler.now() < t_end:
        cluster.scheduler.advance_by(step)
        await asyncio.sleep(0.001)
        cluster.poll()


async def _drive_segments(
    cluster, ctl, *, rate: float, duration: float, seg: float = 0.5,
    seed: int = 0, prefix: str = "sd", samples=None, fills=None,
) -> None:
    """Drive open-loop arrivals in SEGMENTS of the logical clock with at
    most ONE controller step in flight between segments.

    A step that decides to scale must await ``ShardSet.reshard``, whose
    drain needs the clock to keep advancing — so the step runs as a
    background task while the next segment advances time, and is drained
    (errors propagated) before this helper returns.  ``samples`` collects
    ``(t, verdict_status, decision_status)`` per tick; ``fills`` collects
    ``(t, combined_pool_fill)`` per segment — the before-the-knee
    evidence."""
    from .app import wait_for
    from .load import run_open_loop

    async def _step():
        rem = await ctl.step()
        if samples is not None:
            samples.append((
                rem.at, rem.__dict__.get("_verdict_status", ""), rem.status,
            ))

    step_task = None
    nseg = max(1, int(round(duration / seg)))
    for k in range(nseg):
        await run_open_loop(
            cluster, rate=rate, duration=seg, seed=seed * 4096 + k,
            request_prefix=f"{prefix}{k}",
        )
        if fills is not None:
            fills.append((
                cluster.scheduler.now(),
                float(cluster.set.occupancy().get("fill", 0.0)),
            ))
        if step_task is not None and step_task.done():
            step_task.result()
            step_task = None
        if step_task is None:
            step_task = create_logged_task(_step(), name="ctl-step")
    if step_task is not None:
        await wait_for(lambda: step_task.done(), cluster.scheduler, 180.0)
        step_task.result()


async def remediation_storm_round(
    *, seed: int = 1, shards: int = 2, n: int = 4, depth: int = 2,
    spike_rate: float = 1200.0, verbose: bool = True,
) -> dict:
    """One rotating-fault round against the self-driving control plane
    (ISSUE 20): load spike past the knee → engine hang→heal → muted
    leader, all on the logical clock.  The controller must scale out on
    the commit-latency burn BEFORE occupancy saturates, scale back in on
    sustained idle, veto while the breaker owns the hang, and answer the
    view-change breach with a derived-knob retune through the ordered
    reconfig path — with ZERO actions outside the fault windows, zero
    A→B→A flips, and every action a ``ctl.remediate`` span."""
    import tempfile

    from ..control import ControlLoop
    from ..obs.slo import default_slo_spec
    from .app import wait_for
    from .sharded import ShardedCluster, sharded_config

    pool_size = 4096
    cfg = lambda s, i: sharded_config(
        i, depth=depth,
        request_pool_size=pool_size,
        admission_high_water=1.0,
        request_pool_submit_timeout=30.0,
        request_batch_max_count=8,
        # verify_flush_hold's derivation ceiling is the batch interval,
        # and the hold is WALL-clock: keep it small so the retuned hold
        # cannot inflate LOGICAL commit latency under compressed time
        request_batch_max_interval=0.01,
        # long protocol timers: an engine stall must not read as a dead
        # leader (the breaker is the remedy there, not a view change)
        request_forward_timeout=120.0,
        request_complain_timeout=240.0,
        request_auto_remove_timeout=480.0,
        leader_heartbeat_timeout=30.0,
        view_change_resend_interval=15.0,
        view_change_timeout=60.0,
        # device-plane fault policy (wall clock, as in sharded_soak)
        verify_launch_timeout=0.15, verify_launch_retries=2,
        verify_breaker_threshold=3, verify_probe_interval=0.05,
        # compressed reflex-arc knobs (logical seconds)
        control_interval=0.5,
        control_cooldown=20.0,
        control_hysteresis=12.0,
        control_idle_hold=5.0,
        control_budget_actions=6,
        control_budget_window=60.0,
        autoscale_min_shards=shards,
        autoscale_max_shards=shards + 2,
    )
    # Tight SLO windows so breach/clear cycles fit a compressed round;
    # the latency bound sits far above trickle latency and far below the
    # spike's queueing delay.
    spec = default_slo_spec(
        fast_window_s=2.0, slow_window_s=20.0,
    ).with_overrides(**{"latency.commit_p99_ms": 1500.0})

    with tempfile.TemporaryDirectory(prefix="chaos-selfdrive-") as root:
        cluster = ShardedCluster(
            root, shards=shards, n=n, depth=depth, engine_faults=True,
            config_fn=cfg, seed=seed, trace=True, collect_entries=True,
            slo_spec=spec,
        )
        await cluster.start()
        try:
            ctl = ControlLoop(cluster)
            sched = cluster.scheduler
            samples: list = []
            fills: list = []
            windows: list = []

            async def drive(rate, dur, pfx, sd):
                await _drive_segments(
                    cluster, ctl, rate=rate, duration=dur, seed=sd,
                    prefix=pfx, samples=samples, fills=fills,
                )

            # ---- warmup: healthy steady state, zero actions expected
            await drive(4.0, 4.0, "wu", seed)
            assert not ctl.executed, (
                f"controller acted on a healthy cluster: {ctl.executed}"
            )

            # ---- fault 1: open-loop spike past the knee, then cooloff.
            # The burn must draw scale-out while the pool is still far
            # from its occupancy trip point; drained idle must draw the
            # matching scale-in after hysteresis.
            t0 = sched.now()
            await drive(spike_rate, 6.0, "sp", seed + 7)
            await drive(3.0, 26.0, "co", seed + 13)
            windows.append((t0, sched.now()))
            acts = list(ctl.executed)
            assert acts and acts[0]["action"] == "scale_out" \
                and acts[0]["cause"] == "latency.commit_p99_ms" \
                and acts[0]["ok"], f"spike did not draw scale-out: {acts}"
            before = [f for (tf, f) in fills if tf <= acts[0]["at"]]
            fill_at_out = before[-1] if before else 0.0
            assert fill_at_out < ctl.policy.high_occupancy, (
                f"scale-out fired AFTER the knee: fill={fill_at_out} at "
                f"t={acts[0]['at']}"
            )
            assert any(
                e["action"] == "scale_in" and e["ok"] for e in acts
            ), f"sustained idle never drew scale-in: {acts}"
            assert cluster.set.num_shards == shards, cluster.set.num_shards

            # ---- calm gap: out of window, must stay silent and green
            await drive(3.0, 4.0, "g1", seed + 17)
            n_gap1 = len(ctl.executed)
            assert n_gap1 == len(acts), (
                f"controller acted between faults: {ctl.executed[len(acts):]}"
            )

            # ---- fault 2: engine hang.  The breaker owns this outage:
            # commits degrade to the host fallback, and the controller's
            # scale-out candidate (the stall's latency burn) must be
            # VETOED while the breaker is open.
            t1 = sched.now()
            cluster.engine.hang()
            base_committed = [sh.committed() for sh in cluster.shard_list]
            for s in range(cluster.set.num_shards):
                await cluster.submit(
                    cluster.client_for_shard(s), f"hg-{seed}-{s}a"
                )
                await cluster.submit(
                    cluster.client_for_shard(s, 1), f"hg-{seed}-{s}b"
                )
            await wait_for(
                lambda: all(
                    sh.committed() >= b + 2
                    for sh, b in zip(cluster.shard_list, base_committed)
                ),
                sched, 240.0,
            )
            assert cluster.coalescer.breaker_open, \
                "engine hang never opened the verify breaker"
            # Pull the fallback commits into the latency tracker so the
            # flush tick SEES the stall's burn: the scale-out candidate
            # it draws is exactly what the breaker veto must suppress.
            cluster.poll()
            veto0 = ctl.policy.counters["veto_breaker"]
            for _ in range(2):
                rem = await ctl.step()
                samples.append((
                    rem.at, rem.__dict__.get("_verdict_status", ""),
                    rem.status,
                ))
            assert ctl.policy.counters["veto_breaker"] > veto0, (
                f"breaker open did not veto: {ctl.policy.snapshot()}"
            )
            cluster.engine.heal()
            await Invariants.breaker_recovered(cluster, timeout=10.0)
            # Let the stall's latency samples age out of the fast SLO
            # window before the reflex arc resumes ticking: the hang was
            # the breaker's fault to fix, not a capacity problem.
            await _advance_clock(cluster, 3.0)
            await drive(3.0, 6.0, "g2", seed + 19)
            windows.append((t1, sched.now()))
            n_hang = len(ctl.executed)
            assert n_hang == n_gap1, (
                f"controller scaled on a device outage: "
                f"{ctl.executed[n_gap1:]}"
            )

            # ---- fault 3: mute shard 0's leader.  Detection rides the
            # heartbeat timer; the view-change breach must draw a RETUNE
            # (derived knobs through the ordered reconfig stream), never
            # a scale action.  Trickle goes to shard 1 only — the muted
            # shard's clients have failed over.  Quiesce first: a tracked
            # request still in shard 0's pool would ride out the whole
            # view change and resurface as a bogus commit-latency burn.
            await _advance_clock(cluster, 2.0)
            t2 = sched.now()
            sh0 = cluster.shard_list[0]
            muted = sh0.mute_leader()
            for k in range(40):
                await _advance_clock(cluster, 1.0)
                await cluster.submit(
                    cluster.client_for_shard(1, k % 2), f"mu-{seed}-{k}"
                )
                rem = await ctl.step()
                samples.append((
                    rem.at, rem.__dict__.get("_verdict_status", ""),
                    rem.status,
                ))
            sh0.unmute(muted)
            retunes = [
                e for e in ctl.executed[n_hang:] if e["action"] == "retune"
            ]
            assert retunes and all(e["ok"] for e in retunes), (
                f"view-change breach drew no retune: {ctl.executed[n_hang:]}"
            )
            assert all(
                e["action"] == "retune" for e in ctl.executed[n_hang:]
            ), f"mute window drew a scale action: {ctl.executed[n_hang:]}"
            assert ctl.current_config.verify_flush_hold > 0.0

            def _retune_committed():
                cluster.poll()
                return any(
                    "ctl-retune" in rid
                    for e in cluster.delivered_entries
                    for rid in e.request_ids
                )

            await wait_for(_retune_committed, sched, 120.0)
            await drive(3.0, 5.0, "g3", seed + 23)
            windows.append((t2, sched.now()))
            n_mute = len(ctl.executed)

            # ---- settle: healthy, idle, and nothing left to do
            await drive(3.0, 4.0, "st", seed + 29)
            assert len(ctl.executed) == n_mute, (
                f"controller acted after all faults healed: "
                f"{ctl.executed[n_mute:]}"
            )

            # ---- the reflex-arc invariants
            stray_unhealthy = [
                (round(t, 1), st) for (t, st, _d) in samples
                if st != "healthy"
                and not any(a <= t <= b + 1.0 for (a, b) in windows)
            ]
            assert not stray_unhealthy, (
                f"SLO verdicts not green outside fault windows "
                f"{[(round(a, 1), round(b, 1)) for (a, b) in windows]}: "
                f"{stray_unhealthy}"
            )
            Invariants.remediation_quiet(
                ctl.policy.decisions, windows, grace=1.0
            )
            Invariants.no_flip_flop(
                ctl.policy.decisions, ctl.policy.hysteresis
            )
            cluster.check_invariants()
            spans = [
                e for e in cluster.trace_events()
                if e.get("kind") == "ctl.remediate"
            ]
            assert len(spans) == len(ctl.executed) >= 3, (
                f"{len(ctl.executed)} actions but {len(spans)} "
                f"ctl.remediate spans"
            )
            clears = [
                e for e in cluster.trace_events()
                if e.get("kind") == "ctl.clear"
            ]
            assert clears, "no ctl.clear span closed a remediation arc"

            pol = ctl.policy.snapshot()
            peak_fill = max(f for (_tf, f) in fills)
            stats = {
                "seed": seed,
                "faults": 3,
                "actions": len(ctl.executed),
                "actions_ok": sum(1 for e in ctl.executed if e["ok"]),
                "scale_out": pol["counters"]["scale_out"],
                "scale_in": pol["counters"]["scale_in"],
                "retune": pol["counters"]["retune"],
                "vetoes": {
                    k: v for k, v in pol["counters"].items()
                    if k.startswith("veto_") and v
                },
                "reversals": pol["reversals"],
                "actions_per_fault": round(len(ctl.executed) / 3.0, 3),
                "ctl_spans": len(spans),
                "clear_spans": len(clears),
                "verdict_samples": len(samples),
                "final_status": samples[-1][1],
                "peak_fill": round(peak_fill, 3),
                "fill_at_scale_out": round(fill_at_out, 3),
                "windows": [
                    (round(a, 1), round(b, 1)) for (a, b) in windows
                ],
            }
        finally:
            await cluster.stop()
    if verbose:
        print(
            f"selfdrive seed {seed}: actions={stats['actions']} "
            f"(out={stats['scale_out']} in={stats['scale_in']} "
            f"retune={stats['retune']}) "
            f"fill@out={stats['fill_at_scale_out']} "
            f"vetoes={stats['vetoes']} reversals={stats['reversals']} "
            f"final={stats['final_status']} — OK"
        )
    return stats


async def selfdrive_soak(
    *, rounds: int = 2, seed: int = 1, depth: int = 2,
    verbose: bool = True,
) -> None:
    """The ``--selfdrive`` remediation-storm soak: rotating faults on the
    logical clock, the controller as the ONLY remediator (the harness
    injects faults but never heals topology or knobs itself)."""
    for r in range(rounds):
        stats = await remediation_storm_round(
            seed=seed + r, depth=depth, verbose=verbose
        )
        assert stats["actions_per_fault"] <= 2.0, stats
        assert stats["reversals"] == 0, stats


# ---------------------------------------------------------------------- byzantine

#: the ``--byzantine`` matrix: one round per attack mode (ISSUE 18)
BYZANTINE_MODES = ("equivocate", "forge", "censor", "stale", "sync_poison")


async def byzantine_round(
    mode: str, *, seed: int = 1, depth: int = 1, requests: int = 18,
    spike_rate: float = 30.0, verbose: bool = True,
) -> dict:
    """One Byzantine-actor round: an n=4 forgery-rejecting cluster
    (``ChaosCluster(byzantine=True)``: real toy-scheme CryptoProvider per
    replica over ONE shared verify plane) with f=1 actor misbehaving on
    the wire, judged by the mode's oracle plus every standard invariant.
    The cluster must stay safe AND live: every pumped request commits on
    every replica, fork-free and exactly-once, and the health verdict
    must not end critical.  Returns the round's observations."""
    import tempfile

    if mode == "sync_poison":
        # state-transfer plane: scripted-donor scenario over a real
        # net.launch rejoiner (testing.byzantine.sync_poison_round)
        from .byzantine import sync_poison_round

        with tempfile.TemporaryDirectory(prefix="chaos-byz-sync-") as root:
            obs = await sync_poison_round(root)
        liar = obs["liar"]
        assert obs["sync_poisoned"].get(liar, 0) >= obs["shun_threshold"], obs
        assert all(obs["sync_poisoned"].get(p, 0) == 0
                   for p in obs["honest_asks"]), obs
        assert obs["liar_asks_total"] == obs["liar_asks_pass1"], (
            f"the liar was asked again after crossing the donor-shun "
            f"threshold: {obs}"
        )
        assert obs["height"] == obs["target_height"], obs
        if verbose:
            print(
                f"byzantine round sync_poison: height={obs['height']}/"
                f"{obs['target_height']} poisoned={obs['sync_poisoned']} "
                f"liar_asks={obs['liar_asks_total']} — OK"
            )
        return obs

    with tempfile.TemporaryDirectory(prefix=f"chaos-byz-{mode}-") as wal_root:
        # censorship needs a STATIC leader: under rotation every replica's
        # pooled requests commit in its own leadership window, so the
        # forward timer never fires and there is nothing to suppress.  The
        # complain machinery deposing the censor IS the scenario.
        cluster = ChaosCluster(
            wal_root, n=4, depth=depth, rotation=(mode != "censor"),
            seed=seed, byzantine=True, trace=True,
        )
        # equivocation and censorship are LEADER attacks: the actor is the
        # initial leader so its window opens immediately.  Forgery and
        # stale replay work from any seat: the actor starts as a follower.
        actor_node = 1 if mode in ("equivocate", "censor") else 4
        await cluster.start()
        try:
            actor = cluster.install_actor(actor_node)
            schedule: list[ChaosEvent] = []
            if mode == "equivocate":
                actor.equivocate()
            elif mode == "forge":
                actor.forge_votes(per_preprepare=3)
            elif mode == "censor":
                # censorship must be judged UNDER OPEN-LOOP LOAD: the
                # complain/forward machinery has to detect suppression
                # while the admission gate is also working
                actor.censor({"chaos"})
                schedule = [
                    ChaosEvent(at=1.0, action="load_spike",
                               fraction=spike_rate),
                    ChaosEvent(at=6.0, action="load_stop"),
                ]
            elif mode == "stale":
                # record view-0 votes, depose the leader so the cluster
                # moves to view 1, then replay the recorded stale votes
                actor.stale_replay()
                schedule = [
                    ChaosEvent(at=2.0, action="mute", node="leader"),
                    ChaosEvent(at=10.0, action="unmute", node="faulty"),
                    ChaosEvent(at=14.0, action="byz_replay"),
                    ChaosEvent(at=16.0, action="byz_replay"),
                ]
            else:
                raise ValueError(f"unknown byzantine mode {mode!r}")
            if mode == "stale":
                # two phases: pump and drain FIRST (the actor records the
                # view-0 votes it will replay), THEN the mute -> view
                # change -> replay timeline with nothing in flight.  A
                # request still pooled at the leader when it goes mute is
                # unrecoverable: its forward and complain retries all fire
                # into the mute and the pool's auto-remove stage then
                # drops it, so the round must not race the pump against
                # the mute.
                await cluster.run_schedule(
                    [], requests=requests, settle_timeout=600.0
                )
                report = await cluster.run_schedule(
                    schedule, requests=0, settle_timeout=600.0
                )
                # the last replay fires on the final event tick; give the
                # inboxes a moment to dispatch it before the oracle counts
                for _ in range(40):
                    await asyncio.sleep(0)
                    cluster.scheduler.advance_by(0.05)
                    await asyncio.sleep(0.001)
            else:
                report = await cluster.run_schedule(
                    schedule, requests=requests, settle_timeout=600.0
                )

            def checks() -> None:
                Invariants.fork_free(cluster)
                Invariants.exactly_once(cluster, expected=requests)
                if mode == "equivocate":
                    Invariants.no_equivocation_commit(cluster, actor)
                elif mode == "forge":
                    Invariants.forger_shunned_and_shed(cluster, actor)
                elif mode == "stale":
                    Invariants.stale_replay_observed(cluster, actor)
                elif mode == "censor":
                    assert actor.censored > 0, (
                        "censor round: no forwarded request was ever "
                        "suppressed — the attack never engaged"
                    )
                    assert len(report.leaders_seen) > 1, (
                        f"censoring leader was never deposed: "
                        f"leaders={report.leaders_seen}"
                    )

            check_with_flight_dump(cluster, checks,
                                   out_dir=wal_root + "-flight")
            # the actor misbehaves from t=0 with no healing event, so the
            # fault window spans the whole run: any critical verdict
            # inside it is explained, ENDING critical is not
            span = report.fault_span or (0.0, report.heal_at)
            assert_health_verdicts(report.verdicts, span,
                                   report.final_health)
        finally:
            await cluster.stop()
        if verbose:
            print(
                f"byzantine round {mode}: actor=n{actor_node} "
                f"decisions={report.final_decisions} "
                f"committed={report.final_committed} "
                f"leaders={sorted(report.leaders_seen)} "
                f"actor_snapshot={actor.snapshot()} — OK"
            )
        return {"mode": mode, "actor": actor.snapshot(),
                "decisions": report.final_decisions,
                "leaders": sorted(report.leaders_seen)}


async def byzantine_soak(
    *, rounds: int = 1, depth: int = 1, seed: int = 1, requests: int = 18,
    verbose: bool = True,
) -> None:
    """The ``--byzantine`` chaos matrix: every attack mode
    (equivocation, vote forgery, leader censorship, stale-view replay,
    sync poisoning), ``rounds`` times each with fresh seeds.  n=3f+1
    clusters with f=1 actor misbehaving must stay safe and live in every
    round."""
    for r in range(rounds):
        for mode in BYZANTINE_MODES:
            await byzantine_round(
                mode, seed=seed + r * len(BYZANTINE_MODES), depth=depth,
                requests=requests, verbose=verbose,
            )


async def byzantine_latency_probe(
    *, forge: bool = False, seed: int = 1, requests: int = 8,
    rate: float = 30.0, spike_s: float = 6.0,
) -> dict:
    """One honest-path latency measurement for the ``--byzantine`` bench
    row: open-loop spike arrivals against the n=4 forgery-rejecting
    cluster, with (``forge=True``) or without a Byzantine actor flooding
    forged votes at the shared verify plane.  The paired snapshots bound
    how much latency an active forger can inflict on honest clients —
    the accounting/shedding machinery is the thing under test.  Returns
    the latency block plus spike accounting."""
    import tempfile

    with tempfile.TemporaryDirectory(prefix="byz-probe-") as root:
        cluster = ChaosCluster(root, n=4, depth=1, rotation=True,
                               seed=seed, byzantine=True)
        await cluster.start()
        try:
            if forge:
                cluster.install_actor(4).forge_votes(per_preprepare=3)
            schedule = [
                ChaosEvent(at=0.5, action="load_spike", fraction=rate),
                ChaosEvent(at=0.5 + spike_s, action="load_stop"),
            ]
            report = await cluster.run_schedule(
                schedule, requests=requests, settle_timeout=600.0
            )
            Invariants.fork_free(cluster)
            snap = cluster.latency.snapshot()
            shuns = sheds = 0
            for a in cluster.live_apps():
                if a.consensus is None:
                    continue
                mis = a.consensus.misbehavior_snapshot()
                shuns += mis.get("shun_events", 0)
                sheds += sum(mis.get("shed_votes", {}).values())
            return {
                "latency": snap,
                "spike_offered": report.spike_offered,
                "spike_acked": report.spike_acked,
                "decisions": report.final_decisions,
                "forged": cluster.actor.forged if forge else 0,
                "shun_events": shuns,
                "shed_votes": sheds,
            }
        finally:
            await cluster.stop()


# ---------------------------------------------------------------------- reshard

@dataclass
class ReshardReport:
    """What a reshard schedule run observed (the oracle inputs)."""

    submitted_ok: list = field(default_factory=list)   # "client:rid" acked
    submit_failures: list = field(default_factory=list)
    reshards: list = field(default_factory=list)       # transition summaries
    events_fired: list = field(default_factory=list)
    shard_counts_seen: list = field(default_factory=list)


def reshard_schedule(
    *, out_at=2.0, out_to=4, in_at=10.0, in_to=3,
    crash_shard: Optional[int] = 0, crash_node: int = 2,
    restart_at: Optional[float] = 16.0,
) -> list[ChaosEvent]:
    """The acceptance timeline: S -> ``out_to`` mid-burst with one replica
    crashed inside the handoff window, then -> ``in_to``, then the crashed
    replica rejoins.  The events are held (not dropped) when their
    precondition is not yet true — ``reshard`` waits for the previous
    transition to finish, ``crash_during_reshard`` waits for one to be in
    flight."""
    events = [ChaosEvent(at=out_at, action="reshard", count=out_to)]
    if crash_shard is not None:
        events.append(ChaosEvent(
            at=out_at + 0.1, action="crash_during_reshard",
            shard=crash_shard, node=crash_node,
        ))
    events.append(ChaosEvent(at=in_at, action="reshard", count=in_to))
    if crash_shard is not None and restart_at is not None:
        events.append(ChaosEvent(
            at=restart_at, action="restart", shard=crash_shard,
            node=crash_node,
        ))
    return events


async def run_reshard_schedule(
    cluster,
    schedule: list[ChaosEvent],
    *,
    requests: int = 24,
    submit_every: float = 0.2,
    settle_timeout: float = 400.0,
    step: float = 0.05,
) -> ReshardReport:
    """Drive a ``ShardedCluster`` (built with ``collect_entries=True``)
    through a reshard timeline under continuous front-door load.

    The pump submits through the routed front door as BACKGROUND tasks: a
    moved client's submit legitimately parks at the epoch barrier until
    the flip, and the logical clock must keep advancing underneath it.
    Reshard transitions also run as background tasks (they poll commits
    that only happen while the clock here advances).  After the last
    event and submission, the run continues until every acked request is
    visible in the combined committed stream.

    Returns the report; exactly-once/gapless are enforced LIVE by the
    delivery mux (any violation raises out of the transition or the
    drain), and the caller typically finishes with
    ``assert_exactly_once_across_epochs``."""
    from ..shard.epoch import RESHARD_CLIENT
    from ..utils.tasks import create_logged_task

    assert cluster.set.mux._on_deliver is not None, (
        "run_reshard_schedule needs ShardedCluster(collect_entries=True)"
    )
    report = ReshardReport()
    pending = sorted(schedule, key=lambda e: e.at)
    held: list[ChaosEvent] = []
    submit_tasks: list = []
    reshard_tasks: list = []
    now = 0.0
    submitted = 0
    next_submit = 0.0

    def _spawn_reshard(target: int) -> None:
        async def _go():
            try:
                report.reshards.append(await cluster.reshard(target))
            except Exception as e:  # noqa: BLE001 — recorded, checked below
                report.reshards.append({"failed": repr(e), "target": target})

        reshard_tasks.append(
            create_logged_task(_go(), name=f"chaos-reshard-{target}")
        )

    def _spawn_submit(cid: str, rid: str) -> None:
        async def _go():
            try:
                await cluster.submit(cid, rid)
                report.submitted_ok.append(f"{cid}:{rid}")
            except Exception as e:  # noqa: BLE001 — a parked submit may
                # time out at the drain deadline; the oracle only counts
                # ACKED submissions
                report.submit_failures.append((f"{cid}:{rid}", repr(e)))

        submit_tasks.append(
            create_logged_task(_go(), name=f"chaos-submit-{rid}")
        )

    async def _fire(evt: ChaosEvent) -> bool:
        """True = consumed; False = precondition not met, hold."""
        if evt.action == "reshard":
            if cluster.set.reshard_in_progress:
                return False
            _spawn_reshard(int(evt.count))
        elif evt.action == "crash_during_reshard":
            if not cluster.set.reshard_in_progress:
                # if every reshard already finished, the window is gone —
                # degrade to a plain crash rather than hanging the run
                if pending or not all(t.done() for t in reshard_tasks):
                    return False
            await cluster.shard(evt.shard).crash(evt.node)
        elif evt.action == "crash":
            await cluster.shard(evt.shard).crash(evt.node)
        elif evt.action == "restart":
            sh = next((s for s in cluster.shard_list
                       if s.shard_id == evt.shard), None)
            if sh is not None:
                await sh.restart(evt.node)
        else:
            raise ValueError(f"unknown reshard-schedule action {evt.action}")
        report.events_fired.append(evt)
        return True

    deadline = None
    while True:
        # 1. fire due events (holding the ones whose precondition waits)
        due = [e for e in pending if e.at <= now] + held
        pending = [e for e in pending if e.at > now]
        held = []
        for evt in due:
            if not await _fire(evt):
                held.append(evt)
        # 2. pump load over the ACTIVE epoch's shards
        if submitted < requests and now >= next_submit:
            s_active = cluster.set.router.shards_at(cluster.set.epoch)
            sid = submitted % s_active
            cid = cluster.client_for_shard(sid, submitted % 3)
            _spawn_submit(cid, f"rs-{submitted}")
            submitted += 1
            next_submit = now + submit_every
        if (not report.shard_counts_seen
                or report.shard_counts_seen[-1] != cluster.set.num_shards):
            report.shard_counts_seen.append(cluster.set.num_shards)
        # 3. exit condition: everything fired, every transition + submit
        # task done, and every ACKED request visible in the stream
        idle = (not pending and not held and submitted >= requests
                and all(t.done() for t in submit_tasks)
                and all(t.done() for t in reshard_tasks))
        if idle and deadline is None:
            deadline = now + settle_timeout
        if idle:
            cluster.poll()
            delivered = {
                rid
                for e in cluster.delivered_entries
                for rid in e.request_ids
                if not rid.startswith(RESHARD_CLIENT + ":")
            }
            if set(report.submitted_ok) <= delivered:
                break
        if deadline is not None and now > deadline:
            raise TimeoutError(
                f"reshard run did not drain within {settle_timeout}s: "
                f"acked={len(report.submitted_ok)} "
                f"delivered={len(cluster.delivered_entries)}"
            )
        if now > 3600.0:
            raise TimeoutError("reshard run exceeded the hard 1h logical cap")
        # 4. advance logical time in lockstep with the loop
        await asyncio.sleep(0)
        cluster.scheduler.advance_by(step)
        await asyncio.sleep(0.001)
        now += step
    return report


def assert_exactly_once_across_epochs(cluster, report: ReshardReport) -> None:
    """The reshard oracle: every ACKED request appears EXACTLY once in the
    combined committed stream across all epochs (nothing lost, nothing
    doubled through any handoff), every live shard is fork-free, and at
    least the scheduled transitions completed."""
    from collections import Counter

    from ..shard.epoch import RESHARD_CLIENT

    counts = Counter(
        rid
        for e in cluster.delivered_entries
        for rid in e.request_ids
        if not rid.startswith(RESHARD_CLIENT + ":")
    )
    missing = [r for r in report.submitted_ok if counts[r] == 0]
    dupes = {r: c for r, c in counts.items() if c > 1}
    assert not missing, f"acked requests never committed: {missing}"
    assert not dupes, f"requests delivered more than once: {dupes}"
    failed = [r for r in report.reshards if "failed" in r]
    assert not failed, f"reshard transitions failed: {failed}"
    for shard in cluster.shard_list:
        shard.assert_fork_free()


async def reshard_soak(
    *, rounds: int = 2, n: int = 4, depth: int = 2, seed: int = 1,
    requests: int = 18, crash: bool = True, verbose: bool = True,
) -> None:
    """Elastic-shard soak: every round rides S=2 -> 4 -> 3 mid-burst —
    with one replica crashed inside the handoff window when ``crash`` —
    and must lose NOTHING: every acked request exactly once across the
    epochs, per-shard gapless (mux-enforced live), fork-free."""
    import tempfile

    rng = random.Random(seed)
    for r in range(rounds):
        with tempfile.TemporaryDirectory(prefix="chaos-reshard-") as root:
            from .sharded import ShardedCluster

            cluster = ShardedCluster(
                root, shards=2, n=n, depth=depth, seed=seed + r,
                collect_entries=True, reshard_drain_deadline=120.0,
            )
            schedule = reshard_schedule(
                crash_shard=rng.randrange(2) if crash else None,
                crash_node=rng.randrange(2, n + 1),
            )
            await cluster.start()
            try:
                report = await run_reshard_schedule(
                    cluster, schedule, requests=requests,
                    settle_timeout=600.0,
                )
                assert_exactly_once_across_epochs(cluster, report)
                assert cluster.set.num_shards == 3, cluster.set.num_shards
                assert cluster.set.epoch >= 2, cluster.set.epoch
            finally:
                await cluster.stop()
            if verbose:
                print(
                    f"reshard round {r}: epochs={cluster.set.epoch} "
                    f"shards_seen={report.shard_counts_seen} "
                    f"acked={len(report.submitted_ok)} "
                    f"parked_failures={len(report.submit_failures)} "
                    f"reshards={[x.get('epoch') for x in report.reshards]} "
                    f"— OK"
                )


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="SmartBFT chaos harness (scripted fault schedules)"
    )
    ap.add_argument("--soak", action="store_true", help="run randomized soak rounds")
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--depth", type=int, default=16, help="pipeline_depth")
    ap.add_argument("--no-rotation", action="store_true")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument(
        "--engine-faults", action="store_true",
        help="add randomized device-plane faults (hang / transient fail / "
             "slow / permanent) against the shared verify engine",
    )
    ap.add_argument(
        "--shards", type=int, default=0,
        help="run the engine-fault soak against S consensus groups sharing "
             "one verify plane (implies --engine-faults; breaker cycle must "
             "affect all shards coherently)",
    )
    ap.add_argument(
        "--reshard", action="store_true",
        help="run the elastic-shard soak: S=2->4->3 live resharding "
             "mid-burst with a replica crash inside the handoff window; "
             "exactly-once across epochs + fork-free + gapless pinned",
    )
    ap.add_argument(
        "--open-loop", action="store_true",
        help="run the overload soak: open-loop Poisson/Zipf arrivals past "
             "the knee of a small-pool admission-controlled sharded "
             "cluster — shedding engages, occupancy stays bounded, "
             "goodput stays positive, p99 recovers",
    )
    ap.add_argument(
        "--rate", type=float, default=600.0,
        help="--open-loop offered load (arrivals per logical second)",
    )
    ap.add_argument(
        "--sockets", action="store_true",
        help="run the fault matrix at the SOCKET level: one OS process per "
             "replica over real UDS transport (smartbft_tpu.net), SIGKILL-"
             "and-rejoin + slow-link rounds, wall-clock offsets",
    )
    ap.add_argument(
        "--transport", default="uds", choices=("uds", "tcp"),
        help="--sockets / --snapshots transport flavor",
    )
    ap.add_argument(
        "--snapshots", action="store_true",
        help="run the truncating soak at the SOCKET level (ISSUE 17): "
             "kill-rejoin must come back via snapshot install (the donors "
             "have compacted past the victim's crash height), "
             "crash_during_snapshot races a capture with SIGKILL, a donor "
             "dies mid-chunk; disk stays bounded, no poisoning, fork-free",
    )
    ap.add_argument(
        "--selfdrive", action="store_true",
        help="run the remediation-storm soak (ISSUE 20): rotating faults "
             "(load spike past the knee, engine hang->heal, muted leader) "
             "against the self-driving control plane; the controller must "
             "scale out on the latency burn before the knee, retune knobs "
             "through ordered reconfig, veto during breaker/transition "
             "windows, and stay SILENT outside fault windows with zero "
             "A->B->A oscillation",
    )
    ap.add_argument(
        "--byzantine", action="store_true",
        help="run the Byzantine actor matrix (ISSUE 18): equivocation, "
             "vote forgery, leader censorship, stale-view replay and sync "
             "poisoning against n=3f+1 forgery-rejecting clusters; the "
             "cluster must stay safe AND live in every round",
    )
    args = ap.parse_args(argv)
    if not args.soak:
        ap.error("nothing to do: pass --soak")
    if args.selfdrive:
        asyncio.run(
            selfdrive_soak(
                rounds=min(args.rounds, 3),
                depth=min(args.depth, 4),
                seed=args.seed,
            )
        )
        print("chaos soak (selfdrive): all rounds passed")
        return 0
    if args.byzantine:
        asyncio.run(
            byzantine_soak(
                rounds=args.rounds,
                depth=min(args.depth, 4),
                seed=args.seed,
                requests=min(args.requests, 24),
            )
        )
        print("chaos soak (byzantine): all rounds passed")
        return 0
    if args.snapshots:
        from ..net.cluster import snapshot_soak

        snapshot_soak(rounds=args.rounds, transport=args.transport)
        print("chaos soak (snapshots): all rounds passed")
        return 0
    if args.sockets:
        from ..net.cluster import socket_soak

        socket_soak(
            rounds=args.rounds,
            transport=args.transport,
            requests=args.requests,
        )
        print("chaos soak (sockets): all rounds passed")
        return 0
    if args.open_loop:
        asyncio.run(
            openloop_soak(
                rounds=args.rounds,
                depth=min(args.depth, 4),
                seed=args.seed,
                rate=args.rate,
            )
        )
        print("chaos soak (open-loop): all rounds passed")
        return 0
    if args.reshard:
        asyncio.run(
            reshard_soak(
                rounds=args.rounds,
                depth=min(args.depth, 4),
                seed=args.seed,
                requests=args.requests,
            )
        )
        print("chaos soak (reshard): all rounds passed")
        return 0
    if args.shards > 0:
        asyncio.run(
            sharded_soak(
                rounds=args.rounds,
                shards=args.shards,
                depth=min(args.depth, 4),
                seed=args.seed,
                requests=args.requests,
            )
        )
        print("chaos soak (sharded): all rounds passed")
        return 0
    asyncio.run(
        soak(
            rounds=args.rounds,
            depth=args.depth,
            rotation=not args.no_rotation,
            seed=args.seed,
            requests=args.requests,
            engine_faults=args.engine_faults,
        )
    )
    print("chaos soak: all rounds passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
