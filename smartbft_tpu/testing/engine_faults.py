"""Device-fault injection for the verify plane.

:class:`FaultyEngine` wraps any verify engine and injects the device fault
classes the reference's per-goroutine host verify could never exhibit
(view.go:537-541 cannot hang or fail as a unit):

* **hang** — ``verify`` blocks until healed; the coalescer's launch
  deadline abandons the wave (the late result is discarded on arrival);
* **fail-next-K** — the next K calls raise a transient tunnel-class error
  (``UNAVAILABLE``), exercising retry/backoff and breaker accounting;
* **slow** — every call pays a fixed sleep (deadline-edge testing);
* **permanent-error** — calls raise a compile-class error (``Mosaic
  lowering``), which trips the host-fallback breaker immediately.

:class:`CoalescedTrivialCrypto` is the chaos harness's crypto provider: it
keeps the test App's trivial signature semantics (signature = node id, aux
travels in ``Signature.msg``) but routes batched verification through a
REAL :class:`~smartbft_tpu.crypto.provider.AsyncBatchCoalescer`, so a
whole chaos cluster shares one engine + coalescer exactly like the
single-chip deployment shape — and engine faults hit every replica at
once, which is the failure mode this PR hardens.
"""

from __future__ import annotations

import threading
import time

from ..crypto.provider import HostVerifyEngine
from ..messages import Proposal, Signature


class _AlwaysValidScheme:
    """Trivial scheme for HostVerifyEngine: every item verifies.  Chaos
    runs exercise the fault MACHINERY (deadline/retry/breaker), not the
    arithmetic — real-crypto engines are covered by the provider tests."""

    @staticmethod
    def verify_item(item) -> bool:
        return True


def always_valid_engine() -> HostVerifyEngine:
    """A real HostVerifyEngine over the trivial scheme — used both as the
    chaos 'device' engine (wrapped in FaultyEngine) and as the breaker's
    host fallback, so degrade/recover paths run the production classes."""
    return HostVerifyEngine(scheme=_AlwaysValidScheme)


class FaultyEngine:
    """Engine wrapper with schedulable fault modes (thread-safe: ``verify``
    runs on coalescer worker threads while the chaos timeline flips modes
    from the event loop)."""

    def __init__(self, inner):
        self.inner = inner
        self.scheme = getattr(inner, "scheme", None)
        self.preferred_coalesce_window = getattr(
            inner, "preferred_coalesce_window", 0.0
        )
        # a wrapped device engine must still LOOK device-shaped: the
        # provider's coalescer sizing and the "arm a host fallback" default
        # both key off the pad ladder
        if hasattr(inner, "pad_sizes"):
            self.pad_sizes = inner.pad_sizes
        # ...and a wrapped MESH engine must still look mesh-shaped: the
        # configure_verify_mesh idempotence check and the bench `mesh`
        # block key off `devices` / `topology` / `mesh_snapshot`
        if hasattr(inner, "devices"):
            self.devices = inner.devices
        if hasattr(inner, "topology"):
            self.topology = inner.topology
        self._lock = threading.Lock()
        self._fail_next = 0
        self._slow_s = 0.0
        self._permanent = False
        #: mesh-scoped device faults: indices of "lost" mesh devices.  One
        #: lost device fails the WHOLE launch — that is the semantics of a
        #: mesh (one logical launch spans every device), and it is exactly
        #: why a single sick chip degrades ALL shards to host together.
        self._down_devices: set[int] = set()
        #: set = not hanging; cleared by hang(), re-set by heal()/fail_next
        self._release = threading.Event()
        self._release.set()
        self.injected_failures = 0
        self.injected_hangs = 0

    # -- delegation --------------------------------------------------------

    @property
    def stats(self):
        return self.inner.stats

    def prewarm_keys(self, pubs) -> None:
        if hasattr(self.inner, "prewarm_keys"):
            self.inner.prewarm_keys(pubs)

    def mesh_snapshot(self) -> dict:
        snap = getattr(self.inner, "mesh_snapshot", None)
        return snap() if snap is not None else {}

    # -- fault modes -------------------------------------------------------

    def hang(self) -> None:
        """Every verify call blocks until the next heal/fail_next — the
        stuck-tunnel shape.  Abandoned (deadlined) calls stay parked on a
        daemon worker thread and return late after release."""
        with self._lock:
            self.injected_hangs += 1
            self._release.clear()

    def fail_next(self, k: int = 1) -> None:
        """The next ``k`` calls raise a transient tunnel-class error.  Also
        releases a hang: a device cannot be both stuck and failing fast —
        this models 'the tunnel un-wedged but the device is still sick'."""
        with self._lock:
            self._fail_next = int(k)
            self._release.set()

    def slow(self, seconds: float) -> None:
        with self._lock:
            self._slow_s = float(seconds)

    def permanent_error(self, on: bool = True) -> None:
        """Calls raise a compile-class (permanent) error; releases a hang
        like fail_next."""
        with self._lock:
            self._permanent = on
            self._release.set()

    def lose_device(self, idx: int = 0) -> None:
        """Mesh-scoped fault: device ``idx`` of the (wrapped) mesh is
        lost.  Every verify call — one logical launch spanning the whole
        mesh — raises a transient tunnel-class error until the device is
        restored, so the coalescer's retry/breaker machinery sees exactly
        what a real ICI/device loss produces: the WHOLE mesh launch
        failing, for every shard at once."""
        with self._lock:
            self._down_devices.add(int(idx))

    def restore_device(self, idx: int = 0) -> None:
        with self._lock:
            self._down_devices.discard(int(idx))

    def heal(self) -> None:
        """Clear every fault mode and release any parked verify calls."""
        with self._lock:
            self._fail_next = 0
            self._slow_s = 0.0
            self._permanent = False
            self._down_devices.clear()
            self._release.set()

    # -- the engine surface ------------------------------------------------

    def verify(self, items) -> list[bool]:
        self._release.wait()
        with self._lock:
            slow = self._slow_s
            permanent = self._permanent
            failing = self._fail_next > 0
            down = sorted(self._down_devices)
            if failing or down:
                self._fail_next -= 1 if failing else 0
                self.injected_failures += 1
        if slow:
            time.sleep(slow)
        if permanent:
            raise RuntimeError(
                "Mosaic lowering failed (injected permanent device fault)"
            )
        if failing:
            raise RuntimeError(
                "UNAVAILABLE: injected transient device fault"
            )
        if down:
            raise RuntimeError(
                f"UNAVAILABLE: injected mesh device fault (device(s) "
                f"{down} lost; the whole mesh launch fails)"
            )
        return self.inner.verify(items)


class CoalescedTrivialCrypto:
    """Trivial-crypto Signer/Verifier crypto subset over a shared
    coalescer (see module docstring).  Matches the test App's trivial
    semantics exactly — signature value is the node id, the auxiliary data
    IS ``Signature.msg`` — so chaos clusters behave identically to the
    crypto-less default except that quorum verification now traverses the
    verify plane under test."""

    def __init__(self, node_id: int, coalescer, tag=None):
        """``tag``: shard-attribution label forwarded with every coalesced
        submission (see AsyncBatchCoalescer.submit) — the sharded chaos
        harness tags each replica's traffic with its shard id."""
        self.node_id = node_id
        self._coalescer = coalescer
        self.verify_tag = tag

    # -- Signer ------------------------------------------------------------

    def sign(self, data: bytes) -> bytes:
        return b"sig-%d" % self.node_id

    def sign_proposal(self, proposal: Proposal, auxiliary_input: bytes) -> Signature:
        return Signature(
            signer=self.node_id, value=b"sig-%d" % self.node_id,
            msg=auxiliary_input,
        )

    # -- Verifier (crypto methods) -----------------------------------------

    def verify_consenter_sig(self, signature: Signature, proposal: Proposal) -> bytes:
        return signature.msg

    def verify_signature(self, signature: Signature) -> None:
        return None

    def auxiliary_data(self, msg: bytes) -> bytes:
        return msg

    def verify_consenter_sigs_batch(self, signatures, proposal: Proposal):
        return [s.msg for s in signatures]

    async def verify_consenter_sigs_batch_async(self, signatures,
                                                proposal: Proposal):
        items = [("sig", s.signer, bytes(s.msg)) for s in signatures]
        mask = await self._coalescer.submit(items, tag=self.verify_tag)
        return [s.msg if ok else None for s, ok in zip(signatures, mask)]

    def configure_fault_policy(self, policy=None, metrics=None,
                               fallback_engine=None) -> None:
        """Forwarded by the test App so the Consensus facade's wiring seam
        reaches the shared coalescer (fills unset pieces only)."""
        self._coalescer.configure(
            policy=policy, fallback_engine=fallback_engine, metrics=metrics
        )

    def configure_flush_hold(self, hold=None, explicit: bool = False) -> None:
        """Forward the ``verify_flush_hold`` knob to the shared coalescer
        (same explicit-wins precedence as the real CryptoProvider), so
        chaos/trivial clusters exercise occupancy gating through the
        Configuration path too."""
        self._coalescer.configure_hold(hold, explicit=explicit)

    def note_view_flip(self) -> None:
        """Forward the Controller's view-flip warmth hint (ISSUE 15) to
        the shared coalescer, like the real CryptoProvider."""
        self._coalescer.note_view_flip()

    def note_view_depose(self) -> None:
        self._coalescer.note_view_depose()
