"""Open-loop load generation: Poisson arrivals over Zipf-skewed clients.

Every bench before round 12 was CLOSED-loop: the pump waits for its own
submits, so the system's slowness throttles the offered load and the
measured "throughput" is really the burst service rate.  A service
serving millions of users sees OPEN-loop arrivals — requests keep coming
at the offered rate whether or not the system keeps up — and is judged on
tail latency and shed rate under that pressure, not on burst tx/s.  This
module is the shared arrival machinery for everything that measures that:

* :class:`OpenLoopPump` — a Poisson arrival schedule (exponential gaps)
  against an EXTERNAL clock, so the same pump paces wall-clock benches
  (``benchmarks/openloop.py``) and logical-clock tier-1 tests (advance
  the scheduler, ask the pump what is due);
* :class:`ZipfClients` — client ids drawn from a Zipf(s) popularity
  distribution, the canonical skewed-workload shape (Mir-BFT treats
  client bucketing under exactly this skew as a first-class hazard): a
  hot client's whole key concentrates on ONE shard, so overload arrives
  per-shard long before the aggregate saturates;
* :func:`run_open_loop` — the driver that pumps a ShardedCluster's
  routed front door for a fixed span, spawning one background submit
  task per arrival (an open-loop client never waits for the previous
  request), counting acks and the two shed shapes, and polling the
  combined committed stream so the set's CommitLatencyTracker resolves
  stamps as commits land.

The chaos harness reuses the pump directly for its ``load_spike`` /
``load_stop`` timeline actions (an overload burst as a schedulable fault
— see ``testing.chaos``).
"""

from __future__ import annotations

import asyncio
import bisect
import random
import types
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..core.pool import AdmissionRejected, SubmitTimeoutError
from ..utils.tasks import create_logged_task

__all__ = ["OpenLoopPump", "OpenLoopStats", "ZipfClients", "run_open_loop"]


class ZipfClients:
    """Client ids under a Zipf(s) popularity law: client rank r carries
    weight 1/r^s.  At the default s=1.1 over 512 clients the hottest
    client alone draws ~14% of all traffic — which lands on exactly one
    shard of the routed front door, the hot-shard pressure the admission
    gate exists for."""

    def __init__(self, n_clients: int = 512, skew: float = 1.1,
                 prefix: str = "zipf"):
        if n_clients < 1:
            raise ValueError(f"need at least one client, got {n_clients}")
        self.n_clients = n_clients
        self.skew = skew
        self.prefix = prefix
        self._cdf: list[float] = []
        acc = 0.0
        for rank in range(1, n_clients + 1):
            acc += 1.0 / (rank ** skew)
            self._cdf.append(acc)
        self._total = acc

    def sample(self, rng: random.Random) -> str:
        """One client id, hot ranks proportionally more often."""
        x = rng.random() * self._total
        idx = bisect.bisect_left(self._cdf, x)
        return f"{self.prefix}{min(idx, self.n_clients - 1)}"

    def hot_fraction(self, top: int = 1) -> float:
        """The traffic share of the ``top`` hottest clients (row metadata
        for bench output — how skewed was this run, exactly)."""
        return self._cdf[min(top, self.n_clients) - 1] / self._total


class OpenLoopPump:
    """Poisson arrival schedule driven by an external clock.

    ``due(now)`` returns how many arrivals have their (pre-drawn,
    exponentially-gapped) arrival times at or before ``now``, advancing
    the schedule — the caller's loop decides what an arrival does.  The
    pump never skips backlog: if the caller's loop stalls, every missed
    arrival is returned on the next call, which is precisely the
    open-loop property (the world does not pause because the server
    did)."""

    def __init__(self, rate: float, rng: random.Random, start: float = 0.0):
        if rate <= 0:
            raise ValueError(f"arrival rate must be positive, got {rate}")
        self.rate = float(rate)
        self._rng = rng
        self._next = start + rng.expovariate(self.rate)

    def set_rate(self, rate: float, now: float) -> None:
        """Change the offered load mid-run (saturation sweeps reuse one
        pump); the next gap is drawn at the new rate from ``now``."""
        if rate <= 0:
            raise ValueError(f"arrival rate must be positive, got {rate}")
        self.rate = float(rate)
        self._next = now + self._rng.expovariate(self.rate)

    def due(self, now: float) -> int:
        n = 0
        while self._next <= now:
            n += 1
            self._next += self._rng.expovariate(self.rate)
        return n


@dataclass
class OpenLoopStats:
    """What one open-loop span observed at the front door."""

    offered: int = 0          # arrivals the pump generated
    acked: int = 0            # submits accepted into a pool
    shed_admission: int = 0   # AdmissionRejected fast-fails
    shed_timeout: int = 0     # SubmitTimeoutError space-wait sheds
    failed: int = 0           # any other submit error (no leader, closed)
    retry_after_hints: list = field(default_factory=list)  # sampled (<=64)
    peak_occupancy: int = 0   # max combined size+waiters seen at the door
    peak_fill: float = 0.0    # max combined fill fraction seen
    elapsed: float = 0.0      # span length on the driving clock

    @property
    def shed(self) -> int:
        return self.shed_admission + self.shed_timeout

    def block(self) -> dict:
        """JSON-able row fragment."""
        return {
            "offered": self.offered,
            "acked": self.acked,
            "shed_admission": self.shed_admission,
            "shed_timeout": self.shed_timeout,
            "failed": self.failed,
            "shed_rate": round(self.shed / self.offered, 4)
            if self.offered else 0.0,
            "peak_occupancy": self.peak_occupancy,
            "peak_fill": round(self.peak_fill, 3),
            "retry_after_p50": round(
                sorted(self.retry_after_hints)[len(self.retry_after_hints) // 2],
                4,
            ) if self.retry_after_hints else None,
        }


async def run_open_loop(
    cluster,
    *,
    rate: float,
    duration: float,
    clients: Optional[ZipfClients] = None,
    seed: int = 0,
    step: float = 0.02,
    wall: bool = False,
    request_prefix: str = "ol",
    drain: float = 0.0,
    on_tick: Optional[Callable[[float], None]] = None,
) -> OpenLoopStats:
    """Pump a ShardedCluster's front door open-loop for ``duration``.

    One background task per arrival (clients do not wait for each other);
    accepted submits are counted as acks, ``AdmissionRejected`` /
    ``SubmitTimeoutError`` as sheds (with the rejection's retry-after
    hint sampled), anything else as a failure.  The loop polls the
    committed stream each tick so the set's latency tracker resolves
    stamps as commits land, and samples the combined occupancy for the
    bounded-growth assertion the tier-1 gate makes.

    ``wall=False`` (tests): the loop advances the cluster's logical
    scheduler by ``step`` per iteration — seconds of offered load cost
    milliseconds of real time.  ``wall=True`` (benches): the loop sleeps
    ``step`` real seconds and reads the scheduler's clock, which a
    WallClockDriver must be advancing.

    ``drain``: extra span after the last arrival during which the loop
    keeps polling (and timing) so in-flight requests commit; sheds during
    the drain are possible (parked submitters timing out) and counted.
    ``on_tick(now)`` is the caller's per-iteration hook (phase switches,
    chaos injection)."""
    rng = random.Random(seed)
    zipf = clients or ZipfClients()
    now_fn = cluster.scheduler.now
    pump = OpenLoopPump(rate, rng, start=now_fn())
    stats = OpenLoopStats()
    # a done-callback counter instead of a retained task list: scanning
    # O(offered) tasks every 5ms tick would run ON the event loop whose
    # tail latency this harness exists to measure
    pending = {"n": 0}
    arrivals = 0

    async def _submit(cid: str, rid: str) -> None:
        try:
            await cluster.submit(cid, rid)
            stats.acked += 1
        except AdmissionRejected as e:
            stats.shed_admission += 1
            if len(stats.retry_after_hints) < 64:
                stats.retry_after_hints.append(e.retry_after)
        except SubmitTimeoutError:
            stats.shed_timeout += 1
        except Exception:  # noqa: BLE001 — shed accounting must not die
            stats.failed += 1

    # Eager-submit fast path (round 18): in the healthy regime a routed
    # submit never suspends (no space wait, leader is local), so driving
    # the coroutine ONE step completes it inline — skipping the Task +
    # call_soon + done-callback machinery asyncio charges per spawned
    # submit, a measurable slice of the single-core loop budget at the
    # knee.  A submit that actually PARKS (yields a future it is waiting
    # on) is promoted to a real background task that re-yields that same
    # future and then drives the rest of the coroutine to completion —
    # open-loop semantics are unchanged, the parked client still never
    # blocks the pump.  (_submit swallows all exceptions, so the only
    # way out of send() on a completed submit is StopIteration.)
    @types.coroutine
    def _repark(step):
        yield step

    async def _drive(coro, step) -> None:
        try:
            while True:
                await _repark(step)
                try:
                    step = coro.send(None)
                except StopIteration:
                    return
        finally:
            coro.close()

    t0 = now_fn()
    end = t0 + duration
    drain_end = end + drain
    while True:
        now = now_fn()
        if now < end:
            for _ in range(pump.due(now)):
                cid = zipf.sample(rng)
                rid = f"{request_prefix}-{arrivals}"
                arrivals += 1
                coro = _submit(cid, rid)
                try:
                    parked_on = coro.send(None)
                except StopIteration:
                    continue  # completed inline (the common case)
                pending["n"] += 1
                task = create_logged_task(
                    _drive(coro, parked_on), name=f"openloop-{rid}"
                )
                task.add_done_callback(
                    lambda _t: pending.__setitem__("n", pending["n"] - 1)
                )
        cluster.poll()
        occ = cluster.set.occupancy()
        pressure = occ["total_size"] + occ["total_waiters"]
        if pressure > stats.peak_occupancy:
            stats.peak_occupancy = pressure
        if occ["fill"] > stats.peak_fill:
            stats.peak_fill = occ["fill"]
        if on_tick is not None:
            on_tick(now)
        if now >= drain_end and pending["n"] == 0:
            break
        if wall:
            await asyncio.sleep(step)
        else:
            await asyncio.sleep(0)
            cluster.scheduler.advance_by(step)
            await asyncio.sleep(0.001)
    stats.offered = arrivals
    stats.elapsed = now_fn() - t0
    cluster.poll()
    return stats
