"""In-process network simulator with fault injection.

Re-design of /root/reference/test/network.go:18-252: a map of node id ->
Node, each with a bounded inbox drained by its own asyncio task.  Faults are
injectable per node and per peer: probabilistic message loss, message
mutation hooks, full disconnects, and drop-on-overflow.
"""

from __future__ import annotations

import asyncio
import random
from typing import Callable, Optional

from ..messages import Message
from ..utils.tasks import create_logged_task

INCOMING_BUFFER = 1000  # network.go:18-20


class Node:
    """One endpoint: wraps a Consensus instance's handle_message/
    handle_request behind an inbox task (network.go:200-241)."""

    def __init__(self, node_id: int, network: "Network", rng: random.Random):
        self.id = node_id
        self.network = network
        self.rng = rng
        self.consensus = None  # set by the harness (an App or Consensus)
        self.running = False
        self.lossy = False
        self.muted = False  # outbound-only silence (chaos leader-mute)
        self.loss_probability = 0.0
        self.peer_loss_probability: dict[int, float] = {}
        self.mutate_send: Optional[Callable[[int, Message], Optional[Message]]] = None
        self.filters: list[Callable[[Message, int], bool]] = []
        self._inbox: asyncio.Queue = asyncio.Queue(maxsize=INCOMING_BUFFER)
        self._task: Optional[asyncio.Task] = None
        self.dropped = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self.running:
            return
        self.running = True
        self._task = create_logged_task(
            self._serve(), name=f"netnode-{self.id}"
        )

    async def stop(self) -> None:
        self.running = False
        if self._task is not None:
            self._inbox.put_nowait(None)
            await self._task
            self._task = None

    async def _serve(self) -> None:
        while True:
            item = await self._inbox.get()
            if item is None or not self.running:
                return
            kind, sender, payload = item
            try:
                if kind == "consensus":
                    # async intake: a backpressure-configured cluster blocks
                    # THIS node's delivery task on a full component inbox
                    # (the reference's full-channel semantics); in drop mode
                    # it behaves exactly like the sync intake
                    intake = getattr(
                        self.consensus, "handle_message_async", None
                    )
                    if intake is not None:
                        await intake(sender, payload)
                    else:  # injected doubles without the async surface
                        self.consensus.handle_message(sender, payload)
                else:
                    await self.consensus.handle_request(sender, payload)
            except Exception as e:  # pragma: no cover — harness robustness
                import traceback

                traceback.print_exc()
                raise

    # -- ingress -----------------------------------------------------------

    def _offer(self, kind: str, sender: int, payload) -> None:
        if not self.running:
            return
        try:
            self._inbox.put_nowait((kind, sender, payload))
        except asyncio.QueueFull:
            self.dropped += 1  # drop on overflow (network.go:135-139)

    # -- fault injection (test_app.go:129-195) -----------------------------

    def disconnect(self) -> None:
        self.lossy = True
        self.loss_probability = 1.0

    def disconnect_from(self, peer: int) -> None:
        self.peer_loss_probability[peer] = 1.0

    def connect_to(self, peer: int) -> None:
        self.peer_loss_probability.pop(peer, None)

    def connect(self) -> None:
        self.lossy = False
        self.loss_probability = 0.0
        self.peer_loss_probability.clear()

    def lose_messages(self, probability: float) -> None:
        self.lossy = probability > 0
        self.loss_probability = probability

    def mute(self) -> None:
        """Outbound-only silence: the node still RECEIVES everything but
        none of its sends leave — the classic mute-leader fault (a process
        that is alive and ingesting but whose egress is wedged).  Distinct
        from disconnect(), which severs both directions."""
        self.muted = True

    def unmute(self) -> None:
        self.muted = False

    def add_filter(self, f: Callable[[Message, int], bool]) -> None:
        """Keep a message iff every filter returns True (network.go:232-234)."""
        self.filters.append(f)

    def clear_filters(self) -> None:
        self.filters.clear()

    def _drops(self, peer: int) -> bool:
        """Sender-side check: per-peer loss (disconnect_from) OR global loss.

        Per-peer loss is consulted on the SENDER only, matching the
        reference (network.go): DisconnectFrom(x) stops my sends to x but
        x's messages still reach me unless x also disconnects.
        """
        # max(): like the reference's independent r < q OR r < w checks, a
        # per-peer probability never shields a peer from the global loss
        p = max(self.peer_loss_probability.get(peer, 0.0),
                self.loss_probability if self.lossy else 0.0)
        return p > 0 and self.rng.random() < p

    def _drops_inbound(self, peer: int) -> bool:
        """Receiver-side check: only the node-wide loss state applies."""
        p = self.loss_probability if self.lossy else 0.0
        return p > 0 and self.rng.random() < p


class Network:
    """The mesh (network.go:34-74)."""

    def __init__(self, seed: int = 0):
        self.nodes: dict[int, Node] = {}
        self.rng = random.Random(seed)
        #: (node, peer) -> loss probability the link had BEFORE partition()
        #: cut it.  heal() restores exactly these links to their prior
        #: state (0.0 entries are removed), leaving independently injected
        #: disconnect_from() cuts and fractional losses intact.
        self._partition_cuts: dict[tuple[int, int], float] = {}

    def add_node(self, node_id: int) -> Node:
        node = Node(node_id, self, self.rng)
        self.nodes[node_id] = node
        return node

    def node_ids(self) -> list[int]:
        return sorted(self.nodes.keys())

    def start(self) -> None:
        for node in self.nodes.values():
            node.start()

    async def stop(self) -> None:
        for node in self.nodes.values():
            await node.stop()

    # -- transport ---------------------------------------------------------

    def send_consensus(self, source: int, target: int, msg: Message) -> None:
        src = self.nodes.get(source)
        dst = self.nodes.get(target)
        if src is None or dst is None:
            return
        # sender-side faults
        if src.muted or src._drops(target):
            return
        if src.mutate_send is not None:
            msg = src.mutate_send(target, msg)
            if msg is None:
                return
        # receiver-side faults
        if dst._drops_inbound(source):
            return
        for f in dst.filters:
            if not f(msg, source):
                return
        dst._offer("consensus", source, msg)

    def send_transaction(self, source: int, target: int, request: bytes) -> None:
        src = self.nodes.get(source)
        dst = self.nodes.get(target)
        if src is None or dst is None:
            return
        if src.muted or src._drops(target) or dst._drops_inbound(source):
            return
        dst._offer("request", source, request)

    # -- partitions (chaos harness) ----------------------------------------

    def partition(self, *groups: list[int]) -> None:
        """Split the mesh into disjoint groups: messages cross group
        boundaries in neither direction until :meth:`heal`.  Nodes not
        named in any group form an implicit final group."""
        named = {n for g in groups for n in g}
        rest = [n for n in self.nodes if n not in named]
        all_groups = [list(g) for g in groups] + ([rest] if rest else [])
        group_of = {n: i for i, g in enumerate(all_groups) for n in g}
        for nid, node in self.nodes.items():
            for peer in self.nodes:
                if peer != nid and group_of.get(peer) != group_of.get(nid):
                    # a link some other fault already cut stays its fault's
                    # responsibility — heal() must not reconnect it; a
                    # fractional pre-existing loss is remembered so heal()
                    # restores it instead of clearing the link
                    prior = node.peer_loss_probability.get(peer, 0.0)
                    if prior < 1.0 and (nid, peer) not in self._partition_cuts:
                        self._partition_cuts[(nid, peer)] = prior
                    node.disconnect_from(peer)

    def heal(self) -> None:
        """Undo :meth:`partition` — exactly the link cuts it installed,
        restoring any pre-partition fractional loss; independently injected
        per-peer cuts (disconnect_from) and node-level faults
        (mute/disconnect/loss) are left as-is."""
        for (nid, peer), prior in self._partition_cuts.items():
            node = self.nodes.get(nid)
            if node is not None:
                if prior > 0.0:
                    node.peer_loss_probability[peer] = prior
                else:
                    node.peer_loss_probability.pop(peer, None)
        self._partition_cuts.clear()
